"""Serving-throughput bench: micro-batched sampling service vs the
one-shot sequential baseline. CPU-runnable — the first hardware-
independent perf number in the BENCH trajectory.

Prints ONE JSON line:

  {"metric": "serve_rps_<preset>", "value": <requests/sec>,
   "vs_baseline": <x>, "baseline_value": <requests/sec>, ...}

`vs_baseline` compares against the status-quo serving path this PR
replaces: per request, a FRESH `make_sampler` jit closure built and
called sequentially at batch 1 — exactly what `nvs3d sample` does per
invocation (every request re-traces; the persistent compilation cache,
which the baseline is given too, spares it the full XLA compile). The
service side answers from its warm sampler-program cache and coalesces
concurrent requests into padded power-of-two buckets.

`warm_sequential_sec_per_req` is reported for transparency: on a 1-core
CPU host batching itself is roughly throughput-neutral (the chip is
saturated at batch 1) and the win is program reuse; on accelerators with
idle MXU headroom the batching term multiplies in.

The run also performs a warm MIXED-SIZE sweep across >= 3 bucket sizes
and asserts zero new sampler compilations (from the program cache's jit
counters) — the "warm traffic never recompiles" contract. A violation
exits rc=1.

Usage:
  python tools/serve_bench.py [--preset tiny64] [--concurrency 8]
      [--requests 16] [--steps 4] [--sidelength 16] [--max-batch 4]
      [--hot-swap | --continuous | --trajectory | --precision-sweep
       | --chaos]

`--sidelength` downsizes the preset's image for bench runtime (the
tiny64 model is resolution-free; 16 px keeps the CPU run under ~2 min).

`--hot-swap` additionally exercises the model-lifecycle path
(docs/DESIGN.md "Model lifecycle"): a second version is published to a
throwaway registry MID-LOAD, the reload watcher swaps it in under live
traffic, and the run ASSERTS zero rejected/failed requests and zero new
sampler-program compilations across the swap (rc=1 on violation). The
JSON gains a "hot_swap" section with p99 latency before/during/after.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._common import init_jax_env  # noqa: E402

init_jax_env()

# Like bench.py, the persistent compile cache is ON by default at the
# repo-local path (env wins): it keeps bench re-runs warm AND gives the
# one-shot baseline the same compile-cache benefit the CLI now has —
# the reported vs_baseline is program-reuse + batching, not cold compiles.
from novel_view_synthesis_3d_tpu.utils.xla_cache import (  # noqa: E402
    setup_compilation_cache)

setup_compilation_cache(
    default_dir=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"),
    min_entry_bytes=0)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def print_recompile_culprit(
        results_folder: str = "/tmp/nvs3d_serve_bench") -> None:
    """Attribution line under a violated zero-recompile assert: the
    service records every kept program build in the compile ledger
    (obs/compiles.py), so the newest recompile entry names WHICH cache
    -key field changed. Printed best-effort — the assert already set
    rc=1; this only makes the page actionable."""
    try:
        from novel_view_synthesis_3d_tpu import obs
        entry = obs.last_recompile(results_folder)
    except Exception:
        return
    if entry is None:
        print(f"  ledger: no recompile entry in "
              f"{results_folder}/compiles.jsonl — the extra build landed "
              "under a fresh ledger name (first build of a new program), "
              "check `nvs3d obs compiles` for the full build list",
              file=sys.stderr)
        return
    print(f"  ledger culprit [{entry.get('name')}]: "
          f"{entry.get('changed')}", file=sys.stderr)


def get_default_timesteps(preset: str) -> int:
    from novel_view_synthesis_3d_tpu.config import get_preset

    return get_preset(preset).diffusion.timesteps


def build(preset: str, sidelength: int, steps: int, extra_overrides=()):
    from novel_view_synthesis_3d_tpu.config import get_preset
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet

    cfg = get_preset(preset).override(**{
        "data.img_sidelength": sidelength,
        "diffusion.sample_timesteps": steps,
    })
    if extra_overrides:
        cfg = cfg.override(**dict(extra_overrides))
    cfg = cfg.validate()
    model = XUNet(cfg.model)
    batch = make_example_batch(batch_size=8, sidelength=sidelength, seed=0)
    mb = {
        "x": jnp.asarray(batch["x"]), "z": jnp.asarray(batch["target"]),
        "logsnr": jnp.zeros((batch["x"].shape[0],)),
        "R1": jnp.asarray(batch["R1"]), "t1": jnp.asarray(batch["t1"]),
        "R2": jnp.asarray(batch["R2"]), "t2": jnp.asarray(batch["t2"]),
        "K": jnp.asarray(batch["K"]),
    }
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        mb, cond_mask=jnp.ones((batch["x"].shape[0],)), train=False)["params"]
    params = jax.device_put(params, jax.devices()[0])
    conds = [{k: np.asarray(mb[k])[i % mb["x"].shape[0]]
              for k in ("x", "R1", "t1", "R2", "t2", "K")}
             for i in range(max(8, mb["x"].shape[0]))]
    return cfg, model, params, conds


def bench_baseline(cfg, model, params, conds, n_requests: int) -> float:
    """Sequential one-shot path: fresh jit closure per request, batch 1.

    One untimed cold run populates the persistent compilation cache
    first, so the baseline pays retrace + cache hit per request — the
    best the old path can do — not the one-time cold compile."""
    from novel_view_synthesis_3d_tpu.diffusion.schedules import (
        sampling_schedule)
    from novel_view_synthesis_3d_tpu.sample.ddpm import make_sampler

    dcfg = cfg.diffusion
    steps = dcfg.sample_timesteps

    def one_shot(i: int):
        sampler = make_sampler(model, sampling_schedule(dcfg, steps), dcfg)
        cond = {k: jnp.asarray(v)[None]
                for k, v in conds[i % len(conds)].items()}
        return np.asarray(jax.device_get(
            sampler(params, jax.random.PRNGKey(i), cond)))

    one_shot(0)  # untimed: populates the persistent compile cache
    t0 = time.perf_counter()
    for i in range(n_requests):
        one_shot(i + 1)
    return n_requests / (time.perf_counter() - t0)


def warm_service(service, conds, buckets) -> None:
    """Compile each bucket's program once (group sizes = bucket sizes)."""
    seed = 10_000
    for b in buckets:
        tickets = [service.submit(conds[j % len(conds)], seed=seed + j)
                   for j in range(b)]
        seed += b
        for t in tickets:
            t.result(timeout=600)


def bench_service(service, conds, n_requests: int,
                  concurrency: int) -> float:
    """Closed-loop load: `concurrency` submitter threads, wall-clock RPS."""
    per_thread = max(1, n_requests // concurrency)
    total = per_thread * concurrency
    errors = []

    def client(tid: int):
        for j in range(per_thread):
            try:
                service.submit(conds[(tid + j) % len(conds)],
                               seed=1000 + tid * per_thread + j
                               ).result(timeout=600)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise SystemExit(f"serve_bench: {len(errors)} request(s) failed; "
                         f"first: {errors[0]!r}")
    return total / elapsed


def mixed_size_sweep(service, conds, buckets) -> dict:
    """Warm sweep across every bucket size; returns the compile-counter
    delta (must be zero — warm traffic never recompiles)."""
    before = service.compile_counters()
    seed = 50_000
    # Group sizes that land in each bucket, including non-power-of-two
    # groups that PAD up (3 -> bucket 4).
    sizes = sorted(set(
        list(buckets) + [b - 1 for b in buckets if b - 1 >= 1]))
    for n in sizes:
        tickets = [service.submit(conds[j % len(conds)], seed=seed + j)
                   for j in range(n)]
        seed += n
        for t in tickets:
            t.result(timeout=600)
    after = service.compile_counters()
    return {
        "swept_group_sizes": sizes,
        "programs_built_delta": after["programs_built"]
        - before["programs_built"],
        "jit_cache_entries_delta": after["jit_cache_entries"]
        - before["jit_cache_entries"],
    }


def mixed_res_bench(args) -> dict:
    """Judged mixed-resolution serving scenario: the resolution ladder's
    serving counterpart (train.ladder trains ONE param tree across
    rungs; the fleet then serves BOTH rung resolutions side by side).

    One fully-convolutional param tree, one SamplingService PER
    resolution (the sampler program is shape-specialised on H/W, so each
    resolution owns its bucket family). Every service's buckets are
    warmed, then one interleaved mixed-resolution trace is replayed
    through the warm services CONCURRENTLY — the assert is that warm
    mixed traffic never compiles a new sampler program in ANY lane
    (compile-counter deltas zero per resolution; rc=1 + compile-ledger
    culprit on violation)."""
    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    sidelengths = sorted({int(s) for s in args.mr_sidelengths.split(",")
                          if s.strip()})
    if len(sidelengths) < 2:
        raise SystemExit("--mr-sidelengths must name >= 2 distinct "
                         f"resolutions (got {args.mr_sidelengths!r})")
    # Attention OFF: attn_resolutions is keyed on absolute feature-map
    # resolution, so attention would land at DIFFERENT UNet levels per
    # rung and the param trees would diverge — the same constraint
    # Config.validate enforces on train.ladder itself.
    overrides = [("model.num_res_blocks", 1),
                 ("model.attn_resolutions", [])]
    # ONE param tree serves every rung: the XUNet is fully convolutional
    # (param shapes are resolution-independent), so the params built at
    # the smallest rung ARE the ladder-trained deployment's params.
    _, model, params, _ = build(args.preset, sidelengths[0],
                                args.mr_steps, extra_overrides=overrides)
    buckets = [1]
    while buckets[-1] * 2 <= args.mr_max_batch:
        buckets.append(buckets[-1] * 2)
    results_folder = "/tmp/nvs3d_serve_bench_mixed_res"
    lanes = {}
    services = {}
    try:
        for sl in sidelengths:
            rcfg, _, _, conds_r = build(args.preset, sl, args.mr_steps,
                                        extra_overrides=overrides)
            scfg = ServeConfig(
                scheduler="step", max_batch=args.mr_max_batch,
                flush_timeout_ms=args.flush_timeout_ms,
                queue_depth=max(64, 2 * args.mr_requests),
                results_folder=results_folder)
            svc = SamplingService(model, params, rcfg.diffusion, scfg)
            services[sl] = svc
            warm_service(svc, conds_r, buckets)
            lanes[sl] = {"conds": conds_r,
                         "warm": svc.compile_counters()}
        # Interleaved mixed replay: a seeded shuffle of the resolution
        # sequence, all tickets in flight together so both lanes form
        # dynamic (padded) groups under concurrent pressure.
        rng = np.random.default_rng(args.mr_seed)
        order = [sidelengths[i % len(sidelengths)]
                 for i in range(args.mr_requests)]
        rng.shuffle(order)
        t0 = time.perf_counter()
        tickets = []
        for i, sl in enumerate(order):
            conds_r = lanes[sl]["conds"]
            tickets.append(services[sl].submit(
                conds_r[i % len(conds_r)], seed=90_000 + i))
        for t in tickets:
            t.result(timeout=600)
        elapsed = time.perf_counter() - t0
        per_res = {}
        for sl in sidelengths:
            after = services[sl].compile_counters()
            warm = lanes[sl]["warm"]
            per_res[str(sl)] = {
                "requests": sum(1 for o in order if o == sl),
                "programs_built_delta": after["programs_built"]
                - warm["programs_built"],
                "jit_cache_entries_delta": after["jit_cache_entries"]
                - warm["jit_cache_entries"],
                "programs_built_total": after["programs_built"],
            }
        return {
            "sidelengths": sidelengths,
            "requests": len(order),
            "sample_steps": args.mr_steps,
            "rps": round(len(order) / max(elapsed, 1e-9), 3),
            "buckets": buckets,
            "results_folder": results_folder,
            "per_resolution": per_res,
        }
    finally:
        for svc in services.values():
            svc.stop()


def check_mixed_res(mr: dict) -> int:
    """rc for --mixed-res: zero warm recompiles in EVERY resolution
    lane, or rc=1 with the compile-ledger culprit."""
    bad = {sl: d for sl, d in mr["per_resolution"].items()
           if d["programs_built_delta"] or d["jit_cache_entries_delta"]}
    if bad:
        print("error: warm mixed-resolution traffic compiled new "
              f"sampler program(s) ({bad}) — each resolution's bucket "
              "family must be fully warmed before mixed traffic, and "
              "warm traffic must never recompile", file=sys.stderr)
        print_recompile_culprit(mr.get("results_folder",
                                       "/tmp/nvs3d_serve_bench"))
        return 1
    return 0


def _p99(latencies) -> float:
    if not latencies:
        return 0.0
    vals = sorted(latencies)
    return vals[min(len(vals) - 1, int(round(0.99 * (len(vals) - 1))))]


def _pctl(latencies, q: float) -> float:
    if not latencies:
        return 0.0
    vals = sorted(latencies)
    return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]


# ---------------------------------------------------------------------------
# --continuous: step-level continuous batching under mixed Poisson traffic
# ---------------------------------------------------------------------------
def parse_class_map(spec: str, what: str) -> dict:
    """'4:0.8,64:0.12,256:0.08' -> {4: 0.8, 64: 0.12, 256: 0.08}."""
    out = {}
    for part in spec.split(","):
        try:
            k, v = part.split(":")
            out[int(k)] = float(v)
        except ValueError:
            raise SystemExit(f"bad {what} entry {part!r} "
                             "(want steps:value[,steps:value...])")
    if not out:
        raise SystemExit(f"empty {what}")
    return out


def poisson_trace(n: int, rate: float, mix: dict, slo_ms: dict,
                  seed: int) -> list:
    """Deterministic Poisson arrival trace with per-request step class."""
    import numpy as _np

    rng = _np.random.default_rng(seed)
    classes = sorted(mix)
    probs = _np.asarray([mix[c] for c in classes], float)
    probs = probs / probs.sum()
    t = 0.0
    trace = []
    for i in range(n):
        t += float(rng.exponential(1.0 / rate))
        c = int(rng.choice(classes, p=probs))
        trace.append({"at": t, "steps": c,
                      "slo_ms": float(slo_ms.get(c, 0.0)),
                      "seed": 100_000 + i})
    return trace


def replay_trace(service, conds, trace, *, teacher_steps=None,
                 use_deadlines=True) -> tuple:
    """Open-loop replay of `trace` against a live service.

    Each request is submitted at its arrival offset (never gated on
    earlier completions — real traffic does not politely wait) and a
    waiter thread records its outcome: ok / late (served past its SLO) /
    expired (deadline reject) / rejected (backpressure) / failed.
    `teacher_steps` overrides every request's step count (the PR 3
    pre-distillation deployment: no students, everything runs the
    teacher ladder). Returns (records, window_s) with window measured
    from first submit to last completion."""
    from novel_view_synthesis_3d_tpu.sample.service import Rejected

    records = []
    threads = []
    t0 = time.perf_counter()

    def waiter(ticket, rec, t_submit, slo_s):
        from novel_view_synthesis_3d_tpu.sample.service import (
            DeadlineExceeded)

        try:
            ticket.result(timeout=600)
        except DeadlineExceeded:
            rec["status"] = "expired"
            return
        except Exception:
            rec["status"] = "failed"
            return
        lat = time.perf_counter() - t_submit
        rec["latency_s"] = lat
        rec["status"] = "ok" if (not slo_s or lat <= slo_s) else "late"

    for i, req in enumerate(trace):
        delay = t0 + req["at"] - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        steps = teacher_steps or req["steps"]
        slo_s = (req["slo_ms"] / 1000.0) if req["slo_ms"] else 0.0
        rec = {"class": req["steps"], "steps": steps, "status": "pending"}
        records.append(rec)
        try:
            ticket = service.submit(
                conds[i % len(conds)], seed=req["seed"],
                sample_steps=steps,
                deadline_ms=req["slo_ms"] if (use_deadlines
                                              and req["slo_ms"]) else None)
        except Rejected:
            rec["status"] = "rejected"
            continue
        th = threading.Thread(
            target=waiter, args=(ticket, rec, time.perf_counter(), slo_s))
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=600)
    return records, time.perf_counter() - t0


def summarize_replay(records, window_s: float) -> dict:
    """Per-step-class latency/outcome table + RPS over the replay
    window. 'rps_goodput' counts only within-SLO completions — the
    serving metric that punishes head-of-line blocking; 'rps_served'
    counts everything that completed."""
    classes = {}
    for rec in records:
        c = classes.setdefault(rec["class"], {"n": 0, "ok": 0, "late": 0,
                                              "expired": 0, "rejected": 0,
                                              "failed": 0, "lat": []})
        c["n"] += 1
        c[rec["status"]] = c.get(rec["status"], 0) + 1
        if "latency_s" in rec:
            c["lat"].append(rec["latency_s"])
    out_classes = {}
    for cls, c in sorted(classes.items()):
        lat = c.pop("lat")
        out_classes[str(cls)] = dict(
            c, p50_s=round(_pctl(lat, 0.5), 4),
            p99_s=round(_pctl(lat, 0.99), 4))
    ok = sum(1 for r in records if r["status"] == "ok")
    served = ok + sum(1 for r in records if r["status"] == "late")
    return {
        "window_s": round(window_s, 3),
        "rps_served": round(served / window_s, 4) if window_s else 0.0,
        "rps_goodput": round(ok / window_s, 4) if window_s else 0.0,
        "classes": out_classes,
    }


def continuous_bench(model, params, cfg, conds, args) -> dict:
    """The judged --continuous scenario (docs/DESIGN.md "Continuous
    batching & distillation").

    One deterministic Poisson trace with mixed step classes (the
    post-distillation workload: mostly few-step requests, a tail of
    teacher-ladder ones) runs through:

      1. the STEPPER (serve.scheduler='step') — the headline. After a
         few-step-only warmup, the mixed trace must compile NOTHING
         (programs are keyed on bucket/shape; steps/t/w are device
         arguments) — asserted, rc=1 on violation.
      2. the PR 3 whole-request dispatcher on the SAME trace
         ('scheduler_ab'): isolates scheduling — head-of-line blocking
         shows up as expired/late few-step requests and
         per-(steps,bucket) program builds (the old cache key) as
         mid-run stalls.
      3. the PR 3 DEPLOYMENT baseline ('pr3_teacher_steps'): whole-
         request dispatch with every request at the teacher's step
         count — before progressive distillation there were no few-step
         students to serve, so this is what the PR 3 service actually
         shipped for this demand. Capacity-bound, measured over a
         truncated prefix of the trace (no deadlines — in its favor).

    The headline vs_baseline is (1) vs (3) on SERVED RPS: few-step
    serving = distillation × step-level scheduling, the two halves of
    this PR. The (1) vs (2) ratio is reported alongside as the
    scheduler-only delta on within-SLO goodput — on a 1-core CPU host
    batching is throughput-neutral, so most of that delta is SLO
    attainment, not raw rate; on accelerators with batch headroom both
    multiply.

    The arrival rate auto-calibrates to the measured per-row step cost
    (default --cont-rate 0: target ~85% of the host's solo row-step
    capacity) so the scenario stays in the same operating regime on any
    machine; an explicit --cont-rate pins it. 85% loads the stepper at
    the knee — an arrival-bound run (the earlier 60% target) measures
    the TRACE's rate, not the scheduler's, and understates the win; the
    solo-calibrated capacity is itself conservative (bigger buckets
    amortize per-dispatch overhead), so the knee is not overload.
    """
    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    mix = parse_class_map(args.cont_mix, "--cont-mix")
    slo = parse_class_map(args.cont_slo_ms, "--cont-slo-ms")
    max_batch = args.cont_max_batch
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2

    def make_service(scheduler: str) -> SamplingService:
        return SamplingService(
            model, params, cfg.diffusion,
            ServeConfig(scheduler=scheduler, max_batch=max_batch,
                        flush_timeout_ms=args.flush_timeout_ms,
                        queue_depth=max(64, 2 * args.cont_requests),
                        results_folder="/tmp/nvs3d_serve_bench"),
            results_folder="/tmp/nvs3d_serve_bench")

    few = min(mix)  # the distilled few-step class warms the buckets
    probs = {c: p / sum(mix.values()) for c, p in mix.items()}
    mean_steps = sum(c * p for c, p in probs.items())

    # --- 1. stepper on the mixed trace -------------------------------
    svc = make_service("step")
    try:
        seed = 90_000
        for b in buckets:  # warm with the FEW-STEP class only
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=few) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)
        warm = svc.compile_counters()
        # Rate calibration: solo warm few-step requests give the host's
        # per-row step cost; the Poisson rate targets ~85% utilization
        # of that capacity (see the docstring: load at the knee — an
        # arrival-bound run measures the trace, not the scheduler).
        t0 = time.perf_counter()
        cal = 3
        for j in range(cal):
            svc.submit(conds[j % len(conds)], seed=70_000 + j,
                       sample_steps=few).result(timeout=600)
        t_row = (time.perf_counter() - t0) / (cal * few)
        rate = args.cont_rate
        if rate <= 0:
            rate = round(0.85 / (mean_steps * t_row), 3)
        trace = poisson_trace(args.cont_requests, rate, mix, slo,
                              args.cont_seed)
        result = {"trace": {
            "requests": args.cont_requests, "rate_per_s": rate,
            "rate_auto_calibrated": args.cont_rate <= 0,
            "row_step_s": round(t_row, 4),
            "mix": {str(k): v for k, v in mix.items()},
            "slo_ms": {str(k): v for k, v in slo.items()},
            "seed": args.cont_seed, "teacher_steps": args.teacher_steps,
            "max_batch": max_batch,
        }}
        records, window = replay_trace(svc, conds, trace)
        after = svc.compile_counters()
        stepper = summarize_replay(records, window)
        stepper["programs_built_delta"] = (
            after["programs_built"] - warm["programs_built"])
        stepper["jit_cache_entries_delta"] = (
            after["jit_cache_entries"] - warm["jit_cache_entries"])
        result["stepper"] = stepper
    finally:
        svc.stop()

    # --- 2. PR 3 dispatcher, same trace (scheduler A/B) ---------------
    svc = make_service("request")
    try:
        seed = 95_000
        for b in buckets:  # identical warmup policy: few-step class only
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=few) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)
        warm = svc.compile_counters()
        records, window = replay_trace(svc, conds, trace)
        after = svc.compile_counters()
        ab = summarize_replay(records, window)
        # The old cache key folds steps in: mixed traffic compiles one
        # program per (steps, bucket) it meets — counted, not hidden.
        ab["programs_built_delta"] = (
            after["programs_built"] - warm["programs_built"])
        result["scheduler_ab"] = ab
    finally:
        svc.stop()

    # --- 3. PR 3 deployment: teacher-ladder serving -------------------
    svc = make_service("request")
    try:
        base_n = min(args.cont_baseline_requests, len(trace))
        # Warm the one program this lane uses (bucket-1 teacher scan).
        svc.submit(conds[0], seed=80_000,
                   sample_steps=args.teacher_steps).result(timeout=600)
        records, window = replay_trace(
            svc, conds, trace[:base_n],
            teacher_steps=args.teacher_steps, use_deadlines=False)
        pr3 = summarize_replay(records, window)
        pr3["teacher_steps"] = args.teacher_steps
        pr3["note"] = ("pre-distillation deployment: every request runs "
                       "the teacher ladder; capacity-bound, measured "
                       f"over the first {base_n} arrivals with no "
                       "deadlines (in its favor)")
        result["pr3_teacher_steps"] = pr3
    finally:
        svc.stop()

    result["vs_whole_request_same_trace"] = round(
        result["stepper"]["rps_goodput"]
        / max(result["scheduler_ab"]["rps_goodput"], 1e-9), 3)
    # Served-vs-served: delivery throughput of the few-step deployment
    # against what PR 3 could deliver for the same demand.
    result["vs_pr3_few_step_serving"] = round(
        result["stepper"]["rps_served"]
        / max(result["pr3_teacher_steps"]["rps_served"], 1e-9), 3)
    few_cls = result["stepper"]["classes"].get(str(few), {})
    result["p99_few_step_s"] = few_cls.get("p99_s", 0.0)
    result["p99_few_step_bounded"] = bool(
        few_cls and slo.get(few)
        and few_cls["p99_s"] <= slo[few] / 1000.0
        and few_cls.get("expired", 0) == 0)
    return result


# ---------------------------------------------------------------------------
# --trajectory: ring-native orbit serving vs the naive per-frame client loop
# ---------------------------------------------------------------------------
def make_orbit_trace(conds, orbits: int, frames: int, seed0: int) -> list:
    """Deterministic orbit trace: per orbit, a conditioning view, a fixed
    pose ring at that camera's radius, and a seed. BOTH lanes replay
    exactly this."""
    from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

    trace = []
    for i in range(orbits):
        cond = conds[i % len(conds)]
        radius = float(np.linalg.norm(cond["t1"]))
        trace.append({
            "cond": cond,
            "poses": orbit_poses(frames, radius=radius or 1.0,
                                 elevation=0.3),
            "seed": seed0 + i,
        })
    return trace


def trajectory_bench(model, params, cfg, conds, args) -> dict:
    """The judged --trajectory scenario (docs/DESIGN.md "Trajectory
    serving & stochastic conditioning").

    One deterministic orbit trace (--traj-orbits orbits × --traj-frames
    frames at --traj-steps denoise steps each, fixed poses/seeds,
    replayed --traj-reps times) runs through two deployments of the
    SAME weights and the SAME serving config:

      1. RING-NATIVE (serve.k_max > 0): each orbit is ONE
         TrajectoryRequest — admitted once, its frame bank device-
         resident, every finished frame committed in-jit and the next
         re-entering the ring between steps.
      2. NAIVE CLIENT LOOP (serve.k_max = 0 — the pre-trajectory
         deployment): each orbit is a client issuing N sequential
         single-frame requests, frame i conditioned on frame i-1
         (client-side autoregression, the only protocol the
         single-frame API can express). Every frame pays queue
         admission INCLUDING the batch-formation flush window, a ring
         rebuild on join and exit, the cond re-upload, and a full host
         round-trip of the frame before the next can start.

    The headline is delivered frames/second, ring vs naive — the
    acceptance bar is >= 2x (rc=1 below it). Delivery is asserted too
    (every orbit streams ALL frames, in order), and a separate MIXED
    phase runs a trajectory with single-shot riders through the warm
    ring lane and asserts zero new compilations (bank fill, pose,
    schedule, guidance are device arguments — mixed traffic shares one
    program per bucket).

    Regime (every knob in the JSON): the INTERACTIVE orbit — one client
    spinning one object, frames at the progressive-distillation
    endpoint (--traj-steps 1; Salimans & Ho 2022 halve to 1–4 steps),
    under a throughput-tuned batch-formation window (--traj-flush-ms,
    the window that coalesces concurrent traffic into full buckets).
    Per-frame ADMISSION is then the dominant serving cost — exactly
    what the device-resident path removes: the ring pays it once per
    orbit, the naive loop once per frame. Under saturated concurrent
    load the ratio compresses toward 1x on a 1-core CPU host (compute
    hides the admission overhead; both lanes coalesce) — the CPU lane
    measures the latency-dominant regime, the TPU lane is where the
    dispatch/transfer half of the overhead multiplies in."""
    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    orbits, frames, steps = (args.traj_orbits, args.traj_frames,
                             args.traj_steps)
    reps = args.traj_reps
    max_batch = args.traj_max_batch
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2
    trace = make_orbit_trace(conds, orbits, frames, seed0=41_000)
    expect = orbits * frames * reps
    result = {"trace": {
        "orbits": orbits, "frames_per_orbit": frames,
        "steps_per_frame": steps, "reps": reps,
        "k_max": args.traj_k_max, "max_batch": max_batch,
        "singleshot_riders": args.traj_riders,
        "flush_timeout_ms": args.traj_flush_ms,
    }}

    def make_service(k_max: int) -> SamplingService:
        return SamplingService(
            model, params, cfg.diffusion,
            ServeConfig(scheduler="step", max_batch=max_batch,
                        k_max=k_max,
                        flush_timeout_ms=args.traj_flush_ms,
                        queue_depth=max(64, 4 * expect),
                        results_folder="/tmp/nvs3d_serve_bench"),
            results_folder="/tmp/nvs3d_serve_bench")

    def warm(svc, trajectories: bool):
        seed = 30_000
        for b in buckets:
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=steps) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)
        if trajectories:
            # Warms the bank program path AND the in-jit commit program
            # (one executable per (k_max, H, W) — bucket-independent).
            svc.submit_trajectory(
                dict(trace[0]["cond"]), poses=trace[0]["poses"][:2],
                seed=29_999, sample_steps=steps).result(timeout=600)

    # --- 1. ring-native -----------------------------------------------
    svc = make_service(args.traj_k_max)
    try:
        warm(svc, trajectories=True)
        before = svc.compile_counters()
        delivered = 0
        delivery_ok = True
        t0 = time.perf_counter()
        for rep in range(reps):
            tickets = [svc.submit_trajectory(
                dict(o["cond"]), poses=o["poses"],
                seed=o["seed"] + 7919 * rep,
                sample_steps=steps) for o in trace]
            for t in tickets:
                imgs = t.result(timeout=600)
                delivered += int(t.frames_completed())
                delivery_ok &= bool(
                    imgs.shape == (frames,) + conds[0]["x"].shape
                    and np.isfinite(imgs).all())
        ring_window = time.perf_counter() - t0
        # --- mixed phase (untimed): trajectory + single-shot riders
        # through the SAME warm service; the compile-counter delta
        # below covers the timed trace AND this phase.
        mixed = svc.submit_trajectory(
            dict(trace[0]["cond"]), poses=trace[0]["poses"],
            seed=88_888, sample_steps=steps)
        riders = [svc.submit(conds[j % len(conds)], seed=60_000 + j,
                             sample_steps=steps)
                  for j in range(args.traj_riders)]
        mixed.result(timeout=600)
        for t in riders:
            t.result(timeout=600)
        after = svc.compile_counters()
        result["ring"] = {
            "frames_delivered": delivered,
            "window_s": round(ring_window, 3),
            "frames_per_sec": round(delivered / ring_window, 4),
            "delivery_ok": delivery_ok,
            "mixed_phase": {
                "trajectory_frames": int(mixed.frames_completed()),
                "singleshot_served": len(riders),
            },
            "programs_built_delta": (after["programs_built"]
                                     - before["programs_built"]),
            "jit_cache_entries_delta": (after["jit_cache_entries"]
                                        - before["jit_cache_entries"]),
            "commit_jit_entries_delta": (
                after.get("commit_jit_entries", 0)
                - before.get("commit_jit_entries", 0)),
            "trajectory_frame": svc.stats.span_summary("trajectory_frame"),
            "ring_step": svc.stats.span_summary("ring_step"),
        }
    finally:
        svc.stop()

    # --- 2. naive per-frame client loop (k_max=0 deployment) ----------
    svc = make_service(0)
    try:
        warm(svc, trajectories=False)
        naive_frames = [0]
        errors = []

        def orbit_client(orbit: dict, rep: int):
            cond = orbit["cond"]
            prev_x, prev_R, prev_t = cond["x"], cond["R1"], cond["t1"]
            for f in range(frames):
                pose = orbit["poses"][f]
                try:
                    img = svc.submit(
                        {"x": prev_x, "R1": prev_R, "t1": prev_t,
                         "R2": pose[:3, :3], "t2": pose[:3, 3],
                         "K": cond["K"]},
                        seed=(orbit["seed"] + 7919 * rep) * 1000 + f,
                        sample_steps=steps).result(timeout=600)
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return
                naive_frames[0] += 1
                # Client-side autoregression: the frame round-trips the
                # host and re-uploads as the next conditioning view.
                prev_x, prev_R, prev_t = img, pose[:3, :3], pose[:3, 3]

        t0 = time.perf_counter()
        for rep in range(reps):
            threads = [threading.Thread(target=orbit_client, args=(o, rep))
                       for o in trace]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
        naive_window = time.perf_counter() - t0
        if errors:
            raise SystemExit(
                f"serve_bench --trajectory: naive lane failed "
                f"({errors[0]!r})")
        result["naive"] = {
            "frames_delivered": naive_frames[0],
            "window_s": round(naive_window, 3),
            "frames_per_sec": round(naive_frames[0] / naive_window, 4),
        }
    finally:
        svc.stop()

    result["fps_ring"] = result["ring"]["frames_per_sec"]
    result["fps_naive"] = result["naive"]["frames_per_sec"]
    result["ring_vs_naive"] = round(
        result["fps_ring"] / max(result["fps_naive"], 1e-9), 3)
    return result


def check_trajectory(traj: dict) -> int:
    """rc=1 on any violated --trajectory contract (stderr)."""
    rc = 0
    ring = traj["ring"]
    tr = traj["trace"]
    expect = tr["orbits"] * tr["frames_per_orbit"] * tr["reps"]
    if ring["mixed_phase"]["trajectory_frames"] != tr["frames_per_orbit"]:
        print("error: mixed phase delivered "
              f"{ring['mixed_phase']['trajectory_frames']}/"
              f"{tr['frames_per_orbit']} trajectory frames",
              file=sys.stderr)
        rc = 1
    if not ring["delivery_ok"] or ring["frames_delivered"] != expect:
        print(f"error: ring lane delivered {ring['frames_delivered']}/"
              f"{expect} frames (delivery_ok={ring['delivery_ok']}) — "
              "every orbit must stream all its frames in order",
              file=sys.stderr)
        rc = 1
    if traj["naive"]["frames_delivered"] != expect:
        print(f"error: naive lane delivered "
              f"{traj['naive']['frames_delivered']}/{expect} frames",
              file=sys.stderr)
        rc = 1
    if (ring["programs_built_delta"] or ring["jit_cache_entries_delta"]
            or ring["commit_jit_entries_delta"]):
        print("error: the mixed trajectory + single-shot trace compiled "
              f"something (built={ring['programs_built_delta']}, jit="
              f"{ring['jit_cache_entries_delta']}, commit="
              f"{ring['commit_jit_entries_delta']}) — bank fill, pose, "
              "schedule and guidance are device arguments; warm mixed "
              "traffic must not recompile", file=sys.stderr)
        print_recompile_culprit()
        rc = 1
    if traj["ring_vs_naive"] < 2.0:
        print(f"error: ring-native orbit generation is only "
              f"{traj['ring_vs_naive']}x the naive per-frame client loop "
              f"({traj['fps_ring']} vs {traj['fps_naive']} frames/s) — "
              "the acceptance bar is 2x on the same trace",
              file=sys.stderr)
        rc = 1
    return rc


# ---------------------------------------------------------------------------
# --cond-cache: per-request conditioning activations vs in-program re-encode
# ---------------------------------------------------------------------------
def make_cond_cache_trace(conds, args, rate: float) -> list:
    """Deterministic mixed Poisson trace for --cond-cache: single-shot
    requests with every --cc-orbit-every-th arrival an orbit (the
    trajectory traffic whose frame bank the cond cache pre-encodes).
    BOTH lanes replay exactly this."""
    import numpy as _np

    from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

    rng = _np.random.default_rng(args.cc_seed)
    t = 0.0
    trace = []
    for i in range(args.cc_requests):
        t += float(rng.exponential(1.0 / rate))
        cond = conds[i % len(conds)]
        entry = {"at": t, "seed": 100_000 + i, "cond": cond}
        if (args.cc_orbit_every
                and i % args.cc_orbit_every == args.cc_orbit_every - 1):
            radius = float(np.linalg.norm(cond["t1"])) or 1.0
            entry["kind"] = "orbit"
            entry["poses"] = orbit_poses(args.cc_frames, radius=radius,
                                         elevation=0.3)
        else:
            entry["kind"] = "single"
        trace.append(entry)
    return trace


def _attention_coverage_probe(cfg, sidelength: int) -> dict:
    """Untimed: one forward of the bench backbone with cross-frame
    attention at the bottleneck and use_serving_attention=True, so the
    artifact records WHICH serving attention shapes ran the fused
    kernel vs the XLA fallback (ops/serving_attention.py's per-shape
    coverage registry). The timed A/B stays attention-free (see
    cond_cache_bench); this probe is the kernel-coverage evidence that
    rides the same artifact."""
    import dataclasses as _dc

    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.ops.serving_attention import (
        attention_coverage, reset_attention_coverage)

    bottleneck = sidelength // (2 ** (len(cfg.model.ch_mult) - 1))
    mcfg = _dc.replace(cfg.model, attn_resolutions=(bottleneck,),
                       use_serving_attention=True)
    model = XUNet(mcfg)
    raw = make_example_batch(batch_size=2, sidelength=sidelength, seed=1)
    mb = {
        "x": jnp.asarray(raw["x"]), "z": jnp.asarray(raw["target"]),
        "logsnr": jnp.zeros((2,)),
        "R1": jnp.asarray(raw["R1"]), "t1": jnp.asarray(raw["t1"]),
        "R2": jnp.asarray(raw["R2"]), "t2": jnp.asarray(raw["t2"]),
        "K": jnp.asarray(raw["K"]),
    }
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        mb, cond_mask=jnp.ones((2,)), train=False)["params"]
    reset_attention_coverage()
    out = model.apply({"params": params}, mb, cond_mask=jnp.ones((2,)),
                      train=False)
    jax.block_until_ready(out)
    return {
        f"B{b}_Lq{lq}_Lk{lk}_H{h}_D{d}_{dt}": mode
        for (b, lq, lk, h, d, dt), mode
        in sorted(attention_coverage().items())
    }


def cond_cache_bench(model, params, cfg, conds, args) -> dict:
    """The judged --cond-cache scenario (docs/DESIGN.md "Conditioning
    cache & fused serving attention").

    ONE deterministic mixed Poisson trace (single-shot requests plus
    orbits, --cc-steps denoise steps each) runs through two services
    that differ ONLY in serve.cond_cache:

      OFF — every ring step re-encodes the conditioning branch
            in-program (cond-frame features + per-level pose/FiLM
            embeddings), for every row, every step;
      ON  — the cond branch is encoded ONCE at admission (and once per
            bank entry at trajectory frame boundaries), stored
            device-resident in the ring slot, and consumed by the step
            program as device arguments.

    The headline is delivered ROW-STEPS/s (singles contribute steps,
    orbits frames x steps) — the acceptance bar is >= 1.3x (rc=1 below
    it). Delivery is asserted on BOTH lanes, and both must serve their
    warm trace with ZERO new compilations (program identity is
    bucket/shape-only; cached activations are device arguments — the
    ledger culprit is printed on violation).

    Regime: the arrival rate auto-calibrates to --cc-util (default
    1.7) x the cache-OFF lane's measured solo row-step capacity —
    deliberately ABOVE saturation for both lanes, because the A/B
    question is CAPACITY: an arrival-bound replay would measure the
    trace's rate for whichever lane has headroom and understate the
    win. The backbone is the light serving variant with attention OFF
    and emb_ch raised (--cc-emb-ch) so the conditioning branch is a
    production-shaped ~25%+ of step time: tiny CPU stand-in models
    undersize the cond branch relative to the real checkpoints, and
    cross-frame attention here would only re-dilute what the fused
    serving-attention kernel (TPU-only; coverage probe below) wins
    back on real hardware."""
    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import (
        Rejected, SamplingService)
    from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

    steps, frames, k_max = args.cc_steps, args.cc_frames, args.cc_k_max
    max_batch = args.cc_max_batch
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2

    def make_service(cache: bool) -> SamplingService:
        return SamplingService(
            model, params, cfg.diffusion,
            ServeConfig(scheduler="step", max_batch=max_batch,
                        k_max=k_max,
                        flush_timeout_ms=args.flush_timeout_ms,
                        queue_depth=max(128, 4 * args.cc_requests),
                        cond_cache=cache,
                        results_folder="/tmp/nvs3d_serve_bench"),
            results_folder="/tmp/nvs3d_serve_bench")

    def warm(svc) -> dict:
        """Identical warm policy both lanes: every ring bucket, then a
        trajectory + single-shot co-ride — which (cache on) also warms
        BOTH encode shapes (B=1 admission, B=k_max bank) and the in-jit
        commit before anything is timed."""
        seed = 30_000
        for b in buckets:
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=steps) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)
        radius = float(np.linalg.norm(conds[0]["t1"])) or 1.0
        wt = svc.submit_trajectory(
            dict(conds[0]), poses=orbit_poses(2, radius=radius,
                                              elevation=0.3),
            seed=29_999, sample_steps=steps, k_max=k_max)
        ws = svc.submit(conds[1], seed=29_998, sample_steps=steps)
        wt.result(timeout=600)
        ws.result(timeout=600)
        return svc.compile_counters()

    def replay(svc, trace) -> tuple:
        """Open-loop replay (arrivals never gated on completions); a
        waiter thread per request records delivery."""
        records = []
        threads = []
        t0 = time.perf_counter()

        def waiter(ticket, rec):
            try:
                out = ticket.result(timeout=600)
                rec["ok"] = bool(np.isfinite(np.asarray(out)).all())
            except Exception as exc:  # delivery assert catches it
                rec["ok"] = False
                rec["error"] = type(exc).__name__

        for req in trace:
            delay = t0 + req["at"] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            rec = {"kind": req["kind"], "ok": False,
                   "rows": (frames * steps if req["kind"] == "orbit"
                            else steps)}
            records.append(rec)
            try:
                if req["kind"] == "orbit":
                    ticket = svc.submit_trajectory(
                        dict(req["cond"]), poses=req["poses"],
                        seed=req["seed"], sample_steps=steps, k_max=k_max)
                else:
                    ticket = svc.submit(req["cond"], seed=req["seed"],
                                        sample_steps=steps)
            except Rejected:
                rec["error"] = "rejected"
                continue
            th = threading.Thread(target=waiter, args=(ticket, rec))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=600)
        return records, time.perf_counter() - t0

    # --- calibration on the cache-OFF lane (it defines capacity) ------
    svc = make_service(False)
    try:
        warm_off = warm(svc)
        t0 = time.perf_counter()
        cal = 2
        for j in range(cal):
            svc.submit(conds[j % len(conds)], seed=70_000 + j,
                       sample_steps=steps).result(timeout=600)
        t_row = (time.perf_counter() - t0) / (cal * steps)
        n_orbits = (args.cc_requests // args.cc_orbit_every
                    if args.cc_orbit_every else 0)
        mean_rows = steps * (args.cc_requests + n_orbits * (frames - 1)
                             ) / args.cc_requests
        rate = args.cc_rate
        if rate <= 0:
            rate = round(args.cc_util / (mean_rows * t_row), 4)
        trace = make_cond_cache_trace(conds, args, rate)
        result = {"trace": {
            "requests": args.cc_requests, "orbits": n_orbits,
            "orbit_every": args.cc_orbit_every,
            "frames_per_orbit": frames, "steps": steps,
            "k_max": k_max, "max_batch": max_batch,
            "rate_per_s": rate,
            "rate_auto_calibrated": args.cc_rate <= 0,
            "util_target": args.cc_util,
            "row_step_s": round(t_row, 4),
            "emb_ch": cfg.model.emb_ch,
            "seed": args.cc_seed,
        }}

        def lane(svc, warm_counters, records, window) -> dict:
            after = svc.compile_counters()
            rows_ok = sum(r["rows"] for r in records if r["ok"])
            rows_all = sum(r["rows"] for r in records)
            return {
                "row_steps_delivered": rows_ok,
                "row_steps_offered": rows_all,
                "window_s": round(window, 3),
                "row_steps_per_sec": round(rows_ok / window, 4),
                "delivery_ok": all(r["ok"] for r in records),
                "errors": sorted({r["error"] for r in records
                                  if "error" in r}),
                "deltas": {k: after.get(k, 0) - warm_counters.get(k, 0)
                           for k in ("programs_built", "jit_cache_entries",
                                     "encode_jit_entries",
                                     "commit_jit_entries")},
                "cond_cache": svc.summary().get("cond_cache"),
                "ring_step": svc.stats.span_summary("ring_step"),
            }

        records, window = replay(svc, trace)
        result["off"] = lane(svc, warm_off, records, window)
    finally:
        svc.stop()

    # --- cache-ON lane, same trace ------------------------------------
    svc = make_service(True)
    try:
        warm_on = warm(svc)
        records, window = replay(svc, trace)
        result["on"] = lane(svc, warm_on, records, window)
    finally:
        svc.stop()

    result["speedup"] = round(
        result["on"]["row_steps_per_sec"]
        / max(result["off"]["row_steps_per_sec"], 1e-9), 3)
    result["attention_coverage"] = _attention_coverage_probe(
        cfg, args.cc_sidelength)
    return result


def check_cond_cache(cc: dict) -> int:
    """rc=1 on any violated --cond-cache contract (stderr)."""
    rc = 0
    for name in ("off", "on"):
        ln = cc[name]
        if not ln["delivery_ok"]:
            print(f"error: cond_cache={name} lane delivered "
                  f"{cc[name]['row_steps_delivered']}/"
                  f"{cc[name]['row_steps_offered']} row-steps "
                  f"(errors={ln['errors']}) — every request on the "
                  "calibrated trace must be served", file=sys.stderr)
            rc = 1
        if any(ln["deltas"].values()):
            print(f"error: cond_cache={name} lane compiled something on "
                  f"the warm trace ({ln['deltas']}) — program identity "
                  "must stay bucket/shape-only with cached cond "
                  "activations as device arguments", file=sys.stderr)
            print_recompile_culprit()
            rc = 1
    on_stats = cc["on"].get("cond_cache") or {}
    if not (on_stats.get("enabled") and on_stats.get("hits", 0) > 0):
        print("error: the cache-on lane reports no conditioning-cache "
              f"activity ({on_stats}) — the A/B measured nothing",
              file=sys.stderr)
        rc = 1
    off_stats = cc["off"].get("cond_cache") or {}
    if off_stats.get("enabled"):
        print("error: the cache-off lane ran with serve.cond_cache "
              "enabled — the baseline is contaminated", file=sys.stderr)
        rc = 1
    if cc["speedup"] < 1.3:
        print(f"error: the conditioning cache is only {cc['speedup']}x "
              f"the re-encode-every-step lane "
              f"({cc['on']['row_steps_per_sec']} vs "
              f"{cc['off']['row_steps_per_sec']} row-steps/s) — the "
              "acceptance bar is 1.3x on the same trace",
              file=sys.stderr)
        rc = 1
    if not cc["attention_coverage"]:
        print("error: the serving-attention coverage probe recorded no "
              "shapes — the fused-attention evidence is missing from "
              "the artifact", file=sys.stderr)
        rc = 1
    return rc


# ---------------------------------------------------------------------------
# --precision-sweep: f32/bf16/int8 × fused-step on/off on ONE trace
# ---------------------------------------------------------------------------
PRECISION_LANES = (
    # (serve.precision, diffusion.fused_step) — lane 0 is the baseline
    # the headline compares against; f32+fused isolates the kernel
    # (fused on/off A/B at identical numerics-precision), bf16+fused is
    # the intended TPU serving deployment, int8+fused the quantized one.
    ("float32", False),
    ("float32", True),
    ("bfloat16", True),
    ("int8", True),
)


def precision_sweep_bench(model, params, cfg, conds, args) -> dict:
    """The judged --precision-sweep scenario.

    ONE deterministic Poisson trace (mixed step classes, rate calibrated
    to ~85% of the f32-unfused lane's measured row-step capacity) is
    replayed open-loop against four services that differ ONLY in
    (serve.precision, diffusion.fused_step). Open-loop replay measures
    the serving system under fixed demand — the deployment question —
    so the assertions are delivery-shaped: the bf16+fused lane must
    serve at least the f32-unfused lane's RPS (2% replay-jitter
    tolerance, both numbers in the JSON) with zero expiries and zero
    recompiles after its warmup, and its fixed-seed PSNR probe
    (registry/gate.py, staged AT the lane's precision) must sit within
    registry.gate_margin_db of the f32 probe — the same margin the
    promotion gate enforces. int8 numbers ride along unasserted (its
    gate runs at promotion time, against real weights).

    Note for CPU-lane readers: off-TPU the kernel runs in Pallas
    interpret mode and bf16 weights cost an upcast per use, so the
    per-step timings in each lane's spans UNDERSTATE the TPU win —
    the lane exists to prove the precision plumbing end-to-end and to
    keep the trajectory's numbers labeled, not to project TPU speedups.
    """
    import dataclasses

    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    mix = parse_class_map(args.sweep_mix, "--sweep-mix")
    slo = parse_class_map(args.sweep_slo_ms, "--sweep-slo-ms")
    max_batch = args.cont_max_batch
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2
    few = min(mix)
    probs = {c: p / sum(mix.values()) for c, p in mix.items()}
    mean_steps = sum(c * p for c, p in probs.items())

    def make_service(precision: str, fused) -> SamplingService:
        dcfg = dataclasses.replace(cfg.diffusion, fused_step=fused)
        return SamplingService(
            model, params, dcfg,
            ServeConfig(scheduler="step", max_batch=max_batch,
                        flush_timeout_ms=args.flush_timeout_ms,
                        queue_depth=max(64, 2 * args.sweep_requests),
                        precision=precision,
                        results_folder="/tmp/nvs3d_serve_bench"),
            results_folder="/tmp/nvs3d_serve_bench")

    def warm(svc):
        seed = 90_000
        for b in buckets:
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=few) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)

    trace = None
    lanes = []
    for precision, fused in PRECISION_LANES:
        svc = make_service(precision, fused)
        try:
            warm(svc)
            if trace is None:
                # Rate calibration on the BASELINE lane only: every lane
                # then faces the identical demand.
                t0 = time.perf_counter()
                cal = 3
                for j in range(cal):
                    svc.submit(conds[j % len(conds)], seed=70_000 + j,
                               sample_steps=few).result(timeout=600)
                t_row = (time.perf_counter() - t0) / (cal * few)
                rate = args.cont_rate or round(
                    0.85 / (mean_steps * t_row), 3)
                trace = poisson_trace(args.sweep_requests, rate, mix,
                                      slo, args.cont_seed)
            before = svc.compile_counters()
            records, window = replay_trace(svc, conds, trace)
            after = svc.compile_counters()
            lane = summarize_replay(records, window)
            lane.update(
                precision=precision, fused_step=bool(fused),
                programs_built_delta=(after["programs_built"]
                                      - before["programs_built"]),
                jit_cache_entries_delta=(after["jit_cache_entries"]
                                         - before["jit_cache_entries"]),
                ring_step=svc.stats.span_summary("ring_step"),
                expired=sum(1 for r in records
                            if r["status"] == "expired"),
                failed=sum(1 for r in records
                           if r["status"] in ("failed", "rejected")))
            lanes.append(lane)
        finally:
            svc.stop()

    # Fixed-seed PSNR probe per precision (registry/gate.py): the same
    # staging the gate and the serving path use, so the reported deltas
    # ARE what the promotion gate would charge each deployment.
    from novel_view_synthesis_3d_tpu.data.synthetic import (
        make_example_batch)
    from novel_view_synthesis_3d_tpu.registry.gate import make_psnr_probe

    probe_batch = make_example_batch(batch_size=4,
                                     sidelength=args.sidelength, seed=3)
    host_params = jax.tree.map(np.asarray, jax.device_get(params))
    psnr_by_precision = {}
    for precision in ("float32", "bfloat16", "int8"):
        probe = make_psnr_probe(
            model, cfg.diffusion, probe_batch,
            sample_steps=cfg.registry.gate_sample_steps,
            seed=cfg.registry.gate_seed, precision=precision)
        psnr_by_precision[precision] = round(probe(host_params), 4)
    for lane in lanes:
        lane["probe_psnr_db"] = psnr_by_precision[lane["precision"]]
        lane["probe_delta_db"] = round(
            psnr_by_precision[lane["precision"]]
            - psnr_by_precision["float32"], 4)

    base = next(l for l in lanes if l["precision"] == "float32"
                and not l["fused_step"])
    headline = next(l for l in lanes if l["precision"] == "bfloat16"
                    and l["fused_step"])
    return {
        "trace": {
            "requests": args.sweep_requests, "rate_per_s": rate,
            "row_step_s": round(t_row, 4),
            "mix": {str(k): v for k, v in mix.items()},
            "slo_ms": {str(k): v for k, v in slo.items()},
            "seed": args.cont_seed, "max_batch": max_batch,
        },
        "lanes": lanes,
        "psnr_by_precision": psnr_by_precision,
        "gate_margin_db": cfg.registry.gate_margin_db,
        "baseline_lane": "float32 unfused",
        "headline_lane": "bfloat16 fused",
        "rps_f32_unfused": base["rps_served"],
        "rps_bf16_fused": headline["rps_served"],
        "bf16_vs_f32_rps": round(
            headline["rps_served"] / max(base["rps_served"], 1e-9), 3),
        "bf16_psnr_delta_db": headline["probe_delta_db"],
    }


def check_precision_sweep(sweep: dict) -> int:
    """rc=1 on any violated sweep contract (printed to stderr)."""
    rc = 0
    headline = next(l for l in sweep["lanes"]
                    if l["precision"] == "bfloat16" and l["fused_step"])
    if sweep["bf16_vs_f32_rps"] < 0.98:
        print("error: bf16+fused served "
              f"{sweep['rps_bf16_fused']} req/s < f32-unfused "
              f"{sweep['rps_f32_unfused']} req/s (beyond the 2% "
              "replay-jitter tolerance) — the precision-lowered fused "
              "path must not regress delivery", file=sys.stderr)
        rc = 1
    if headline["expired"] or headline["failed"]:
        print(f"error: bf16+fused lane expired {headline['expired']} / "
              f"failed {headline['failed']} requests under the "
              "calibrated trace", file=sys.stderr)
        rc = 1
    if abs(sweep["bf16_psnr_delta_db"]) > sweep["gate_margin_db"]:
        print("error: bf16 probe PSNR delta "
              f"{sweep['bf16_psnr_delta_db']} dB exceeds "
              f"registry.gate_margin_db={sweep['gate_margin_db']} — the "
              "promotion gate would refuse this deployment",
              file=sys.stderr)
        rc = 1
    for lane in sweep["lanes"]:
        if lane["programs_built_delta"] or lane["jit_cache_entries_delta"]:
            print(f"error: lane {lane['precision']}/fused="
                  f"{lane['fused_step']} compiled "
                  f"{lane['programs_built_delta']} program(s) during the "
                  "warm trace — precision rides the cache key; warm "
                  "traffic must not recompile", file=sys.stderr)
            print_recompile_culprit()
            rc = 1
    return rc


def hot_swap_bench(service, conds, params, concurrency: int,
                   per_phase: int) -> dict:
    """Publish a new version mid-load and measure the swap's cost.

    Three phases of `per_phase` requests each at `concurrency` client
    threads — before (v1), during (the publish + watcher swap lands in
    the middle of this phase), after (v2) — with per-request wall-clock
    latency collected per phase. Asserts (SystemExit) zero failed or
    rejected requests and zero new sampler-program compilations across
    the whole sequence, and that traffic actually moved to the new
    version."""
    import tempfile
    import jax as _jax

    from novel_view_synthesis_3d_tpu.registry import (
        RegistryStore, RegistryWatcher)

    reg_dir = tempfile.mkdtemp(prefix="nvs3d_serve_bench_reg_")
    store = RegistryStore(reg_dir)
    host = _jax.tree.map(np.asarray, _jax.device_get(params))
    m1 = store.publish_params(host, step=1, ema=False, channel="stable")
    # v2: same shapes (warm programs must survive), different values.
    host2 = _jax.tree.map(lambda p: np.asarray(p) * 1.02, host)
    service.swap_params(store.load_params(m1.version), m1.version,
                        step=m1.step, timeout=600)
    watcher = RegistryWatcher(service, store, "stable", poll_s=0.05)
    compile_before = service.compile_counters()
    errors = []
    versions = []
    vlock = threading.Lock()

    def run_phase(seed0: int):
        lat = []

        def client(tid: int):
            for j in range(max(1, per_phase // concurrency)):
                t0 = time.perf_counter()
                try:
                    t = service.submit(
                        conds[(tid + j) % len(conds)],
                        seed=seed0 + tid * 1000 + j)
                    t.result(timeout=600)
                    with vlock:
                        versions.append(t.model_version)
                except Exception as e:
                    errors.append(e)
                    continue
                lat.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(concurrency)]
        for t in threads:
            t.start()
        return threads, lat

    try:
        th, lat_before = run_phase(70_000)
        [t.join() for t in th]
        th, lat_during = run_phase(80_000)
        time.sleep(0.05)  # let the during-phase load build up
        m2 = store.publish_params(host2, step=2, ema=False,
                                  channel="stable")
        [t.join() for t in th]
        # The swap may land at the tail of the during phase; make sure it
        # is applied before the after phase so "after" is all-v2.
        deadline = time.monotonic() + 30
        while (service.model_version != m2.version
               and time.monotonic() < deadline):
            time.sleep(0.02)
        th, lat_after = run_phase(90_000)
        [t.join() for t in th]
    finally:
        watcher.stop()
    compile_after = service.compile_counters()
    built_delta = (compile_after["programs_built"]
                   - compile_before["programs_built"])
    jit_delta = (compile_after["jit_cache_entries"]
                 - compile_before["jit_cache_entries"])
    result = {
        "registry": reg_dir,
        "versions": [m1.version, m2.version],
        "swaps": watcher.swaps,
        "served_on": sorted(set(versions)),
        "failed_requests": len(errors),
        "p99_before_s": round(_p99(lat_before), 4),
        "p99_during_s": round(_p99(lat_during), 4),
        "p99_after_s": round(_p99(lat_after), 4),
        "programs_built_delta": built_delta,
        "jit_cache_entries_delta": jit_delta,
    }
    if errors:
        raise SystemExit(
            f"serve_bench --hot-swap: {len(errors)} request(s) failed/"
            f"rejected across the swap; first: {errors[0]!r}")
    if built_delta or jit_delta:
        raise SystemExit(
            "serve_bench --hot-swap: the swap triggered new sampler "
            f"compilations ({result}) — the program cache must survive "
            "a params swap (it is keyed on shapes, not params)")
    if service.model_version != m2.version:
        raise SystemExit(
            f"serve_bench --hot-swap: watcher never swapped to "
            f"{m2.version} (still {service.model_version})")
    if m2.version not in set(versions):
        raise SystemExit(
            "serve_bench --hot-swap: no request was served on the new "
            "version after the swap")
    return result


# ---------------------------------------------------------------------------
# --chaos: survivability drills under the calibrated Poisson trace
# ---------------------------------------------------------------------------
def _phase_counts(records) -> dict:
    counts = {"ok": 0, "late": 0, "expired": 0, "rejected": 0, "failed": 0}
    for rec in records:
        counts[rec["status"]] = counts.get(rec["status"], 0) + 1
    return counts


def chaos_bench(model, params, cfg, conds, args) -> dict:
    """The judged --chaos scenario (docs/DESIGN.md "Serving
    survivability"): ONE stepper service rides through every injected
    fault and must keep its contracts.

    A Poisson trace is calibrated once (~60% of the measured row-step
    capacity — headroom on purpose: this lane measures survivability
    under faults, not throughput at the knee; --continuous owns the
    knee) and replayed four times against the SAME service instance:

      steady      clean replay — the baseline every fault phase's p99
                  is compared against.
      nan         NVS3D_FI_SERVE_NAN_AT poisons ring row 0's carry
                  mid-request. Exactly that request must fail (with the
                  retryable SampleAnomaly), every co-rider must be
                  served within SLO — the in-ring quarantine bounds the
                  blast radius to one row.
      worker_die  NVS3D_FI_SERVE_WORKER_DIE_AT kills the serving worker
                  thread mid-trace. In-flight requests (at most the
                  ring capacity) fail retryably; the supervisor
                  restarts the worker exactly once and every queued /
                  later arrival is served within SLO.
      swap_fail   a v2 publish lands mid-trace with
                  NVS3D_FI_SERVE_SWAP_FAIL armed: the first swap
                  attempt fails (breaker opens), the half-open probe
                  recovers to v2 — with ZERO failed or rejected
                  requests (the old weights keep serving throughout).

    Across ALL phases — quarantine, restart, breaker, swap — the
    compile counters must not move: survivability is an in-program /
    supervisor concern, never a recompile (rc=1 on violation, like
    every other judged lane)."""
    import tempfile

    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.registry import (
        RegistryStore, RegistryWatcher)
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService
    from novel_view_synthesis_3d_tpu.utils import faultinject

    if faultinject.armed():
        raise SystemExit(
            f"serve_bench --chaos: faults already armed in the "
            f"environment ({faultinject.armed()}); refusing to run on "
            "top of them — the lane arms its own")

    mix = parse_class_map(args.chaos_mix, "--chaos-mix")
    slo = parse_class_map(args.chaos_slo_ms, "--chaos-slo-ms")
    max_batch = args.chaos_max_batch
    buckets = []
    b = 1
    while b <= max_batch:
        buckets.append(b)
        b *= 2
    few = min(mix)
    probs = {c: p / sum(mix.values()) for c, p in mix.items()}
    mean_steps = sum(c * p for c, p in probs.items())
    n = args.chaos_requests

    svc = SamplingService(
        model, params, cfg.diffusion,
        ServeConfig(scheduler="step", max_batch=max_batch,
                    flush_timeout_ms=args.flush_timeout_ms,
                    queue_depth=max(64, 2 * n),
                    results_folder="/tmp/nvs3d_serve_chaos"),
        results_folder="/tmp/nvs3d_serve_chaos")
    phases = {}
    try:
        seed = 90_000
        for b in buckets:
            tickets = [svc.submit(conds[j % len(conds)], seed=seed + j,
                                  sample_steps=few) for j in range(b)]
            seed += b
            for t in tickets:
                t.result(timeout=600)
        t0 = time.perf_counter()
        cal = 3
        for j in range(cal):
            svc.submit(conds[j % len(conds)], seed=70_000 + j,
                       sample_steps=few).result(timeout=600)
        t_row = (time.perf_counter() - t0) / (cal * few)
        rate = args.chaos_rate
        if rate <= 0:
            rate = round(0.60 / (mean_steps * t_row), 3)
        warm = svc.compile_counters()

        def run_phase(name: str, arm=None, disarm=None) -> dict:
            trace = poisson_trace(
                n, rate, mix, slo,
                args.chaos_seed + len(phases))  # distinct arrivals/seeds
            if arm is not None:
                arm()
            try:
                records, window = replay_trace(svc, conds, trace)
            finally:
                if disarm is not None:
                    disarm()
            summ = summarize_replay(records, window)
            summ.update(_phase_counts(records))
            lat = sorted(r["latency_s"] for r in records
                         if "latency_s" in r)
            summ["p50_s"] = round(_pctl(lat, 0.5), 4)
            summ["p99_s"] = round(_pctl(lat, 0.99), 4)
            phases[name] = summ
            return summ

        # --- steady: the clean baseline ------------------------------
        run_phase("steady")

        # --- nan: carry poison -> in-ring quarantine -----------------
        anomalies0 = svc.anomalies
        # Row 0 is the first arrival's slot (the ring is empty between
        # phases); +2 is its SECOND step — the first step draws z on
        # device, so the poison needs a materialized carry to land on.
        run_phase(
            "nan",
            arm=lambda: os.environ.__setitem__(
                "NVS3D_FI_SERVE_NAN_AT", f"{svc.dispatches + 2}:0"),
            disarm=lambda: os.environ.pop("NVS3D_FI_SERVE_NAN_AT", None))
        phases["nan"]["anomalies"] = svc.anomalies - anomalies0
        phases["nan"]["injected"] = "NVS3D_FI_SERVE_NAN_AT (ring row 0)"

        # --- worker_die: supervisor restart --------------------------
        restarts0 = svc.worker_restarts
        run_phase(
            "worker_die",
            arm=lambda: os.environ.__setitem__(
                "NVS3D_FI_SERVE_WORKER_DIE_AT", str(svc.dispatches + 3)),
            disarm=lambda: os.environ.pop(
                "NVS3D_FI_SERVE_WORKER_DIE_AT", None))
        phases["worker_die"]["worker_restarts"] = (
            svc.worker_restarts - restarts0)
        phases["worker_die"]["injected"] = "NVS3D_FI_SERVE_WORKER_DIE_AT"

        # --- swap_fail: breaker opens, half-open probe recovers ------
        reg_dir = tempfile.mkdtemp(prefix="nvs3d_serve_chaos_reg_")
        store = RegistryStore(reg_dir)
        host = jax.tree.map(np.asarray, jax.device_get(params))
        m1 = store.publish_params(host, step=1, ema=False,
                                  channel="stable")
        svc.swap_params(store.load_params(m1.version), m1.version,
                        step=m1.step, timeout=600)
        # Same shapes (warm programs must survive), different values.
        host2 = jax.tree.map(lambda p: np.asarray(p) * 1.02, host)
        watcher = RegistryWatcher(svc, store, "stable", poll_s=0.05,
                                  breaker_base_s=0.1)
        try:
            m2 = store.publish_params(host2, step=2, ema=False,
                                      channel="stable")
            # Armed BEFORE the replay: the watcher's first v2 poll fails
            # (breaker opens), its half-open probe ~0.1s later succeeds
            # — all of it under the trace's live traffic.
            run_phase(
                "swap_fail",
                arm=lambda: os.environ.__setitem__(
                    "NVS3D_FI_SERVE_SWAP_FAIL", "1"),
                disarm=lambda: os.environ.pop(
                    "NVS3D_FI_SERVE_SWAP_FAIL", None))
            deadline = time.monotonic() + 30
            while (svc.model_version != m2.version
                   and time.monotonic() < deadline):
                time.sleep(0.02)
        finally:
            watcher.stop()
        phases["swap_fail"]["injected"] = "NVS3D_FI_SERVE_SWAP_FAIL"
        phases["swap_fail"]["swap_failures"] = watcher.failures
        phases["swap_fail"]["swaps"] = watcher.swaps
        phases["swap_fail"]["versions"] = [m1.version, m2.version]
        phases["swap_fail"]["served_version_after"] = svc.model_version
        phases["swap_fail"]["recovered_to_v2"] = bool(
            svc.model_version == m2.version)

        after = svc.compile_counters()
        summary = svc.summary()
    finally:
        svc.stop()
    return {
        "trace": {
            "requests_per_phase": n, "rate_per_s": rate,
            "rate_auto_calibrated": args.chaos_rate <= 0,
            "row_step_s": round(t_row, 4),
            "mix": {str(k): v for k, v in mix.items()},
            "slo_ms": {str(k): v for k, v in slo.items()},
            "seed": args.chaos_seed, "max_batch": max_batch,
            "utilization_target": 0.60,
        },
        "phases": phases,
        "anomalies_total": summary["anomalies"],
        "worker_restarts_total": summary["worker_restarts"],
        "programs_built_delta": (after["programs_built"]
                                 - warm["programs_built"]),
        "jit_cache_entries_delta": (after["jit_cache_entries"]
                                    - warm["jit_cache_entries"]),
        "p99_steady_s": phases["steady"]["p99_s"],
        "p99_worst_fault_s": max(
            phases[p]["p99_s"] for p in ("nan", "worker_die",
                                         "swap_fail")),
    }


def check_chaos(chaos: dict) -> int:
    """rc=1 on any violated --chaos contract (stderr). The contract per
    phase: every request the injected fault did not poison is served
    within its SLO."""
    rc = 0
    n = chaos["trace"]["requests_per_phase"]
    max_batch = chaos["trace"]["max_batch"]
    ph = chaos["phases"]

    def served_except(name: str, poisoned: int):
        nonlocal rc
        p = ph[name]
        if p["ok"] != n - poisoned or p["late"] or p["expired"] \
                or p["rejected"]:
            print(f"error: chaos phase {name!r} served {p['ok']}/"
                  f"{n - poisoned} non-poisoned requests within SLO "
                  f"(late={p['late']}, expired={p['expired']}, "
                  f"rejected={p['rejected']}, failed={p['failed']}) — "
                  "a fault's blast radius must stop at the requests it "
                  "actually poisoned", file=sys.stderr)
            rc = 1

    served_except("steady", 0)
    if ph["steady"]["failed"]:
        print(f"error: {ph['steady']['failed']} request(s) failed in the "
              "steady phase — no fault was armed", file=sys.stderr)
        rc = 1
    if ph["nan"]["failed"] != 1 or ph["nan"]["anomalies"] != 1:
        print("error: the NaN drill must quarantine EXACTLY the poisoned "
              f"request (failed={ph['nan']['failed']}, anomalies="
              f"{ph['nan']['anomalies']})", file=sys.stderr)
        rc = 1
    served_except("nan", ph["nan"]["failed"])
    died = ph["worker_die"]["failed"]
    if not (1 <= died <= max_batch):
        print(f"error: worker death failed {died} request(s) — the blast "
              f"radius is the in-flight ring, 1..{max_batch}",
              file=sys.stderr)
        rc = 1
    if ph["worker_die"]["worker_restarts"] != 1:
        print("error: expected exactly one supervised worker restart, "
              f"got {ph['worker_die']['worker_restarts']}",
              file=sys.stderr)
        rc = 1
    served_except("worker_die", died)
    sw = ph["swap_fail"]
    if sw["failed"] or not sw["recovered_to_v2"] \
            or sw["swap_failures"] < 1 or sw["swaps"] != 1:
        print("error: swap-fail drill must serve every request on the "
              "old weights while the breaker opens, then recover to v2 "
              f"via the half-open probe (failed={sw['failed']}, "
              f"swap_failures={sw['swap_failures']}, swaps="
              f"{sw['swaps']}, recovered={sw['recovered_to_v2']})",
              file=sys.stderr)
        rc = 1
    served_except("swap_fail", sw["failed"])
    if chaos["programs_built_delta"] or chaos["jit_cache_entries_delta"]:
        print("error: the chaos phases compiled something (built="
              f"{chaos['programs_built_delta']}, jit="
              f"{chaos['jit_cache_entries_delta']}) — quarantine, "
              "restart and swap recovery are in-program / supervisor "
              "concerns, never a recompile", file=sys.stderr)
        print_recompile_culprit("/tmp/nvs3d_serve_chaos")
        rc = 1
    return rc


# ---------------------------------------------------------------------------
# --reqtrace: request-scoped tracing cost + reconstruction contract
# ---------------------------------------------------------------------------
def reqtrace_bench(model, params, cfg, conds, args) -> dict:
    """Judged --reqtrace scenario (docs/DESIGN.md "Request tracing,
    SLOs & flight recorder").

    ONE deterministic mixed trace — single-shot requests (half with
    client-supplied trace ids) plus trajectory orbits — replays through
    two identically configured stepper services:

      OFF: obs.enabled=False — NullTracer, no JSONL sink. The flight
           recorder stays on (it is always-on by design, so its deque
           append is part of both lanes' cost).
      ON:  the `nvs3d serve` deployment wiring — RunTelemetry with the
           JSONL sink, span tracing, the SLO engine, and the flight
           recorder's bus tap.

    Asserts (check_reqtrace, rc=1 on violation):
      - every completed request's timeline reconstructs from
        telemetry.jsonl via obs/reqtrace.py (the SAME functions
        `nvs3d obs trace` runs) with zero invariant violations;
      - zero new programs compiled inside either timed window (tracing
        is host-side: program identity must be untouched);
      - the ON lane's RPS is within NVS3D_REQTRACE_OVERHEAD_PCT
        (default 2%) of the OFF lane. CPU CI hosts are noisy at bench
        request counts — the env override exists for that, the default
        documents the contract.
    """
    import dataclasses as _dc
    import shutil

    from novel_view_synthesis_3d_tpu import obs
    from novel_view_synthesis_3d_tpu.config import ServeConfig, SLOConfig
    from novel_view_synthesis_3d_tpu.obs import reqtrace
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    steps = cfg.diffusion.sample_timesteps
    n_single = args.rt_requests
    orbits, frames = args.rt_orbits, args.rt_frames
    traj_trace = make_orbit_trace(conds, orbits, frames, seed0=71_000)
    max_batch = 4
    buckets = [1, 2, 4]
    base_dir = "/tmp/nvs3d_reqtrace"
    tol = float(os.environ.get("NVS3D_REQTRACE_OVERHEAD_PCT", "2.0"))

    def run_lane(name: str, instrumented: bool) -> dict:
        run_dir = os.path.join(base_dir, name)
        shutil.rmtree(run_dir, ignore_errors=True)
        os.makedirs(run_dir, exist_ok=True)
        ocfg = _dc.replace(cfg.obs, enabled=instrumented,
                           jsonl=instrumented, trace=instrumented,
                           device_poll_s=0.0, metrics_port=0)
        telemetry = obs.RunTelemetry.create(ocfg, run_dir,
                                            start_server=False)
        # SLO targets on the ON lane only: the artifact embeds the live
        # engine's snapshot; a generous whole-run budget keeps the CPU
        # lane's attainment meaningful rather than saturation-noisy.
        slo = (SLOConfig(targets=f"{steps}:120000") if instrumented
               else SLOConfig())
        svc = SamplingService(
            model, params, cfg.diffusion,
            ServeConfig(scheduler="step", max_batch=max_batch,
                        k_max=args.rt_k_max, flush_timeout_ms=10.0,
                        queue_depth=max(64, 4 * (n_single + orbits)),
                        results_folder=run_dir, slo=slo),
            results_folder=run_dir, tracer=telemetry.tracer,
            flight=telemetry.flight, model_version="bench:0")
        try:
            seed = 10_000
            for b in buckets:
                for t in [svc.submit(conds[j % len(conds)],
                                     seed=seed + j, sample_steps=steps)
                          for j in range(b)]:
                    t.result(timeout=600)
                seed += b
            svc.submit_trajectory(
                dict(traj_trace[0]["cond"]),
                poses=traj_trace[0]["poses"][:2], seed=9_999,
                sample_steps=steps).result(timeout=600)
            before = svc.compile_counters()
            t0 = time.perf_counter()
            tickets = [svc.submit_trajectory(
                dict(o["cond"]), poses=o["poses"], seed=o["seed"],
                sample_steps=steps, trace_id=f"orbit-{k}")
                for k, o in enumerate(traj_trace)]
            tickets += [svc.submit(
                conds[i % len(conds)], seed=5_000 + i,
                sample_steps=steps,
                trace_id=(f"cli-{i}" if i % 2 == 0 else None))
                for i in range(n_single)]
            completed = 0
            for t in tickets:
                t.result(timeout=600)
                completed += 1
            window = time.perf_counter() - t0
            after = svc.compile_counters()
            summary = svc.summary()
        finally:
            svc.stop()
            telemetry.finalize(export_trace=False)
        return {
            "run_dir": run_dir,
            "instrumented": instrumented,
            "completed": completed,
            "window_s": round(window, 3),
            "rps": round(completed / window, 3) if window else 0.0,
            "programs_built_delta": after["programs_built"]
            - before["programs_built"],
            "jit_cache_entries_delta": after["jit_cache_entries"]
            - before["jit_cache_entries"],
            "slo": summary.get("slo"),
            "flight_dumps": summary.get("flight_dumps", 0),
        }

    # OFF first, ON second: both warm their own service from the same
    # persistent compile cache, so ordering costs neither lane.
    off = run_lane("off", False)
    on = run_lane("on", True)

    rows = reqtrace.load_rows(on["run_dir"])
    timelines = reqtrace.reconstruct(rows)
    problems = reqtrace.verify_timelines(timelines, rows)
    complete_ok = sum(1 for tl in timelines.values()
                     if tl["complete"] and tl["outcome"] == "ok")
    overhead_pct = (100.0 * (off["rps"] - on["rps"]) / off["rps"]
                    if off["rps"] else 0.0)
    return {
        "trace": {"single_requests": n_single, "orbits": orbits,
                  "frames_per_orbit": frames, "steps": steps,
                  "k_max": args.rt_k_max, "max_batch": max_batch},
        "off": off,
        "on": on,
        "overhead_pct": round(overhead_pct, 2),
        "overhead_tolerance_pct": tol,
        "telemetry_rows": len(rows),
        "timelines_reconstructed": len(timelines),
        "timelines_complete_ok": complete_ok,
        "completed_on_lane": on["completed"],
        "reconstruction_problems": problems,
        "span_percentiles": reqtrace.span_percentiles(rows),
    }


def check_reqtrace(rt: dict) -> int:
    """rc=1 on any violated --reqtrace contract (stderr)."""
    rc = 0
    if rt["reconstruction_problems"]:
        for p in rt["reconstruction_problems"]:
            print(f"error: reqtrace invariant: {p}", file=sys.stderr)
        rc = 1
    if rt["timelines_complete_ok"] < rt["completed_on_lane"]:
        print("error: only "
              f"{rt['timelines_complete_ok']}/{rt['completed_on_lane']} "
              "completed requests reconstruct a complete ok timeline "
              "from telemetry.jsonl — every served request must be "
              "traceable", file=sys.stderr)
        rc = 1
    for lane in ("off", "on"):
        d = rt[lane]
        if d["programs_built_delta"] or d["jit_cache_entries_delta"]:
            print(f"error: the {lane} lane compiled something (built="
                  f"{d['programs_built_delta']}, jit="
                  f"{d['jit_cache_entries_delta']}) — request tracing "
                  "is host-side and must not perturb program identity",
                  file=sys.stderr)
            if d.get("run_dir"):
                print_recompile_culprit(d["run_dir"])
            rc = 1
    if rt["overhead_pct"] > rt["overhead_tolerance_pct"]:
        print(f"error: tracing overhead {rt['overhead_pct']}% exceeds "
              f"the {rt['overhead_tolerance_pct']}% budget "
              "(NVS3D_REQTRACE_OVERHEAD_PCT overrides on noisy hosts)",
              file=sys.stderr)
        rc = 1
    return rc


# ---------------------------------------------------------------------------
# --fleet: replica router + failover + rolling deploy (subprocess fleet)
# ---------------------------------------------------------------------------
def _await_ready(path: str, timeout_s: float) -> dict:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        time.sleep(0.2)
    raise RuntimeError(f"replica ready file {path} never appeared "
                       f"within {timeout_s:.0f}s")


def _spawn_replica(name: str, base: str, registry_dir: str, args,
                   jax_cache: str, extra_env=None):
    """One fleet replica as a real OS process (serve/replica_main.py):
    own JAX runtime, own telemetry dir (<base>/replica_<name>/), own
    registry watcher on the 'stable' channel with poke-driven polling
    (poll_s is huge on purpose — the deploy driver owns swap timing).

    serve.step_floor_ms paces each denoise dispatch to a wall-clock
    floor (the sleep releases the GIL/core), emulating the device-bound
    replica a CPU CI host cannot provide — so the scaling lane measures
    the ROUTER's ability to overlap N replicas, which is what fleet
    serving adds, not the host's ability to run N models at once."""
    import subprocess

    rdir = os.path.join(base, f"replica_{name}")
    os.makedirs(rdir, exist_ok=True)
    spec_path = os.path.join(base, f"{name}.spec.json")
    # FleetSupervisor.adopt() pins the concrete port into the spec so
    # respawns keep the replica's URL; a rewrite must not unpin it.
    port = 0
    try:
        with open(spec_path) as fh:
            port = int(json.load(fh).get("port", 0))
    except (OSError, ValueError, TypeError):
        pass
    spec = {
        "name": name,
        "results_folder": rdir,
        "ready_file": os.path.join(base, f"{name}.ready"),
        "preset": args.preset,
        "sidelength": args.sidelength,
        "steps": args.steps,
        "port": port,
        "jax_cache_dir": jax_cache,
        "registry": {"dir": registry_dir, "channel": "stable",
                     "poll_s": 3600.0},
        "overrides": {
            "model.num_res_blocks": 1,
            "model.attn_resolutions": [8],
            "serve.scheduler": "step",
            "serve.max_batch": 1,
            "serve.k_max": max(4, args.fleet_frames),
            "serve.flush_timeout_ms": 5.0,
            "serve.queue_depth": 256,
            "serve.step_floor_ms": args.fleet_floor_ms,
            "serve.slo.targets": f"{args.steps}:60000",
            "obs.device_poll_s": 0.0,
        },
    }
    with open(spec_path, "w") as fh:
        json.dump(spec, fh)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    # Append: a supervisor respawn's output lands after its dead
    # predecessor's, not over it.
    log = open(os.path.join(rdir, "replica.log"), "a")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "novel_view_synthesis_3d_tpu.serve.replica_main", spec_path],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=repo_root)
    return proc


def _fleet_closed_loop(router, conds, n: int, concurrency: int,
                       steps: int, seed0: int, prefix: str) -> dict:
    """Closed-loop load through the router: `concurrency` clients drain
    a shared counter of `n` single-shot requests. Wall-clock RPS."""
    lock = threading.Lock()
    state = {"next": 0, "lat": [], "errors": []}

    def client():
        while True:
            with lock:
                i = state["next"]
                if i >= n:
                    return
                state["next"] = i + 1
            t0 = time.perf_counter()
            try:
                router.request(conds[i % len(conds)], seed=seed0 + i,
                               sample_steps=steps,
                               trace_id=f"{prefix}-{i}")
            except Exception as e:
                with lock:
                    state["errors"].append(
                        f"{prefix}-{i}: {type(e).__name__}: {e}")
                continue
            with lock:
                state["lat"].append(time.perf_counter() - t0)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"requests": n, "wall_s": round(wall, 3),
            "rps": round(n / wall, 3), "p99_s": round(_p99(state["lat"]), 3),
            "errors": state["errors"]}


def _free_port() -> int:
    """A port the router process can bind — picked up front so the
    respawn after the SIGKILL binds the SAME address and the clients'
    retries land on the new incarnation."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _ingress_closed_loop(ingress, conds, n: int, concurrency: int,
                         steps: int, seed0: int, prefix: str,
                         deadline_s: float = 600.0) -> dict:
    """Closed-loop load through the ROUTER PROCESS (an HttpReplica
    handle over router_main's ingress). Retryable transport errors —
    ReplicaUnreachable while the router is down, the wire round-trip of
    the same — are ridden out with a fresh trace id per attempt (so a
    dead incarnation's half-trace never collides with the retry's), the
    exact client discipline sample/client.submit_with_retry encodes.
    Only errors that exhaust the deadline count as failures."""
    lock = threading.Lock()
    state = {"next": 0, "lat": [], "errors": [], "retries": 0}

    def client():
        while True:
            with lock:
                i = state["next"]
                if i >= n:
                    return
                state["next"] = i + 1
            t0 = time.perf_counter()
            attempt = 0
            while True:
                tid = f"{prefix}-{i}-a{attempt}"
                try:
                    ingress.submit(
                        conds[i % len(conds)], seed=seed0 + i,
                        sample_steps=steps,
                        trace_id=tid).result(timeout=deadline_s)
                    with lock:
                        state["lat"].append(time.perf_counter() - t0)
                    break
                except Exception as e:
                    attempt += 1
                    if (not getattr(e, "retryable", False)
                            or time.perf_counter() - t0 > deadline_s):
                        with lock:
                            state["errors"].append(
                                f"{tid}: {type(e).__name__}: {e}")
                        break
                    with lock:
                        state["retries"] += 1
                    time.sleep(0.25)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"requests": n, "wall_s": round(wall, 3),
            "rps": round(n / wall, 3),
            "p99_s": round(_p99(state["lat"]), 3),
            "retries": state["retries"], "errors": state["errors"]}


def _counter_total(metrics_text: str, family: str) -> float:
    """Sum every sample of one Prometheus counter family."""
    total = 0.0
    for line in metrics_text.splitlines():
        if not line.startswith(family):
            continue
        rest = line[len(family):]
        if rest and rest[0] not in ("{", " "):
            continue  # a different family sharing the prefix
        try:
            total += float(line.rsplit(None, 1)[-1])
        except ValueError:
            continue
    return total


def _spawn_router_proc(base: str, spec_path: str) -> "object":
    """router_main as a real OS process over an existing spec file —
    the first spawn and the post-SIGKILL respawn run the SAME command,
    which is the whole crash-safety claim."""
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    log = open(os.path.join(base, "router_proc", "router.log"), "a")
    return subprocess.Popen(
        [sys.executable, "-m",
         "novel_view_synthesis_3d_tpu.serve.router_main", spec_path],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=repo_root)


def fleet_bench(args) -> dict:
    """Six judged drills over one real 4-process fleet:

      scaling   closed-loop RPS with 1 replica in rotation vs all N —
                the router must deliver near-linear fan-out (>= 3.2x at
                N=4) over step-floor-paced replicas;
      chaos     SIGKILL one replica while it owns a mid-flight orbit
                and carries single-shot traffic — zero failed requests,
                every failover hop names the victim (blast radius), and
                the cross-replica trace reconstructs clean — then the
                FleetSupervisor must RESURRECT the victim into the same
                spec/port under load, verified ready + healthy + on the
                channel-head version, and the fleet serves through it;
      deploy    three scripted rolling deploys on the survivors: a good
                version (zero-downtime, status 'deployed'), a corrupt
                artifact (the swap breaker opens -> auto-rollback), and
                a version whose canary gets an SLO-burn burst during
                probation (the PR 14 gate -> auto-rollback) — with
                closed-loop router traffic across all three asserting
                zero failures;
      restart   the ROUTER itself as a process (router_main ingress)
                SIGKILLed mid-load: clients ride the outage on
                retryable errors (zero failures), the respawn replays
                the journal (recovery provenance in its ready file),
                and the consistent-hash ring digest is bit-identical
                across incarnations — every affinity pin re-derives
                from zero recovered state;
      gray      one replica comes back SLOW (fault-injected step delay,
                not dead — the failure health checks can't see): hedged
                dispatch + p99 demotion must keep fleet p99 within 2x
                the steady state, zero failures, hedges observed;
      recompile survivors that were never restarted end the whole
                gauntlet with their program-build counters exactly
                where warmup left them — kills, deploys, and hedges
                never recompile warm replicas.
    """
    from novel_view_synthesis_3d_tpu import obs
    from novel_view_synthesis_3d_tpu.config import RouterConfig, get_preset
    from novel_view_synthesis_3d_tpu.obs import reqtrace
    from novel_view_synthesis_3d_tpu.registry import RegistryStore
    from novel_view_synthesis_3d_tpu.serve import FleetRouter, HttpReplica
    from novel_view_synthesis_3d_tpu.serve.deploy import rolling_deploy
    from novel_view_synthesis_3d_tpu.serve.fleet_supervisor import (
        FleetSupervisor,
        ReplicaSpec,
    )
    from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

    base = args.fleet_dir or "/tmp/nvs3d_fleet_bench"
    if os.path.isdir(base):
        import shutil

        shutil.rmtree(base)
    os.makedirs(base, exist_ok=True)
    jax_cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")

    # Parent-side build: conds for the load + the params the fleet
    # serves (published as v1; every replica loads the channel head, so
    # the whole fleet starts byte-identical).
    cfg, model, params, conds = build(
        args.preset, args.sidelength, args.steps,
        extra_overrides=[("model.num_res_blocks", 1),
                         ("model.attn_resolutions", [8])])
    registry_dir = os.path.join(base, "registry")
    store = RegistryStore(registry_dir)
    v1 = store.publish_params(params, step=1, ema=False,
                              channel="stable", notes="fleet v1").version

    n = args.fleet_replicas
    names = [f"r{i}" for i in range(n)]
    procs = {}
    handles = []
    supervisor = None
    router_proc = None
    try:
        # r0 first: its first request compiles the (bucket=1) program
        # into the shared persistent cache; r1..rN then spawn into a
        # warm cache instead of compiling 4x concurrently on one core.
        procs[names[0]] = _spawn_replica(names[0], base, registry_dir,
                                         args, jax_cache)
        ready = _await_ready(os.path.join(base, f"{names[0]}.ready"),
                             args.fleet_spawn_timeout_s)
        handles.append(HttpReplica(
            names[0], ready["url"],
            run_dir=os.path.join(base, f"replica_{names[0]}")))
        handles[0].submit(conds[0], seed=1, sample_steps=args.steps,
                          trace_id="warm-r0").result(timeout=600)
        for name in names[1:]:
            procs[name] = _spawn_replica(name, base, registry_dir, args,
                                         jax_cache)
        for name in names[1:]:
            ready = _await_ready(os.path.join(base, f"{name}.ready"),
                                 args.fleet_spawn_timeout_s)
            handles.append(HttpReplica(
                name, ready["url"],
                run_dir=os.path.join(base, f"replica_{name}")))
        warm = [(h, h.submit(conds[0], seed=2, sample_steps=args.steps,
                             trace_id=f"warm-{h.name}"))
                for h in handles[1:]]
        for _, t in warm:
            t.result(timeout=600)
        # Program-build counters after warmup: the recompile drill at
        # the end asserts these stay FLAT on every replica the
        # supervisor never restarted.
        builds0 = {h.name: int(h.healthz().get("programs_built", -1))
                   for h in handles}

        router_dir = os.path.join(base, "router")
        telemetry = obs.RunTelemetry.create(
            get_preset(args.preset).obs, router_dir, start_server=False)
        rcfg = RouterConfig(health_poll_s=0.25, health_ttl_s=5.0,
                            retry_budget=3,
                            deploy_drain_timeout_s=60.0,
                            deploy_probation_s=4.0,
                            deploy_swap_timeout_s=60.0)
        router = FleetRouter(handles, rcfg=rcfg,
                             tracer=telemetry.tracer, bus=telemetry.bus,
                             start=True)
        router.poll_health()

        # -- fleet supervisor ---------------------------------------
        # Adopts the bench-spawned processes (pinning each concrete
        # port into its spec) and owns every respawn from here on. The
        # slow_env overlay is how the gray-failure drill later arranges
        # for one replica to come back SLOW instead of healthy.
        slow_env = {}

        def respawn(spec):
            return _spawn_replica(spec.name, base, registry_dir, args,
                                  jax_cache,
                                  extra_env=slow_env.get(spec.name))

        sup_rcfg = RouterConfig(
            supervisor_max_restarts=6,
            supervisor_backoff_s=0.5,
            supervisor_backoff_cap_s=2.0,
            supervisor_heartbeat_max_age_s=60.0,
            supervisor_health_fails=8,
            supervisor_poll_s=0.5,
            supervisor_ready_timeout_s=args.fleet_spawn_timeout_s)
        supervisor = FleetSupervisor(
            [ReplicaSpec(name=name,
                         spec_path=os.path.join(base,
                                                f"{name}.spec.json"),
                         ready_file=os.path.join(base, f"{name}.ready"))
             for name in names],
            rcfg=sup_rcfg, bus=telemetry.bus, spawn=respawn)
        for name in names:
            supervisor.adopt(name, procs[name])
        supervisor.start()

        def await_resurrection(name, want, timeout_s):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                st = supervisor.status()[name]
                if st["resurrections"] >= want and st["alive"]:
                    procs[name] = supervisor.procs()[name]
                    return True
                if st["failed"]:
                    return False
                time.sleep(0.25)
            return False

        # -- scaling lane -------------------------------------------
        for name in names[1:]:
            router.quiesce(name)
        n1 = _fleet_closed_loop(router, conds, args.fleet_requests,
                                args.fleet_concurrency, args.steps,
                                1000, "scale1")
        for name in names[1:]:
            router.readmit(name)
        router.poll_health()
        nN = _fleet_closed_loop(router, conds, args.fleet_requests * n,
                                args.fleet_concurrency, args.steps,
                                2000, "scaleN")
        scaling = {
            "replicas": n,
            "step_floor_ms": args.fleet_floor_ms,
            "n1": n1, "nN": nN,
            "scaling_x": round(nN["rps"] / max(n1["rps"], 1e-9), 3),
        }

        # -- chaos lane ---------------------------------------------
        tcond = {k: conds[0][k] for k in ("x", "R1", "t1", "K")}
        poses = orbit_poses(
            args.fleet_frames,
            radius=float(np.linalg.norm(conds[0]["t1"])) or 1.0,
            elevation=0.3)
        orbit_out = {}

        def orbit_client():
            try:
                frames = router.request_trajectory(
                    tcond, poses, seed=7, sample_steps=args.steps,
                    session="chaos-orbit", trace_id="chaos-orbit",
                    timeout_s=600.0)
                orbit_out["frames"] = int(frames.shape[0])
            except Exception as e:
                orbit_out["error"] = f"{type(e).__name__}: {e}"

        ot = threading.Thread(target=orbit_client, daemon=True)
        ot.start()
        deadline = time.time() + 15
        while (time.time() < deadline
               and "chaos-orbit" not in router._sessions):
            time.sleep(0.02)
        victim = (router._sessions.get("chaos-orbit")
                  or router.ring_pin("chaos-orbit") or names[-1])
        # Let the orbit get properly mid-flight on the victim's ring,
        # then kill -9: no drain, no goodbye — the transport must
        # surface ReplicaUnreachable and the router must fail over.
        time.sleep(3.0 * args.fleet_floor_ms / 1000.0)
        procs[victim].kill()
        single = _fleet_closed_loop(
            router, conds, args.fleet_requests * 2,
            args.fleet_concurrency, args.steps, 3000, "chaos")
        ot.join(timeout=600)
        procs[victim].wait(timeout=30)
        survivors = [name for name in names if name != victim]

        # Resurrection under load: the supervisor must notice the
        # corpse, respawn it into the SAME spec (same port — the
        # router's handle stays valid), verify ready + healthy + on the
        # channel-head version, and the router readmits it through its
        # natural health poll. The fleet then serves THROUGH the
        # resurrected replica with zero failures.
        resurrected = await_resurrection(victim, 1,
                                         args.fleet_spawn_timeout_s)
        victim_back = False
        if resurrected:
            back_by = time.time() + 60
            while time.time() < back_by:
                snap = router.poll_health().get(victim)
                if snap is not None:
                    victim_back = True
                    break
                time.sleep(0.25)
        resur_load = _fleet_closed_loop(
            router, conds, args.fleet_requests, args.fleet_concurrency,
            args.steps, 3500, "resur")
        chaos = {
            "victim": victim,
            "orbit": orbit_out,
            "single": single,
            "failed": len(single["errors"])
            + (0 if "frames" in orbit_out else 1),
            "resurrection": {
                "resurrected": resurrected,
                "victim_back_in_rotation": victim_back,
                "supervisor": supervisor.status()[victim],
                "load": resur_load,
            },
        }

        # -- rolling-deploy lane ------------------------------------
        canary = sorted(survivors)[0]
        canary_h = next(h for h in handles if h.name == canary)
        bg_stop = threading.Event()
        bg = {"ok": 0, "errors": []}

        def bg_load(lane: int):
            i = 0
            while not bg_stop.is_set():
                tid = f"deploy-bg{lane}-{i}"  # unique per lane thread
                try:
                    router.request(conds[i % len(conds)],
                                   seed=50_000 + 1000 * lane + i,
                                   sample_steps=args.steps,
                                   trace_id=tid)
                    bg["ok"] += 1
                except Exception as e:
                    bg["errors"].append(
                        f"{tid}: {type(e).__name__}: {e}")
                i += 1

        bg_threads = [threading.Thread(target=bg_load, args=(lane,),
                                       daemon=True)
                      for lane in range(2)]
        for t in bg_threads:
            t.start()

        v2 = store.publish_params(params, step=2, ema=False,
                                  channel=None, notes="fleet v2").version
        good = rolling_deploy(router, store, "stable", v2, rcfg=rcfg,
                              bus=telemetry.bus, replicas=survivors)

        # Corrupt artifact: published clean, then its payload bytes are
        # torn on disk — verify() fails on the canary, the swap breaker
        # opens, and the deploy must roll the whole fleet back.
        v3 = store.publish_params(params, step=3, ema=False,
                                  channel=None, notes="fleet v3").version
        payload = os.path.join(registry_dir, "versions", v3,
                               "params.msgpack")
        with open(payload, "r+b") as fh:
            fh.seek(100)
            fh.write(b"\xde\xad\xbe\xef")
        breaker_roll = rolling_deploy(router, store, "stable", v3,
                                      rcfg=rcfg, bus=telemetry.bus,
                                      replicas=survivors)
        # The rollback's poke clears the canary's breaker on the
        # watcher THREAD; wait until the whole fleet reads closed so
        # the next deploy's pre-gate doesn't race it.
        settle = time.time() + 30
        while time.time() < settle:
            if all(h.healthz().get("breaker") == "closed"
                   for h in handles if h.name in survivors):
                break
            time.sleep(0.1)

        # SLO-gated rollback: v4 is GOOD bytes, but the canary takes a
        # burst of deadline-doomed requests during probation (fired
        # straight at the canary, bypassing the router — intentional
        # chaos inputs, excluded from the zero-failure accounting);
        # the DeadlineExceeded errors burn its fast window past
        # deploy_burn_max and the gate must revert the fleet.
        v4 = store.publish_params(params, step=4, ema=False,
                                  channel=None, notes="fleet v4").version
        burst_done = threading.Event()

        def doomed_burst():
            deadline = time.time() + 60
            while time.time() < deadline and not burst_done.is_set():
                try:
                    if canary_h.healthz().get("model_version") == v4:
                        break
                except Exception:
                    pass
                time.sleep(0.05)
            tickets = []
            for i in range(12):
                try:
                    tickets.append(canary_h.submit(
                        conds[i % len(conds)], seed=90_000 + i,
                        sample_steps=args.steps, deadline_ms=1.0,
                        trace_id=f"doomed-{i}"))
                except Exception:
                    pass
            for t in tickets:
                try:
                    t.result(timeout=120)
                except Exception:
                    pass  # expected: DeadlineExceeded burns the canary

        bt = threading.Thread(target=doomed_burst, daemon=True)
        bt.start()
        slo_roll = rolling_deploy(router, store, "stable", v4,
                                  rcfg=rcfg, bus=telemetry.bus,
                                  replicas=survivors)
        burst_done.set()
        bt.join(timeout=120)

        bg_stop.set()
        for t in bg_threads:
            t.join(timeout=600)
        final_versions = {}
        for name in survivors:
            try:
                final_versions[name] = next(
                    h for h in handles
                    if h.name == name).healthz().get("model_version")
            except Exception:
                final_versions[name] = None
        deploy = {
            "v1": v1, "v2": v2, "v3_corrupt": v3, "v4_doomed": v4,
            "good": good, "breaker_rollback": breaker_roll,
            "slo_rollback": slo_roll,
            "bg_ok": bg["ok"], "bg_errors": bg["errors"],
            "final_versions": final_versions,
        }

        # The in-process router's work is done; the remaining drills
        # target the router AS A PROCESS (router_main ingress). Close
        # it cleanly so its telemetry is flushed for reconstruction.
        router.close()
        telemetry.finalize()

        # -- router-restart lane (crash-safe ingress) ----------------
        # The router runs as its own process over ALL N replicas
        # (including the resurrected victim). Clients speak the replica
        # wire protocol to it. Mid-load it is SIGKILLed — no drain, no
        # journal flush beyond the per-append fsync discipline — and
        # respawned from the same spec: clients ride the outage on
        # retryable errors, the respawn replays the journal (recovery
        # provenance lands in its ready file), and the consistent-hash
        # ring digest must be BIT-IDENTICAL across incarnations: every
        # session's home re-derives from zero recovered state.
        router_port = _free_port()
        rproc_dir = os.path.join(base, "router_proc")
        os.makedirs(rproc_dir, exist_ok=True)
        rspec = {
            "name": "ingress",
            "results_folder": rproc_dir,
            "ready_file": os.path.join(base, "router.ready"),
            "port": router_port,
            "replicas": [{"name": h.name, "url": h.base_url,
                          "run_dir": h.run_dir} for h in handles],
            "journal": os.path.join(rproc_dir, "router_journal.jsonl"),
            "heartbeat_s": 1.0,
            "rcfg": {
                "health_poll_s": 0.25,
                "health_ttl_s": 5.0,
                "retry_budget": 3,
                # Gray-failure defenses, exercised by the NEXT lane:
                # hedge stalled singles at ~1.5x the healthy service
                # time; demote a replica whose reported p99 is 4x the
                # best peer's.
                "hedge_delay_s": 1.5 * args.steps
                * args.fleet_floor_ms / 1000.0,
                "demote_p99_factor": 4.0,
            },
        }
        rspec_path = os.path.join(base, "router.spec.json")
        with open(rspec_path, "w") as fh:
            json.dump(rspec, fh)
        router_proc = _spawn_router_proc(base, rspec_path)
        ready1 = _await_ready(rspec["ready_file"],
                              args.fleet_spawn_timeout_s)
        ingress = HttpReplica("ingress", ready1["url"],
                              connect_timeout_s=5.0)
        digest_before = ingress.healthz()["affinity"]["ring_digest"]

        kill_out = {}

        def kill_load():
            kill_out.update(_ingress_closed_loop(
                ingress, conds, args.fleet_requests * 2,
                args.fleet_concurrency, args.steps, 5000, "rr"))

        kt = threading.Thread(target=kill_load, daemon=True)
        kt.start()
        # Let the load get properly mid-flight, then kill -9 and
        # respawn the same spec while the clients are still retrying.
        time.sleep(3.0 * args.steps * args.fleet_floor_ms / 1000.0)
        router_proc.kill()
        router_proc.wait(timeout=30)
        try:
            os.remove(rspec["ready_file"])
        except OSError:
            pass
        router_proc = _spawn_router_proc(base, rspec_path)
        ready2 = _await_ready(rspec["ready_file"],
                              args.fleet_spawn_timeout_s)
        kt.join(timeout=900)
        digest_after = ingress.healthz()["affinity"]["ring_digest"]
        # Steady-state reference through the SAME ingress, all
        # replicas healthy and fast — the gray lane's p99 yardstick.
        steady = _ingress_closed_loop(
            ingress, conds, args.fleet_requests * 2,
            args.fleet_concurrency, args.steps, 6000, "steady")
        restart = {
            "load": kill_out,
            "steady": steady,
            "recovery": (ready2 or {}).get("recovery"),
            "ring_digest_before": digest_before,
            "ring_digest_after": digest_after,
            "ring_digest_match": digest_before == digest_after,
        }

        # -- gray-failure lane (slow replica, hedged dispatch) -------
        # One survivor comes back SLOW: its respawn inherits a fault-
        # injected per-step delay the health checks cannot see (healthz
        # stays ok). Hedging + p99 demotion must keep fleet p99 within
        # 2x the steady state with zero failures.
        slowpoke = sorted(nm for nm in survivors if nm != victim)[0]
        slow_s = 4.0 * args.fleet_floor_ms / 1000.0
        slow_env[slowpoke] = {"NVS3D_FI_SERVE_SLOW_STEP": f"*:{slow_s}"}
        hedges_before = _counter_total(ingress.metrics_text(),
                                       "nvs3d_router_hedges_total")
        procs[slowpoke].kill()
        slow_ok = await_resurrection(slowpoke, 1,
                                     args.fleet_spawn_timeout_s)
        # The ingress readmits the respawn through its natural health
        # poll; the load must find the slowpoke IN rotation, or the
        # drill would measure failover instead of gray-failure hedging.
        back_by = time.time() + 60
        while time.time() < back_by:
            snap = ingress.healthz()["replicas"].get(slowpoke, {})
            if snap.get("reachable") and snap.get("in_rotation"):
                break
            time.sleep(0.25)
        gray_load = _ingress_closed_loop(
            ingress, conds, args.fleet_requests * 2,
            args.fleet_concurrency, args.steps, 7000, "gray")
        hedges_after = _counter_total(ingress.metrics_text(),
                                      "nvs3d_router_hedges_total")
        gray = {
            "slowpoke": slowpoke,
            "slow_step_s": slow_s,
            "respawned_slow": slow_ok,
            "load": gray_load,
            "steady_p99_s": steady["p99_s"],
            "p99_ratio": round(
                gray_load["p99_s"] / max(steady["p99_s"], 1e-9), 3),
            "hedges": hedges_after - hedges_before,
        }

        # -- recompile audit ----------------------------------------
        # Replicas the supervisor never restarted must end the whole
        # gauntlet with their program-build counters untouched —
        # failover, deploys, router kills, and hedges never recompile
        # a warm replica. (Restarted replicas are new PROCESSES whose
        # counters restarted from zero; they are excluded, their
        # warm-cache boot is covered by the spawn path.)
        sup_status = supervisor.status()
        builds1 = {h.name: int(h.healthz().get("programs_built", -1))
                   for h in handles}
        never_restarted = [nm for nm in names
                           if sup_status[nm]["restarts"] == 0]
        recompiles = {
            "builds_after_warmup": builds0,
            "builds_final": builds1,
            "never_restarted": never_restarted,
            "flat": all(builds1[nm] == builds0[nm]
                        for nm in never_restarted),
        }

        # -- fleet trace reconstruction -----------------------------
        # The subprocess router's telemetry dir (router_proc/) is
        # deliberately OUTSIDE the router/ + replica_* fleet layout:
        # a SIGKILLed incarnation's half-traces are the drill, not a
        # reconstruction defect. Replica-side rows from its traffic
        # still verify below.
        per_source = reqtrace.load_fleet_rows(base)
        fleet_tl = reqtrace.reconstruct_fleet(per_source)
        problems = reqtrace.verify_fleet(fleet_tl, per_source)
        chaos_hops = [
            h for tid, tl in fleet_tl.items() if tid.startswith("chaos")
            for h in tl["hops"] if h.get("outcome") == "failover"]
        chaos["failovers"] = len(chaos_hops)
        chaos["blast_ok"] = bool(chaos_hops) and all(
            h.get("replica") == victim for h in chaos_hops)
        trace = {
            "sources": sorted(per_source),
            "timelines": len(fleet_tl),
            "problems": problems[:10],
            "problem_count": len(problems),
        }
        return {"scaling": scaling, "chaos": chaos, "deploy": deploy,
                "restart": restart, "gray": gray,
                "recompiles": recompiles, "trace": trace,
                "fleet_dir": base}
    finally:
        import signal as _signal

        # The supervisor must stand down BEFORE the teardown SIGTERMs,
        # or it would dutifully resurrect everything we retire; it
        # also holds the freshest process handle for every respawned
        # slot.
        if supervisor is not None:
            supervisor.close()
            for nm, proc in supervisor.procs().items():
                procs[nm] = proc
        if router_proc is not None and router_proc.poll() is None:
            router_proc.send_signal(_signal.SIGTERM)
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)
        for proc in list(procs.values()) + (
                [router_proc] if router_proc is not None else []):
            try:
                proc.wait(timeout=120)
            except Exception:
                proc.kill()


def check_fleet(fleet: dict) -> int:
    """rc=1 on any violated --fleet contract (stderr)."""
    rc = 0
    scaling = fleet["scaling"]
    if scaling["scaling_x"] < 3.2:
        print(f"error: fleet scaling {scaling['scaling_x']}x at "
              f"N={scaling['replicas']} is below the 3.2x floor — the "
              "router is serializing replicas it should overlap "
              f"(N=1 {scaling['n1']['rps']} rps, "
              f"N={scaling['replicas']} {scaling['nN']['rps']} rps)",
              file=sys.stderr)
        rc = 1
    for lane in ("n1", "nN"):
        if scaling[lane]["errors"]:
            print(f"error: scaling lane {lane} failed requests: "
                  f"{scaling[lane]['errors'][:3]}", file=sys.stderr)
            rc = 1
    chaos = fleet["chaos"]
    if chaos["failed"]:
        print(f"error: chaos lane lost {chaos['failed']} request(s) to "
              f"a single replica kill (orbit={chaos['orbit']}, "
              f"single errors={chaos['single']['errors'][:3]}) — "
              "failover must be transparent", file=sys.stderr)
        rc = 1
    resur = chaos["resurrection"]
    if not resur["resurrected"]:
        print(f"error: the supervisor never resurrected the killed "
              f"replica {chaos['victim']} "
              f"(status={resur['supervisor']})", file=sys.stderr)
        rc = 1
    if not resur["victim_back_in_rotation"]:
        print(f"error: resurrected replica {chaos['victim']} never "
              "re-entered router rotation", file=sys.stderr)
        rc = 1
    if resur["load"]["errors"]:
        print(f"error: {len(resur['load']['errors'])} request(s) "
              "failed while serving through the resurrected replica: "
              f"{resur['load']['errors'][:3]}", file=sys.stderr)
        rc = 1
    if chaos["failovers"] < 1:
        print("error: chaos lane recorded no failover hops — the kill "
              "landed after all traffic drained, the drill proved "
              "nothing", file=sys.stderr)
        rc = 1
    if not chaos["blast_ok"]:
        print(f"error: a failover hop names a replica other than the "
              f"victim {chaos['victim']} — blast radius exceeded the "
              "killed replica", file=sys.stderr)
        rc = 1
    deploy = fleet["deploy"]
    if deploy["good"]["status"] != "deployed":
        print(f"error: good rolling deploy did not complete: "
              f"{deploy['good']}", file=sys.stderr)
        rc = 1
    if deploy["breaker_rollback"]["status"] != "rolled_back":
        print(f"error: corrupt-artifact deploy was not rolled back: "
              f"{deploy['breaker_rollback']}", file=sys.stderr)
        rc = 1
    if deploy["slo_rollback"]["status"] != "rolled_back":
        print(f"error: SLO-burned canary deploy was not rolled back: "
              f"{deploy['slo_rollback']}", file=sys.stderr)
        rc = 1
    if deploy["bg_errors"]:
        print(f"error: {len(deploy['bg_errors'])} request(s) failed "
              "during the rolling deploys — zero-downtime violated: "
              f"{deploy['bg_errors'][:3]}", file=sys.stderr)
        rc = 1
    want = deploy["v2"]
    wrong = {k: v for k, v in deploy["final_versions"].items()
             if v != want}
    if wrong:
        print(f"error: fleet did not converge on {want} after the "
              f"rollbacks: {wrong}", file=sys.stderr)
        rc = 1
    restart = fleet["restart"]
    if restart["load"]["errors"]:
        print(f"error: {len(restart['load']['errors'])} client "
              "request(s) failed across the router-process kill — "
              "retryable-error ride-through violated: "
              f"{restart['load']['errors'][:3]}", file=sys.stderr)
        rc = 1
    if restart["load"]["retries"] < 1:
        print("error: router-restart lane saw zero client retries — "
              "the kill landed after the load drained, the drill "
              "proved nothing", file=sys.stderr)
        rc = 1
    rec = restart["recovery"] or {}
    if int(rec.get("records") or 0) < 1:
        print(f"error: the respawned router replayed no journal "
              f"records (recovery={restart['recovery']}) — crash-safe "
              "restart unproven", file=sys.stderr)
        rc = 1
    if not restart["ring_digest_match"]:
        print(f"error: consistent-hash ring digest changed across the "
              f"router restart ({restart['ring_digest_before']} -> "
              f"{restart['ring_digest_after']}) — affinity pins are "
              "NOT bit-reproduced from zero recovered state",
              file=sys.stderr)
        rc = 1
    if restart["steady"]["errors"]:
        print(f"error: steady-state lane failed requests: "
              f"{restart['steady']['errors'][:3]}", file=sys.stderr)
        rc = 1
    gray = fleet["gray"]
    if not gray["respawned_slow"]:
        print(f"error: the gray lane's slow respawn of "
              f"{gray['slowpoke']} never came back", file=sys.stderr)
        rc = 1
    if gray["load"]["errors"]:
        print(f"error: {len(gray['load']['errors'])} request(s) "
              "failed with a slow replica in rotation: "
              f"{gray['load']['errors'][:3]}", file=sys.stderr)
        rc = 1
    if gray["p99_ratio"] > 2.0:
        print(f"error: fleet p99 with one slow replica is "
              f"{gray['p99_ratio']}x steady state "
              f"({gray['load']['p99_s']}s vs {gray['steady_p99_s']}s) "
              "— hedging/demotion failed to contain the gray failure "
              "(<= 2x required)", file=sys.stderr)
        rc = 1
    if gray["hedges"] < 1:
        print("error: gray lane recorded no hedged dispatches — the "
              "slow replica never stalled a request past the hedge "
              "delay, the drill proved nothing", file=sys.stderr)
        rc = 1
    recompiles = fleet["recompiles"]
    if not recompiles["flat"]:
        print(f"error: program-build counters moved on never-restarted "
              f"replicas (after warmup {recompiles['builds_after_warmup']}"
              f" -> final {recompiles['builds_final']}) — the gauntlet "
              "recompiled a warm replica", file=sys.stderr)
        rc = 1
    if fleet["trace"]["problem_count"]:
        print(f"error: {fleet['trace']['problem_count']} fleet trace "
              "reconstruction problem(s): "
              f"{fleet['trace']['problems'][:5]}", file=sys.stderr)
        rc = 1
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="tiny64")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--baseline-requests", type=int, default=6)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--sidelength", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--flush-timeout-ms", type=float, default=25.0)
    ap.add_argument("--hot-swap", action="store_true",
                    help="publish a new version mid-bench and assert a "
                         "zero-downtime, zero-recompile swap")
    ap.add_argument("--scheduler", choices=("step", "request"),
                    default="step",
                    help="service scheduler for the classic bench path "
                         "(default: the step-level stepper)")
    ap.add_argument("--continuous", action="store_true",
                    help="judged continuous-batching scenario: Poisson "
                         "arrivals with mixed step classes through the "
                         "stepper vs the PR 3 whole-request dispatcher "
                         "(same trace AND teacher-ladder deployment), "
                         "with the zero-recompile mixed-sweep assert")
    ap.add_argument("--cont-requests", type=int, default=128,
                    help="trace length; long enough that the steady "
                         "state, not the fixed ~one-teacher-ladder drain "
                         "tail after the last arrival, dominates the "
                         "measured window")
    ap.add_argument("--cont-rate", type=float, default=0.0,
                    help="Poisson arrival rate, requests/second "
                         "(0 = auto-calibrate to ~85%% of the measured "
                         "row-step capacity)")
    ap.add_argument("--cont-mix", default="4:0.8,64:0.12,256:0.08",
                    help="step-class mix 'steps:prob,...' (default: "
                         "mostly 4-step distilled requests with a tail "
                         "of 64/256-step legacy ones)")
    ap.add_argument("--cont-slo-ms", default="4:5000,64:20000,256:60000",
                    help="per-class latency SLO in ms (doubles as the "
                         "request deadline; 0 = none). Defaults give "
                         "each class ~10x its solo service time — tight "
                         "enough that one teacher-ladder scan ahead of "
                         "you (~20s+) blows the few-step SLO, loose "
                         "enough that knee-load ring waits don't")
    ap.add_argument("--cont-max-batch", type=int, default=16,
                    help="ring capacity (power of two). Sized so bursts "
                         "of long-ladder requests (~4 in flight at the "
                         "default mix/rate) cannot fill the ring and "
                         "starve few-step arrivals of slots — ring size "
                         "bounds CONCURRENCY, not throughput, under "
                         "processor sharing")
    ap.add_argument("--cont-seed", type=int, default=0)
    ap.add_argument("--trajectory", action="store_true",
                    help="judged trajectory-serving scenario: ring-"
                         "native orbit generation (device-resident "
                         "frame banks) vs a naive client loop issuing "
                         "one single-frame request per frame, on the "
                         "same deterministic orbit trace, with zero-"
                         "recompile and delivery asserts (rc=1)")
    ap.add_argument("--traj-orbits", type=int, default=1,
                    help="orbits in flight per rep (default 1: the "
                         "interactive single-client regime where per-"
                         "frame admission dominates; under saturated "
                         "concurrency the ratio compresses — see "
                         "trajectory_bench docstring)")
    ap.add_argument("--traj-frames", type=int, default=8,
                    help="frames per orbit")
    ap.add_argument("--traj-steps", type=int, default=1,
                    help="denoise steps per frame (default 1: the "
                         "progressive-distillation endpoint — the "
                         "few-step serving regime this feature targets)")
    ap.add_argument("--traj-reps", type=int, default=3,
                    help="times the trace replays per lane (longer "
                         "window, stabler frames/s)")
    ap.add_argument("--traj-flush-ms", type=float, default=50.0,
                    help="serve.flush_timeout_ms for BOTH lanes: the "
                         "batch-formation window a throughput-tuned "
                         "service holds admissions open for. The ring "
                         "lane pays it once per orbit, the naive loop "
                         "once per frame — the admission cost the "
                         "device-resident path removes")
    ap.add_argument("--traj-k-max", type=int, default=4,
                    help="frame-bank capacity (serve.k_max) for the "
                         "ring lane")
    ap.add_argument("--traj-max-batch", type=int, default=8,
                    help="ring capacity for both lanes")
    ap.add_argument("--traj-riders", type=int, default=4,
                    help="single-shot requests in the untimed mixed "
                         "phase (the mixed-traffic zero-recompile "
                         "assert)")
    ap.add_argument("--cond-cache", action="store_true",
                    help="judged conditioning-cache scenario: one "
                         "calibrated mixed single-shot + trajectory "
                         "Poisson trace replayed against serve."
                         "cond_cache off vs on (same weights, same "
                         "config otherwise), asserting full delivery, "
                         "zero warm recompiles on BOTH lanes, and >= "
                         "1.3x delivered row-steps/s (rc=1 on "
                         "violation); the artifact also carries the "
                         "fused serving-attention coverage table")
    ap.add_argument("--cc-requests", type=int, default=14,
                    help="arrivals in the --cond-cache trace (both "
                         "lanes replay it)")
    ap.add_argument("--cc-steps", type=int, default=24,
                    help="denoise steps per request: long enough that "
                         "the one-time admission encode amortizes "
                         "(short requests re-pay it and understate the "
                         "steady-state win)")
    ap.add_argument("--cc-orbit-every", type=int, default=7,
                    help="every Nth arrival is an orbit (0 = singles "
                         "only)")
    ap.add_argument("--cc-frames", type=int, default=3,
                    help="frames per --cond-cache orbit")
    ap.add_argument("--cc-k-max", type=int, default=3,
                    help="frame-bank capacity (serve.k_max) both lanes")
    ap.add_argument("--cc-max-batch", type=int, default=4,
                    help="ring capacity both lanes")
    ap.add_argument("--cc-emb-ch", type=int, default=256,
                    help="model.emb_ch override for the bench backbone: "
                         "sized so the conditioning branch is a "
                         "production-shaped ~25%%+ of step time (tiny "
                         "CPU stand-ins undersize it)")
    ap.add_argument("--cc-sidelength", type=int, default=32,
                    help="image sidelength for the --cond-cache "
                         "backbone (its own lane; not --sidelength)")
    ap.add_argument("--cc-util", type=float, default=3.5,
                    help="arrival-rate target as a multiple of the "
                         "cache-OFF lane's measured solo row-step "
                         "capacity. Deliberately > 1: the A/B question "
                         "is capacity, so the trace must saturate BOTH "
                         "lanes — an arrival-bound replay measures the "
                         "trace's rate, not the cache's")
    ap.add_argument("--cc-rate", type=float, default=0.0,
                    help="explicit Poisson arrival rate, requests/s "
                         "(0 = auto-calibrate via --cc-util)")
    ap.add_argument("--cc-seed", type=int, default=0)
    ap.add_argument("--precision-sweep", action="store_true",
                    help="judged precision/fused-step scenario: one "
                         "Poisson trace replayed against f32-unfused, "
                         "f32-fused, bf16-fused, and int8-fused "
                         "services, with per-precision PSNR probes and "
                         "zero-recompile asserts (rc=1 on violation)")
    ap.add_argument("--sweep-requests", type=int, default=40,
                    help="trace length for --precision-sweep (4 lanes "
                         "replay it, so it is sized below --cont-requests)")
    ap.add_argument("--sweep-mix", default="4:0.85,16:0.15",
                    help="step-class mix for --precision-sweep")
    ap.add_argument("--sweep-slo-ms", default="4:8000,16:30000",
                    help="per-class SLO/deadline ms for --precision-sweep")
    ap.add_argument("--chaos", action="store_true",
                    help="judged survivability scenario: the calibrated "
                         "Poisson trace replayed 4x against ONE stepper "
                         "service — clean, with an injected ring-carry "
                         "NaN, with an injected worker death, and with "
                         "an injected registry swap failure — asserting "
                         "every non-poisoned request is served within "
                         "SLO with zero recompiles (rc=1 on violation)")
    ap.add_argument("--chaos-requests", type=int, default=20,
                    help="trace length PER PHASE (4 phases replay it)")
    ap.add_argument("--chaos-rate", type=float, default=0.0,
                    help="Poisson arrival rate, requests/second (0 = "
                         "auto-calibrate to ~60%% of the measured "
                         "row-step capacity — headroom on purpose: this "
                         "lane judges survivability, --continuous owns "
                         "the knee)")
    ap.add_argument("--chaos-mix", default="4:0.85,16:0.15",
                    help="step-class mix for --chaos")
    ap.add_argument("--chaos-slo-ms", default="4:8000,16:30000",
                    help="per-class SLO/deadline ms for --chaos")
    ap.add_argument("--chaos-max-batch", type=int, default=8,
                    help="ring capacity for --chaos (also the worker-"
                         "death blast-radius bound the check asserts)")
    ap.add_argument("--chaos-seed", type=int, default=0)
    ap.add_argument("--fleet", action="store_true",
                    help="judged fleet-serving scenario: N replica "
                         "PROCESSES behind the FleetRouter — scaling "
                         "(>= 3.2x RPS at N=4 vs N=1 over step-floor-"
                         "paced replicas), chaos (SIGKILL the replica "
                         "holding a mid-flight orbit, zero failed "
                         "requests, blast radius = the victim, then "
                         "supervised RESURRECTION of the victim into "
                         "the same spec/port under load), three "
                         "scripted rolling deploys (good / corrupt-"
                         "artifact breaker rollback / SLO-burned "
                         "canary rollback) under live load, a router-"
                         "PROCESS SIGKILL mid-load (clients ride the "
                         "restart on retryable errors, the journal "
                         "replays, the consistent-hash ring digest is "
                         "bit-identical across incarnations), a gray-"
                         "failure drill (one replica respawned SLOW; "
                         "hedging + p99 demotion keep fleet p99 <= 2x "
                         "steady state), a zero-recompile audit on "
                         "never-restarted replicas, and a cross-"
                         "replica trace reconstruction audit (rc=1 on "
                         "any violation)")
    ap.add_argument("--fleet-replicas", type=int, default=4,
                    help="replica process count for --fleet")
    ap.add_argument("--fleet-requests", type=int, default=12,
                    help="closed-loop requests PER REPLICA-EQUIVALENT "
                         "in the scaling lane (N=1 runs this many, "
                         "N=k runs k times as many)")
    ap.add_argument("--fleet-concurrency", type=int, default=8,
                    help="closed-loop client threads through the router")
    ap.add_argument("--fleet-floor-ms", type=float, default=200.0,
                    help="serve.step_floor_ms per replica: the paced "
                         "device-time floor that makes 1-host fleet "
                         "scaling honest (must exceed N x the tiny "
                         "model's actual CPU step so replicas overlap "
                         "in their sleep windows)")
    ap.add_argument("--fleet-frames", type=int, default=6,
                    help="orbit length for the chaos-lane trajectory")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet scratch dir (default "
                         "/tmp/nvs3d_fleet_bench; wiped on start)")
    ap.add_argument("--fleet-spawn-timeout-s", type=float, default=300.0,
                    help="per-replica ready-file timeout")
    ap.add_argument("--reqtrace", action="store_true",
                    help="judged request-tracing scenario: one mixed "
                         "single-shot + trajectory trace replayed with "
                         "instrumentation off vs on (JSONL + spans + "
                         "SLO engine), asserting every completed "
                         "request reconstructs from telemetry.jsonl, "
                         "zero recompiles, and tracing overhead within "
                         "NVS3D_REQTRACE_OVERHEAD_PCT (default 2%%) "
                         "(rc=1 on violation)")
    ap.add_argument("--rt-requests", type=int, default=16,
                    help="single-shot requests in the --reqtrace trace")
    ap.add_argument("--rt-orbits", type=int, default=2,
                    help="trajectory orbits in the --reqtrace trace")
    ap.add_argument("--rt-frames", type=int, default=3,
                    help="frames per --reqtrace orbit")
    ap.add_argument("--rt-k-max", type=int, default=4,
                    help="frame-bank capacity for --reqtrace")
    ap.add_argument("--mixed-res", action="store_true",
                    help="judged mixed-resolution serving scenario (the "
                         "train.ladder serving counterpart): ONE fully-"
                         "convolutional param tree served at every rung "
                         "resolution side by side — each resolution's "
                         "bucket family is warmed, then one interleaved "
                         "mixed-resolution trace replays through the "
                         "warm services, asserting zero new sampler "
                         "compilations in every lane (rc=1 + compile-"
                         "ledger culprit on violation)")
    ap.add_argument("--mr-sidelengths", default="64,128",
                    help="comma list of >= 2 rung resolutions to serve "
                         "concurrently (default: the canonical 64,128 "
                         "ladder; use smaller values on CPU smoke runs)")
    ap.add_argument("--mr-requests", type=int, default=24,
                    help="interleaved mixed-resolution trace length")
    ap.add_argument("--mr-steps", type=int, default=4,
                    help="denoise steps per request for --mixed-res")
    ap.add_argument("--mr-max-batch", type=int, default=4,
                    help="ring capacity per resolution lane")
    ap.add_argument("--mr-seed", type=int, default=0,
                    help="shuffle seed for the interleaved trace")
    ap.add_argument("--precision", default=None,
                    choices=("float32", "bfloat16", "int8"),
                    help="serve.precision for the classic bench path")
    ap.add_argument("--fused-step", default=None,
                    choices=("auto", "on", "off"),
                    help="diffusion.fused_step for the classic bench path")
    ap.add_argument("--teacher-steps", type=int, default=256,
                    help="step count of the pre-distillation teacher "
                         "(the PR 3 deployment baseline serves everything "
                         "at this ladder)")
    ap.add_argument("--cont-baseline-requests", type=int, default=6,
                    help="trace prefix length for the capacity-bound "
                         "teacher-ladder baseline")
    args = ap.parse_args()

    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    if args.mixed_res:
        # Its own per-resolution builds happen inside (one service per
        # rung resolution over one shared param tree).
        mr = mixed_res_bench(args)
        result = {
            "metric": f"serve_mixed_res_rps_{args.preset}",
            "value": mr["rps"],
            "unit": "req/s",
            "sidelengths": mr["sidelengths"],
            "mixed_res": mr,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_mixed_res(mr)

    if args.fleet:
        # Its own light-backbone build happens inside (the parent only
        # supplies conds + the published v1 params; the replicas are
        # separate processes with their own JAX runtimes).
        fleet = fleet_bench(args)
        result = {
            "metric": f"serve_fleet_rps_{args.preset}",
            "value": fleet["scaling"]["nN"]["rps"],
            "unit": "req/s",
            "vs_baseline": fleet["scaling"]["scaling_x"],
            "baseline_value": fleet["scaling"]["n1"]["rps"],
            "baseline": ("same router, same closed-loop clients, one "
                         "replica in rotation (quiesced fleet)"),
            "sidelength": args.sidelength,
            "fleet": fleet,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_fleet(fleet)

    cfg, model, params, conds = build(args.preset, args.sidelength,
                                      args.steps)

    if args.trajectory:
        # Same light backbone as --continuous (its own metric lane);
        # full-depth timesteps so any per-frame step count fits.
        cfg, model, params, conds = build(
            args.preset, args.sidelength, args.steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", [8]),
                             ("diffusion.sample_timesteps",
                              get_default_timesteps(args.preset))])
        traj = trajectory_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_trajectory_fps_{args.preset}",
            "value": traj["fps_ring"],
            "unit": "frames/s",
            "vs_baseline": traj["ring_vs_naive"],
            "baseline_value": traj["fps_naive"],
            "baseline": ("naive client loop: one single-frame request "
                         "per orbit frame (frame i conditioned on frame "
                         "i-1 client-side), same deterministic trace"),
            "sidelength": args.sidelength,
            "precision": cfg.serve.precision,
            "fused_step": cfg.diffusion.fused_step,
            "trajectory": traj,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_trajectory(traj)

    if args.cond_cache:
        # Its own backbone (its own metric lane): attention OFF and
        # emb_ch raised so the conditioning branch carries a
        # production-shaped fraction of step time (see the
        # cond_cache_bench docstring); full-depth timesteps so
        # --cc-steps fits.
        cfg, model, params, conds = build(
            args.preset, args.cc_sidelength, args.cc_steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", []),
                             ("model.ch_mult", [1, 1]),
                             ("model.emb_ch", args.cc_emb_ch),
                             ("diffusion.sample_timesteps",
                              get_default_timesteps(args.preset))])
        cc = cond_cache_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_cond_cache_rowsteps_{args.preset}",
            "value": cc["on"]["row_steps_per_sec"],
            "unit": "row-steps/s",
            "vs_baseline": cc["speedup"],
            "baseline_value": cc["off"]["row_steps_per_sec"],
            "baseline": ("same trace, serve.cond_cache=false — every "
                         "ring step re-encodes the conditioning branch "
                         "in-program for every row"),
            "sidelength": args.cc_sidelength,
            "precision": cfg.serve.precision,
            "fused_step": cfg.diffusion.fused_step,
            "cond_cache": cc,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        artifact_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "results", "serve_r18")
        os.makedirs(artifact_dir, exist_ok=True)
        with open(os.path.join(artifact_dir, "cond_cache.json"),
                  "w") as fh:
            json.dump(result, fh, indent=2)
        return check_cond_cache(cc)

    if args.reqtrace:
        # Same light backbone as --continuous (its own metric lane).
        cfg, model, params, conds = build(
            args.preset, args.sidelength, args.steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", [8])])
        rt = reqtrace_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_reqtrace_rps_{args.preset}",
            "value": rt["on"]["rps"],
            "unit": "req/s",
            "vs_baseline": round(
                rt["on"]["rps"] / max(rt["off"]["rps"], 1e-9), 3),
            "baseline_value": rt["off"]["rps"],
            "baseline": "same trace, obs.enabled=false (no spans, no "
                        "JSONL — the instrumentation-off deployment)",
            "overhead_pct": rt["overhead_pct"],
            "sidelength": args.sidelength,
            "precision": cfg.serve.precision,
            "fused_step": cfg.diffusion.fused_step,
            "reqtrace": rt,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_reqtrace(rt)

    if args.chaos:
        # Same light backbone as --continuous (its own metric lane);
        # full-depth timesteps so every step class in the mix fits.
        cfg, model, params, conds = build(
            args.preset, args.sidelength, args.steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", [8]),
                             ("diffusion.sample_timesteps",
                              get_default_timesteps(args.preset))])
        chaos = chaos_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_chaos_{args.preset}",
            # Headline: worst fault-phase p99 vs the same trace's clean
            # p99 — the latency cost of surviving a fault.
            "value": chaos["p99_worst_fault_s"],
            "unit": "s",
            "vs_baseline": round(
                chaos["p99_worst_fault_s"]
                / max(chaos["p99_steady_s"], 1e-9), 3),
            "baseline_value": chaos["p99_steady_s"],
            "baseline": "same Poisson trace, no fault armed (the "
                        "steady phase)",
            "sidelength": args.sidelength,
            "precision": cfg.serve.precision,
            "fused_step": cfg.diffusion.fused_step,
            "chaos": chaos,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_chaos(chaos)

    if args.precision_sweep:
        # Same light backbone as --continuous (a separate metric lane,
        # never compared to the classic serve_rps numbers); full-depth
        # timesteps so every step class in the mix fits.
        cfg, model, params, conds = build(
            args.preset, args.sidelength, args.steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", [8]),
                             ("diffusion.sample_timesteps",
                              get_default_timesteps(args.preset))])
        sweep = precision_sweep_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_precision_sweep_{args.preset}",
            "value": sweep["rps_bf16_fused"],
            "unit": "req/s",
            "precision": "bfloat16",
            "fused_step": True,
            "vs_baseline": sweep["bf16_vs_f32_rps"],
            "baseline_value": sweep["rps_f32_unfused"],
            "baseline": "same trace, serve.precision=float32, "
                        "diffusion.fused_step=False",
            "sidelength": args.sidelength,
            "precision_sweep": sweep,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        return check_precision_sweep(sweep)

    if args.continuous:
        # The continuous scenario runs its own model variant: the preset
        # block with a LIGHT backbone (1 res-block, attention at the
        # bottleneck only) so a 256-step teacher request costs seconds,
        # not half a minute, on the 1-core CI host — its trajectory is a
        # separate metric (serve_continuous_rps_*), never compared to
        # the classic serve_rps numbers. Full-depth timesteps (the
        # preset's) so every step class up to the teacher ladder fits.
        cfg, model, params, conds = build(
            args.preset, args.sidelength, args.steps,
            extra_overrides=[("model.num_res_blocks", 1),
                             ("model.attn_resolutions", [8]),
                             ("diffusion.sample_timesteps",
                              get_default_timesteps(args.preset))])
        cont = continuous_bench(model, params, cfg, conds, args)
        result = {
            "metric": f"serve_continuous_rps_{args.preset}",
            "value": cont["stepper"]["rps_served"],
            "unit": "req/s",
            "rps_goodput": cont["stepper"]["rps_goodput"],
            "vs_baseline": cont["vs_pr3_few_step_serving"],
            "baseline_value": cont["pr3_teacher_steps"]["rps_served"],
            "baseline": ("PR 3 deployment: whole-request dispatcher, "
                         "every request at the "
                         f"{args.teacher_steps}-step teacher ladder "
                         "(pre-distillation serving)"),
            "vs_whole_request_same_trace":
                cont["vs_whole_request_same_trace"],
            "sidelength": args.sidelength,
            "precision": cfg.serve.precision,
            "fused_step": cfg.diffusion.fused_step,
            "continuous": cont,
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        sweep_delta = cont["stepper"]["programs_built_delta"]
        if sweep_delta or cont["stepper"]["jit_cache_entries_delta"]:
            print("error: the mixed-step trace compiled "
                  f"{sweep_delta} new stepper program(s) — the stepper "
                  "program cache must be keyed on bucket/shape only "
                  "(steps/t/w are device arguments)", file=sys.stderr)
            print_recompile_culprit()
            return 1
        return 0

    scfg = ServeConfig(scheduler=args.scheduler, max_batch=args.max_batch,
                       flush_timeout_ms=args.flush_timeout_ms,
                       queue_depth=max(64, 2 * args.requests),
                       precision=args.precision or "float32",
                       results_folder="/tmp/nvs3d_serve_bench")
    dcfg = cfg.diffusion
    if args.fused_step is not None:
        import dataclasses as _dc
        dcfg = _dc.replace(
            cfg.diffusion,
            fused_step={"auto": "auto", "on": True,
                        "off": False}[args.fused_step])
    buckets = []
    b = 1
    while b <= args.max_batch:
        buckets.append(b)
        b *= 2
    if len(buckets) < 3:
        raise SystemExit("--max-batch must be >= 4 so the warm sweep "
                         "covers >= 3 bucket sizes")

    service = SamplingService(model, params, dcfg, scfg)
    try:
        warm_service(service, conds, buckets)

        # Warm sequential floor (batch-1 program, no coalescing): the
        # transparency number that isolates program-reuse from batching.
        t0 = time.perf_counter()
        for i in range(4):
            service.submit(conds[i % len(conds)], seed=200 + i
                           ).result(timeout=600)
        warm_seq = (time.perf_counter() - t0) / 4

        rps = bench_service(service, conds, args.requests, args.concurrency)
        sweep = mixed_size_sweep(service, conds, buckets)
        hot_swap = None
        if args.hot_swap:
            hot_swap = hot_swap_bench(service, conds, params,
                                      args.concurrency,
                                      per_phase=args.requests)
        base_rps = bench_baseline(cfg, model, params, conds,
                                  args.baseline_requests)
        stats = service.stats
        result = {
            "metric": f"serve_rps_{args.preset}",
            "value": round(rps, 3),
            "unit": "req/s",
            "vs_baseline": round(rps / base_rps, 3),
            "baseline_value": round(base_rps, 3),
            "baseline": "one-shot sequential path: fresh make_sampler jit "
                        "closure per request, batch 1, persistent compile "
                        "cache warm",
            "warm_sequential_sec_per_req": round(warm_seq, 4),
            "concurrency": args.concurrency,
            "requests": args.requests,
            "sample_steps": args.steps,
            "sidelength": args.sidelength,
            "precision": scfg.precision,
            "fused_step": service.summary()["fused_step"],
            "buckets": buckets,
            "queue_wait": stats.span_summary("queue_wait"),
            "device": stats.span_summary("device"),
            "compile": stats.span_summary("compile"),
            "mixed_size_sweep": sweep,
            "compile_counters": service.compile_counters(),
            "platform": jax.default_backend(),
        }
        if hot_swap is not None:
            result["hot_swap"] = hot_swap
        print(json.dumps(result))
        if (sweep["programs_built_delta"] != 0
                or sweep["jit_cache_entries_delta"] != 0):
            print("error: warm mixed-size sweep triggered new sampler "
                  f"compilations ({sweep}) — the program cache is not "
                  "holding its zero-recompile contract", file=sys.stderr)
            print_recompile_culprit()
            return 1
        return 0
    finally:
        service.stop()


if __name__ == "__main__":
    sys.exit(main())
