"""Serving-throughput bench: micro-batched sampling service vs the
one-shot sequential baseline. CPU-runnable — the first hardware-
independent perf number in the BENCH trajectory.

Prints ONE JSON line:

  {"metric": "serve_rps_<preset>", "value": <requests/sec>,
   "vs_baseline": <x>, "baseline_value": <requests/sec>, ...}

`vs_baseline` compares against the status-quo serving path this PR
replaces: per request, a FRESH `make_sampler` jit closure built and
called sequentially at batch 1 — exactly what `nvs3d sample` does per
invocation (every request re-traces; the persistent compilation cache,
which the baseline is given too, spares it the full XLA compile). The
service side answers from its warm sampler-program cache and coalesces
concurrent requests into padded power-of-two buckets.

`warm_sequential_sec_per_req` is reported for transparency: on a 1-core
CPU host batching itself is roughly throughput-neutral (the chip is
saturated at batch 1) and the win is program reuse; on accelerators with
idle MXU headroom the batching term multiplies in.

The run also performs a warm MIXED-SIZE sweep across >= 3 bucket sizes
and asserts zero new sampler compilations (from the program cache's jit
counters) — the "warm traffic never recompiles" contract. A violation
exits rc=1.

Usage:
  python tools/serve_bench.py [--preset tiny64] [--concurrency 8]
      [--requests 16] [--steps 4] [--sidelength 16] [--max-batch 4]

`--sidelength` downsizes the preset's image for bench runtime (the
tiny64 model is resolution-free; 16 px keeps the CPU run under ~2 min).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._common import init_jax_env  # noqa: E402

init_jax_env()

# Like bench.py, the persistent compile cache is ON by default at the
# repo-local path (env wins): it keeps bench re-runs warm AND gives the
# one-shot baseline the same compile-cache benefit the CLI now has —
# the reported vs_baseline is program-reuse + batching, not cold compiles.
from novel_view_synthesis_3d_tpu.utils.xla_cache import (  # noqa: E402
    setup_compilation_cache)

setup_compilation_cache(
    default_dir=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"),
    min_entry_bytes=0)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def build(preset: str, sidelength: int, steps: int):
    from novel_view_synthesis_3d_tpu.config import get_preset
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet

    cfg = get_preset(preset).override(**{
        "data.img_sidelength": sidelength,
        "diffusion.sample_timesteps": steps,
    }).validate()
    model = XUNet(cfg.model)
    batch = make_example_batch(batch_size=8, sidelength=sidelength, seed=0)
    mb = {
        "x": jnp.asarray(batch["x"]), "z": jnp.asarray(batch["target"]),
        "logsnr": jnp.zeros((batch["x"].shape[0],)),
        "R1": jnp.asarray(batch["R1"]), "t1": jnp.asarray(batch["t1"]),
        "R2": jnp.asarray(batch["R2"]), "t2": jnp.asarray(batch["t2"]),
        "K": jnp.asarray(batch["K"]),
    }
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        mb, cond_mask=jnp.ones((batch["x"].shape[0],)), train=False)["params"]
    params = jax.device_put(params, jax.devices()[0])
    conds = [{k: np.asarray(mb[k])[i % mb["x"].shape[0]]
              for k in ("x", "R1", "t1", "R2", "t2", "K")}
             for i in range(max(8, mb["x"].shape[0]))]
    return cfg, model, params, conds


def bench_baseline(cfg, model, params, conds, n_requests: int) -> float:
    """Sequential one-shot path: fresh jit closure per request, batch 1.

    One untimed cold run populates the persistent compilation cache
    first, so the baseline pays retrace + cache hit per request — the
    best the old path can do — not the one-time cold compile."""
    from novel_view_synthesis_3d_tpu.diffusion.schedules import (
        sampling_schedule)
    from novel_view_synthesis_3d_tpu.sample.ddpm import make_sampler

    dcfg = cfg.diffusion
    steps = dcfg.sample_timesteps

    def one_shot(i: int):
        sampler = make_sampler(model, sampling_schedule(dcfg, steps), dcfg)
        cond = {k: jnp.asarray(v)[None]
                for k, v in conds[i % len(conds)].items()}
        return np.asarray(jax.device_get(
            sampler(params, jax.random.PRNGKey(i), cond)))

    one_shot(0)  # untimed: populates the persistent compile cache
    t0 = time.perf_counter()
    for i in range(n_requests):
        one_shot(i + 1)
    return n_requests / (time.perf_counter() - t0)


def warm_service(service, conds, buckets) -> None:
    """Compile each bucket's program once (group sizes = bucket sizes)."""
    seed = 10_000
    for b in buckets:
        tickets = [service.submit(conds[j % len(conds)], seed=seed + j)
                   for j in range(b)]
        seed += b
        for t in tickets:
            t.result(timeout=600)


def bench_service(service, conds, n_requests: int,
                  concurrency: int) -> float:
    """Closed-loop load: `concurrency` submitter threads, wall-clock RPS."""
    per_thread = max(1, n_requests // concurrency)
    total = per_thread * concurrency
    errors = []

    def client(tid: int):
        for j in range(per_thread):
            try:
                service.submit(conds[(tid + j) % len(conds)],
                               seed=1000 + tid * per_thread + j
                               ).result(timeout=600)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise SystemExit(f"serve_bench: {len(errors)} request(s) failed; "
                         f"first: {errors[0]!r}")
    return total / elapsed


def mixed_size_sweep(service, conds, buckets) -> dict:
    """Warm sweep across every bucket size; returns the compile-counter
    delta (must be zero — warm traffic never recompiles)."""
    before = service.compile_counters()
    seed = 50_000
    # Group sizes that land in each bucket, including non-power-of-two
    # groups that PAD up (3 -> bucket 4).
    sizes = sorted(set(
        list(buckets) + [b - 1 for b in buckets if b - 1 >= 1]))
    for n in sizes:
        tickets = [service.submit(conds[j % len(conds)], seed=seed + j)
                   for j in range(n)]
        seed += n
        for t in tickets:
            t.result(timeout=600)
    after = service.compile_counters()
    return {
        "swept_group_sizes": sizes,
        "programs_built_delta": after["programs_built"]
        - before["programs_built"],
        "jit_cache_entries_delta": after["jit_cache_entries"]
        - before["jit_cache_entries"],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="tiny64")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--baseline-requests", type=int, default=6)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--sidelength", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--flush-timeout-ms", type=float, default=25.0)
    args = ap.parse_args()

    from novel_view_synthesis_3d_tpu.config import ServeConfig
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService

    cfg, model, params, conds = build(args.preset, args.sidelength,
                                      args.steps)
    scfg = ServeConfig(max_batch=args.max_batch,
                       flush_timeout_ms=args.flush_timeout_ms,
                       queue_depth=max(64, 2 * args.requests),
                       results_folder="/tmp/nvs3d_serve_bench")
    buckets = []
    b = 1
    while b <= args.max_batch:
        buckets.append(b)
        b *= 2
    if len(buckets) < 3:
        raise SystemExit("--max-batch must be >= 4 so the warm sweep "
                         "covers >= 3 bucket sizes")

    service = SamplingService(model, params, cfg.diffusion, scfg)
    try:
        warm_service(service, conds, buckets)

        # Warm sequential floor (batch-1 program, no coalescing): the
        # transparency number that isolates program-reuse from batching.
        t0 = time.perf_counter()
        for i in range(4):
            service.submit(conds[i % len(conds)], seed=200 + i
                           ).result(timeout=600)
        warm_seq = (time.perf_counter() - t0) / 4

        rps = bench_service(service, conds, args.requests, args.concurrency)
        sweep = mixed_size_sweep(service, conds, buckets)
        base_rps = bench_baseline(cfg, model, params, conds,
                                  args.baseline_requests)
        stats = service.stats
        result = {
            "metric": f"serve_rps_{args.preset}",
            "value": round(rps, 3),
            "unit": "req/s",
            "vs_baseline": round(rps / base_rps, 3),
            "baseline_value": round(base_rps, 3),
            "baseline": "one-shot sequential path: fresh make_sampler jit "
                        "closure per request, batch 1, persistent compile "
                        "cache warm",
            "warm_sequential_sec_per_req": round(warm_seq, 4),
            "concurrency": args.concurrency,
            "requests": args.requests,
            "sample_steps": args.steps,
            "sidelength": args.sidelength,
            "buckets": buckets,
            "queue_wait": stats.span_summary("queue_wait"),
            "device": stats.span_summary("device"),
            "compile": stats.span_summary("compile"),
            "mixed_size_sweep": sweep,
            "compile_counters": service.compile_counters(),
            "platform": jax.default_backend(),
        }
        print(json.dumps(result))
        if (sweep["programs_built_delta"] != 0
                or sweep["jit_cache_entries_delta"] != 0):
            print("error: warm mixed-size sweep triggered new sampler "
                  f"compilations ({sweep}) — the program cache is not "
                  "holding its zero-recompile contract", file=sys.stderr)
            return 1
        return 0
    finally:
        service.stop()


if __name__ == "__main__":
    sys.exit(main())
