"""Copy-baseline floor analysis for a quality run (VERDICT r4 item 2).

Quantifies the two no-synthesis baselines every held-out PSNR number must
be judged against, on the run's OWN train/val split:

  - mean-image: predict the per-instance MEAN of the train views for every
    held-out view. The "pose-ignoring" floor — a model scoring here learned
    nothing view-dependent.
  - nearest-pose: predict the train view whose camera direction is closest
    to the target's. The "copy, don't synthesize" bar — a model must beat
    this for its conditioning to be doing more than retrieval.

Reads the model's per_view_psnr from eval_single.json (alignment identical
to tools/pose_generalization.py: per instance, k consecutive cond views
from cond_view, targets = remaining views in index order) and reports
model-vs-floor margins per view and in aggregate.

Usage:
    python tools/quality_floor.py <quality_out_dir> [eval_single.json]
Writes <dir>/floor_analysis.json and prints one JSON summary line.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pose_generalization import angular_deg, cam_dir  # noqa: E402


def _psnr(pred: np.ndarray, target: np.ndarray) -> float:
    mse = float(np.mean(np.square(pred - target)))
    return 10.0 * np.log10(4.0 / max(mse, 1e-20))  # data_range 2 ([-1,1])


def main() -> int:
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    out_dir = sys.argv[1]
    eval_json = (sys.argv[2] if len(sys.argv) > 2
                 else os.path.join(out_dir, "eval_single.json"))

    from novel_view_synthesis_3d_tpu.config import Config
    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset

    with open(eval_json) as fh:
        ev = json.load(fh)
    with open(os.path.join(out_dir, "work", "config.json")) as fh:
        cfg = Config.from_json(fh.read())
    per_psnr = np.asarray(ev["per_view_psnr"], np.float64)

    side = cfg.data.img_sidelength
    val = SRNDataset(os.path.join(out_dir, "work", "val"),
                     img_sidelength=side)
    train = SRNDataset(os.path.join(out_dir, "work", "train"),
                       img_sidelength=side)
    by_name = {os.path.basename(os.path.normpath(t.instance_dir)): t
               for t in train.instances}

    # Same deterministic pair ordering as the eval that produced per_psnr.
    k = cfg.model.num_cond_frames
    cond_view = ev.get("cond_view", 0)
    n_inst = min(ev.get("num_instances") or len(val.instances),
                 len(val.instances))
    vpi = ev.get("views_per_instance")
    if vpi is None:
        if len(per_psnr) % len(val.instances) != 0:
            raise SystemExit("eval JSON lacks protocol fields and views "
                             "don't divide evenly — re-run eval --out")
        vpi = len(per_psnr) // len(val.instances)

    rows = []
    idx = 0
    for i in range(n_inst):
        inst = val.instances[i]
        name = os.path.basename(os.path.normpath(inst.instance_dir))
        if name not in by_name:
            raise SystemExit(
                f"val instance {name!r} has no counterpart in the train "
                "tree: the floor baselines (per-instance mean image, "
                "nearest-pose train view) are only defined for PER-VIEW "
                "splits where every instance appears in both trees (e.g. "
                "quality_run's split-object layout). A per-instance split "
                "cannot be floor-analyzed with this tool.")
        tr = by_name[name]
        tr_views = [tr.view(v) for v in range(len(tr))]
        mean_img = np.mean([img for img, _ in tr_views], axis=0)
        tr_dirs = [cam_dir(pose) for _, pose in tr_views]
        cond_idx = [(cond_view + j) % len(inst) for j in range(k)]
        others = [v for v in range(len(inst)) if v not in cond_idx]
        for v in others[:vpi]:
            target_img, target_pose = inst.view(v)
            tdir = cam_dir(target_pose)
            dists = [angular_deg(tdir, d) for d in tr_dirs]
            nearest = int(np.argmin(dists))
            rows.append({
                "instance": name, "view": v,
                "model_psnr": float(per_psnr[idx]),
                "mean_image_psnr": _psnr(mean_img, target_img),
                "nearest_pose_psnr": _psnr(tr_views[nearest][0], target_img),
                "nearest_train_deg": float(dists[nearest]),
            })
            idx += 1
    if idx != len(per_psnr):
        raise SystemExit(f"pair alignment failed: {idx} reconstructed vs "
                         f"{len(per_psnr)} per_view_psnr entries")

    model = np.array([r["model_psnr"] for r in rows])
    mean_fl = np.array([r["mean_image_psnr"] for r in rows])
    near_fl = np.array([r["nearest_pose_psnr"] for r in rows])
    summary = {
        "metric": "quality_floor_analysis",
        "num_views": len(rows),
        "model_psnr_mean": round(float(model.mean()), 3),
        "mean_image_floor_psnr": round(float(mean_fl.mean()), 3),
        "nearest_pose_floor_psnr": round(float(near_fl.mean()), 3),
        "model_minus_mean_floor_db": round(float((model - mean_fl).mean()),
                                           3),
        "model_minus_nearest_floor_db": round(
            float((model - near_fl).mean()), 3),
        "views_beating_mean_floor": int((model > mean_fl).sum()),
        "views_beating_nearest_floor": int((model > near_fl).sum()),
        "interpretation": (
            "model > nearest-pose floor on most views = genuine synthesis; "
            "model ~ mean-image floor = pose-ignoring; between the two = "
            "retrieval-grade conditioning"),
    }
    with open(os.path.join(out_dir, "floor_analysis.json"), "w") as fh:
        json.dump({"summary": summary, "per_view": rows}, fh, indent=1)
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
