"""Compare samplers (ddpm / ddim / dpm++) on one trained checkpoint.

Evaluates each (sampler, step-count) pair on the SAME held-out views with
the SAME PRNG seed and reports PSNR/SSIM plus wall-clock sec/view, so the
"dpm++ at ~1/8 the steps matches many-step ancestral quality" claim is a
measured table instead of a citation. The reference repo has nothing like
this (its sampling.py displays images and computes nothing).

Usage:
  python tools/sampler_comparison.py DATA_ROOT OUT.json \
      [--preset tiny64] [--num-instances 8] [--views-per-instance 2] \
      [key=value config overrides ...]

The checkpoint is read from the preset's train.checkpoint_dir (override
with train.checkpoint_dir=...). The sweep is fixed: ddpm@256, ddpm@64,
ddim@64, ddim@32, dpm++@32, dpm++@16, dpm++@8 (clamped to
diffusion.timesteps when the training schedule is shorter).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SWEEP = [
    ("ddpm", 256),
    ("ddpm", 64),
    ("ddim", 64),
    ("ddim", 32),
    ("dpm++", 32),
    ("dpm++", 16),
    ("dpm++", 8),
]


def clamped_sweep(sweep, timesteps: int):
    """Clamp step counts to the training schedule and drop the duplicate
    (sampler, steps) pairs clamping creates, preserving order."""
    out = []
    for sampler, steps in sweep:
        pair = (sampler, min(steps, timesteps))
        if pair not in out:
            out.append(pair)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("folder")
    ap.add_argument("out")
    ap.add_argument("--preset", default="tiny64")
    ap.add_argument("--config", default=None,
                    help="path to a resolved Config JSON (e.g. the "
                         "work/config.json a quality run writes); "
                         "takes precedence over --preset")
    ap.add_argument("--num-instances", type=int, default=8)
    ap.add_argument("--views-per-instance", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args, rest = ap.parse_known_args()
    overrides = [a for a in rest if "=" in a]
    bad = [a for a in rest if "=" not in a]
    if bad:
        ap.error(f"unrecognized arguments: {bad}")

    from _common import init_jax_env
    init_jax_env()
    import jax
    import numpy as np

    from novel_view_synthesis_3d_tpu.config import Config, get_preset
    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
    from novel_view_synthesis_3d_tpu.eval.evaluate import evaluate_dataset
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.train.checkpoint import CheckpointManager
    from novel_view_synthesis_3d_tpu.train.state import create_train_state
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch

    if args.config:
        cfg = Config.from_json(open(args.config).read())
    else:
        cfg = get_preset(args.preset)
    if overrides:
        cfg = cfg.apply_cli(overrides)
    # The sweep passes explicit step counts; the preset's default
    # sample_timesteps (e.g. 1000) may exceed a short training schedule.
    cfg = dataclasses.replace(
        cfg, diffusion=dataclasses.replace(
            cfg.diffusion,
            sample_timesteps=min(cfg.diffusion.sample_timesteps,
                                 cfg.diffusion.timesteps)))
    cfg.validate()

    ds = SRNDataset(args.folder, img_sidelength=cfg.data.img_sidelength)
    model = XUNet(cfg.model)
    rec = ds.pair(0, np.random.default_rng(0))
    template = create_train_state(
        cfg.train, model, _sample_model_batch({k: v[None]
                                               for k, v in rec.items()}))
    ckpt = CheckpointManager(cfg.train.checkpoint_dir)
    step = ckpt.latest_step()
    if step is None:
        raise SystemExit(
            f"no checkpoint under {cfg.train.checkpoint_dir!r} — train first")
    state = ckpt.restore(template, step=step)
    ckpt.close()
    params = state.ema_params if getattr(state, "ema_params",
                                         None) is not None else state.params
    print(f"restored checkpoint at step {step}", flush=True)

    rows = []
    for sampler, steps in clamped_sweep(SWEEP, cfg.diffusion.timesteps):
        run_cfg = dataclasses.replace(
            cfg, diffusion=dataclasses.replace(cfg.diffusion, sampler=sampler))
        t0 = time.perf_counter()
        result = evaluate_dataset(
            run_cfg, model, params, ds,
            key=jax.random.PRNGKey(args.seed),
            num_instances=args.num_instances,
            views_per_instance=args.views_per_instance,
            sample_steps=steps,
        )
        wall = time.perf_counter() - t0
        row = {
            "sampler": sampler,
            "steps": steps,
            "psnr": round(result.psnr, 4),
            "ssim": round(result.ssim, 4),
            "num_views": result.num_views,
            # Includes this config's compile; relative timing only.
            "wall_sec_per_view": round(wall / result.num_views, 4),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = {
        "checkpoint_step": step,
        "preset": args.preset,
        "platform": jax.default_backend(),
        "timing_note": "wall_sec_per_view includes each config's jit "
                       "compile — compare rows relatively, not as "
                       "deployment latency (bench.py sample measures that)",
        "rows": rows,
    }
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {args.out}", flush=True)
    # Single platform-tagged JSON line LAST (the bench watcher parses the
    # last {-line and refuses CPU-fallback output as TPU evidence). Value:
    # PSNR cost of the cheapest dpm++ config vs the most expensive ddpm.
    dpmpp = [r for r in rows if r["sampler"] == "dpm++"]
    print(json.dumps({
        "metric": "sampler_comparison_psnr_delta_fastest_dpmpp_vs_ddpm",
        "value": (round(dpmpp[-1]["psnr"] - rows[0]["psnr"], 4)
                  if dpmpp else None),
        "unit": "dB",
        "platform": jax.default_backend(),
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
