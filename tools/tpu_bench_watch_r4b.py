"""Round-4 TPU watcher, phase B: reordered by VERDICT-r3 value.

Same OUT dir as tools/tpu_bench_watch_r4.py, so completed entries (their
{name}.json exists) are skipped and failed ones retry. Reordering
rationale, given a live-but-mortal tunnel:
  1. paper256 analyze+train retry FIRST — the r4a attempt measured the
     OOM (17.94G/15.75G) that motivated train.ema_host; this validates
     the fix on hardware (VERDICT item 5);
  2. the 20k-step 64px quality run next (VERDICT item 2 — the
     framework's entire purpose; nothing else in the matrix is worth
     more if the tunnel dies early);
  3. then the Pallas A/B grid (item 4), the base128 sampler retry, the
     k=2/k=1 quality pair (item 8), and the long-tail extras.

Usage: python tools/tpu_bench_watch_r4b.py [max_wait_hours]
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "results", "tpu_r04")
sys.path.insert(0, REPO)
from bench import CACHE_DIR as CACHE  # noqa: E402
from _common import run_watcher  # noqa: E402

Q = os.path.join("results", "quality_tpu_r04")

MATRIX = [
    # Done in phase A (skipped via .json): tiny64_train, sample_tiny64_256.
    ("analyze_paper256", ["bench.py", "analyze", "paper256"], 3600),
    ("paper256_train", ["bench.py", "paper256", "10"], 5400),
    # 7200s, not 14400: the run needs ~1-2h on the chip, and the watcher
    # skips any entry whose TIMEOUT crosses its deadline — an oversized
    # budget would sacrifice the highest-value entry on a late tunnel
    # revival.
    ("quality_tpu_64px", ["tools/quality_run.py", Q, "20000", "64"], 7200),
    # paper256 optimizer A/B: adafactor drops optimizer state from 2x to
    # ~0x param bytes (state.make_optimizer) — memory-margin evidence via
    # analyze, throughput delta vs Adam via train. Also the fallback that
    # lands paper256 numbers if the ema_host margin (predicted 15.30G of
    # 15.75G) loses to allocator fragmentation variance.
    ("analyze_paper256_adafactor",
     ["bench.py", "analyze", "paper256", "train.optimizer=adafactor"], 3600),
    ("paper256_adafactor",
     ["bench.py", "paper256", "10", "train.optimizer=adafactor"], 5400),
    ("base128_train", ["bench.py", "base128", "20"], 2400),
    # Fused multi-step dispatch A/B (train.steps_per_dispatch): the r4a
    # tiny64_train.json (188.5 imgs/s/chip) was spd=1; bench.py now
    # defaults tiny64 to spd=10, so measure both explicitly. base128 at
    # spd=5 probes whether dispatch overhead still matters at 200ms steps.
    ("tiny64_spd10", ["bench.py", "tiny64", "30"], 1800),
    ("tiny64_spd1", ["bench.py", "tiny64", "30",
                     "train.steps_per_dispatch=1"], 1800),
    ("base128_spd5", ["bench.py", "base128", "20",
                      "train.steps_per_dispatch=5"], 2400),
    ("tiny64_noflash", ["bench.py", "tiny64", "30",
                        "model.use_flash_attention=False"], 1800),
    ("tiny64_fusedgn", ["bench.py", "tiny64", "30",
                        "model.use_fused_groupnorm=True"], 1800),
    ("base128_noflash", ["bench.py", "base128", "20",
                         "model.use_flash_attention=False"], 2400),
    ("base128_fusedgn", ["bench.py", "base128", "20",
                         "model.use_fused_groupnorm=True"], 2400),
    # 3600s, not 2400: its phase-A attempt showed the 256-step base128
    # scan's remote compile alone can eat a 2400s budget (and a timeout
    # mid-compile caches nothing, so a short retry can never land).
    ("sample_base128_256", ["bench.py", "sample", "base128", "256"], 3600),
    ("base128_bs16", ["bench.py", "base128", "20",
                      "train.batch_size=16"], 2400),
    ("sample_dpmpp32_tiny64", ["bench.py", "sample", "tiny64", "32",
                               "diffusion.sampler=dpm++"], 1800),
    ("sample_ar_tiny64", ["bench.py", "sample-ar", "tiny64", "8"], 2400),
    ("sampler_comparison_quality64",
     ["tools/sampler_comparison.py", os.path.join(Q, "work", "val"),
      os.path.join(Q, "sampler_comparison.json"),
      "--config", os.path.join(Q, "work", "config.json"),
      "--num-instances", "6", "--views-per-instance", "2"], 3600),
    ("quality_tpu_k2", ["tools/quality_run.py",
                        os.path.join("results", "quality_tpu_r04_k2"),
                        "8000", "64", "model.num_cond_frames=2"], 5400),
    ("quality_tpu_k1_matched", ["tools/quality_run.py",
                                os.path.join("results",
                                             "quality_tpu_r04_k1m"),
                                "8000", "64"], 5400),
    ("profile_base128", ["bench.py", "profile", "base128", "5"], 2400),
    # Perf probes, config-only: bf16 sampling compute on the f32-trained
    # tiny64 shape (params stay f32; casts per use), and the 'dots' remat
    # point re-measured post-r3/r4 changes (r2 ladder:
    # results/tpu_r02/base128_remat_*.json).
    ("sample_tiny64_256_bf16", ["bench.py", "sample", "tiny64", "256",
                                "model.dtype=bfloat16"], 1800),
    ("base128_dots", ["bench.py", "base128", "20",
                      "model.remat=dots"], 2400),
]


if __name__ == "__main__":
    max_wait_h = float(sys.argv[1]) if len(sys.argv) > 1 else 9.0
    run_watcher(OUT, MATRIX, max_wait_h, CACHE)
