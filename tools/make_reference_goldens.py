"""Generate golden reference-model artifacts for checkpoint-import parity.

Runs the ACTUAL reference X-UNet source (/root/reference/model/xunet.py)
under the current flax, captures its init param tree and forward outputs on
a fixed batch, and writes them to tests/golden/reference_xunet.npz. The
parity tests (tests/test_reference_ckpt.py) then prove — without needing
/root/reference present — that:

  - the checkpoint importer maps the reference tree onto this repo's layout
    with nothing left over, and
  - this repo's model under the `reference` preset reproduces the reference
    model's forward outputs on identical weights.

visu3d (the reference's ray dependency, not installed here) is shimmed with
the pure-jnp rays from models/rays.py — the shim implements exactly the
v3d.Camera(...).rays() surface the reference touches. Ray semantics are
pinned independently against hand-computed pinhole geometry in
tests/test_posenc_rays.py, so the shim does not make ray parity circular
with the model code under test.

Usage (dev machine with the reference checkout):
    PYTHONPATH=/root/repo python tools/make_reference_goldens.py
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE = os.environ.get("NVS3D_REFERENCE", "/root/reference")
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "golden", "reference_xunet.npz")


def _install_visu3d_shim() -> None:
    from novel_view_synthesis_3d_tpu.models.rays import camera_rays

    shim = types.ModuleType("visu3d")

    class Transform:
        def __init__(self, R, t):
            self.R, self.t = jnp.asarray(R), jnp.asarray(t)

    class PinholeCamera:
        def __init__(self, resolution, K):
            self.resolution, self.K = resolution, jnp.asarray(K)

    class _Rays:
        def __init__(self, pos, dir):
            self.pos, self.dir = pos, dir

    class Camera:
        def __init__(self, spec, world_from_cam):
            self.spec, self.world_from_cam = spec, world_from_cam

        def rays(self):
            pos, dirs = camera_rays(
                self.world_from_cam.R, self.world_from_cam.t, self.spec.K,
                resolution=self.spec.resolution)
            return _Rays(pos, dirs)

    shim.Transform = Transform
    shim.PinholeCamera = PinholeCamera
    shim.Camera = Camera
    sys.modules["visu3d"] = shim


def _load_reference_model():
    path = os.path.join(REFERENCE, "model", "xunet.py")
    spec = importlib.util.spec_from_file_location("reference_xunet", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def make_batch(B=2, S=16, seed=0):
    rng = np.random.default_rng(seed)
    # Plausible look-at-style cameras on a sphere; values fixed by seed.
    def rot(_):
        a, b, c = rng.uniform(-np.pi, np.pi, 3)
        Rz = np.array([[np.cos(a), -np.sin(a), 0], [np.sin(a), np.cos(a), 0],
                       [0, 0, 1]])
        Ry = np.array([[np.cos(b), 0, np.sin(b)], [0, 1, 0],
                       [-np.sin(b), 0, np.cos(b)]])
        Rx = np.array([[1, 0, 0], [0, np.cos(c), -np.sin(c)],
                       [0, np.sin(c), np.cos(c)]])
        return (Rz @ Ry @ Rx).astype(np.float32)

    K = np.array([[S * 1.2, 0, S / 2], [0, S * 1.2, S / 2], [0, 0, 1]],
                 np.float32)
    return {
        "x": rng.uniform(-1, 1, (B, S, S, 3)).astype(np.float32),
        "z": rng.normal(size=(B, S, S, 3)).astype(np.float32),
        "logsnr": rng.uniform(-15, 15, (B,)).astype(np.float32),
        "R1": np.stack([rot(i) for i in range(B)]),
        "t1": rng.uniform(-2, 2, (B, 3)).astype(np.float32),
        "R2": np.stack([rot(i) for i in range(B)]),
        "t2": rng.uniform(-2, 2, (B, 3)).astype(np.float32),
        "K": np.broadcast_to(K, (B, 3, 3)).copy(),
    }


def _capture(ref, batch, cond_mask, out_path, **model_kwargs) -> None:
    model = ref.XUNet(**model_kwargs)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        {k: jnp.asarray(v) for k, v in batch.items()},
        cond_mask=jnp.asarray(cond_mask), train=False)
    out = model.apply(variables,
                      {k: jnp.asarray(v) for k, v in batch.items()},
                      cond_mask=jnp.asarray(cond_mask), train=False)

    flat = {}
    def flatten(tree, prefix=""):
        for k, v in tree.items():
            p = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                flatten(v, p)
            else:
                flat[f"param:{p}"] = np.asarray(v)
    flatten(variables["params"])

    n_params = sum(v.size for k, v in flat.items())
    arrays = dict(flat)
    for k, v in batch.items():
        arrays[f"batch:{k}"] = v
    arrays["cond_mask"] = cond_mask
    arrays["output"] = np.asarray(out)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    np.savez_compressed(out_path, **arrays)
    print(f"wrote {out_path}: {len(flat)} param leaves, {n_params:,} "
          f"params, output shape {np.asarray(out).shape}, "
          f"{os.path.getsize(out_path) / 1e6:.2f} MB")


def main() -> None:
    jax.config.update("jax_platforms", "cpu")
    _install_visu3d_shim()
    ref = _load_reference_model()

    batch = make_batch()
    cond_mask = np.array([1.0, 0.0], np.float32)  # exercise the CFG zeroing
    # Reference defaults (ch=32, ch_mult=(1,2), emb 32) — the published
    # pretrained model's config.
    _capture(ref, batch, cond_mask, OUT)
    # Optional learned embeddings ON — covers the pos_emb /
    # ref_pose_emb_{first,other} param mapping the defaults never create.
    _capture(ref, batch, cond_mask,
             OUT.replace(".npz", "_posemb.npz"),
             use_pos_emb=True, use_ref_pose_emb=True)


if __name__ == "__main__":
    main()
