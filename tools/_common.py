"""Shared start-up for the tools/ entry points (and bench.py's twin block).

One place for the JAX environment dance every standalone script needs:
honor a JAX_PLATFORMS=cpu pin set after interpreter start (the container
sitecustomize imports jax first, so the env var alone is not enough), and
wire the persistent compilation cache when configured.
"""

from __future__ import annotations

import os


def init_jax_env() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# --- TPU bench watcher machinery (round watchers supply only a MATRIX) ---
#
# Probe/run/resume lessons accumulated over rounds 2-3 (see
# docs/DESIGN.md and the r2/r3 watcher files for history):
#   - probe with a REAL computation in a disposable child and ABANDON a
#     stuck child (a process touching the wedged tunnel enters
#     uninterruptible sleep; SIGKILL doesn't reap it until the syscall
#     returns, so communicate()/wait() without timeout blocks forever);
#   - refuse CPU-fallback output as TPU evidence BEFORE persisting it;
#   - resume across watcher restarts via the presence of {name}.json;
#   - never start a bench whose timeout crosses the watcher deadline —
#     the driver's end-of-round `python bench.py` needs the
#     single-process-exclusive TPU free.

PROBE_INTERVAL_S = 180
PROBE_TIMEOUT_S = 120


def run_watcher(out_dir: str, matrix, max_wait_h: float,
                cache_dir: str) -> None:
    """Wait for the TPU tunnel, then run `matrix` entries sequentially.

    matrix: [(name, argv-after-python relative to the repo, timeout_s)].
    Artifacts land in out_dir: {name}.out (full output), {name}.json (the
    last platform-tagged JSON line, written only for a non-CPU rc=0 run),
    log.txt.
    """
    import json
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def log(msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "log.txt"), "a") as fh:
            fh.write(line + "\n")

    def probe_alive() -> bool:
        code = ("import jax, jax.numpy as jnp; "
                "x = jnp.ones((256, 256)); "
                "print(float((x @ x).sum()), jax.devices()[0].platform)")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # probe the real accelerator
        proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        try:
            out, _ = proc.communicate(timeout=PROBE_TIMEOUT_S)
            if proc.returncode == 0 and "cpu" not in out:
                log(f"probe OK: {out.strip()}")
                return True
            log(f"probe rc={proc.returncode} out={out.strip()!r} "
                "(cpu or fail)")
            return False
        except subprocess.TimeoutExpired:
            proc.kill()  # child may be unreapable; abandon
            log("probe timed out — tunnel still wedged")
            return False

    def run_bench(name: str, argv: list, timeout_s: int) -> bool:
        log(f"running {name}: {' '.join(argv)}")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # use the real accelerator
        env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        # The watcher's probe already ran here; don't let the bench burn
        # its full default budget re-probing a tunnel we just saw alive.
        env.setdefault("NVS3D_PROBE_BUDGET_S", "120")
        out_path = os.path.join(out_dir, f"{name}.out")
        script, script_args = argv[0], argv[1:]
        with open(out_path, "w") as fh:
            proc = subprocess.Popen(
                [sys.executable, os.path.join(repo, script)] + script_args,
                stdout=fh, stderr=subprocess.STDOUT, env=env, cwd=repo)
            try:
                rc = proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                log(f"{name}: TIMED OUT after {timeout_s}s "
                    f"(output in {out_path})")
                return False
        tail = open(out_path).read().strip().splitlines()
        result = next(
            (ln for ln in reversed(tail) if ln.startswith("{")), None)
        log(f"{name}: rc={rc} result={result}")
        platform = None
        if result:
            try:
                platform = json.loads(result).get("platform")
            except json.JSONDecodeError:
                pass
        if platform == "cpu":
            # Reject BEFORE persisting: a CPU-fallback .json in out_dir
            # would be indistinguishable from TPU evidence (the .out
            # keeps the full output for debugging).
            log(f"{name}: completed on CPU — not TPU evidence; counting "
                "as failure")
            return False
        if rc != 0:
            return False
        if not result:
            # Every matrix entry prints a platform-tagged JSON line; its
            # absence means the run died oddly — do NOT persist evidence
            # or count it done.
            log(f"{name}: rc=0 but no JSON line — counting as failure")
            return False
        with open(os.path.join(out_dir, f"{name}.json"), "w") as fh:
            fh.write(result + "\n")
        return True

    deadline = time.time() + max_wait_h * 3600
    log(f"watcher: waiting for TPU (max {max_wait_h:.1f}h)")
    done, failed, skipped = set(), set(), set()
    for name, _, _ in matrix:
        if os.path.exists(os.path.join(out_dir, f"{name}.json")):
            done.add(name)
    if done:
        log(f"resuming: {len(done)} entries already have artifacts "
            f"({json.dumps(sorted(done))})")
    while time.time() < deadline:
        if probe_alive():
            log("TPU alive — running matrix")
            for name, argv, timeout_s in matrix:
                if name in done or name in failed or name in skipped:
                    continue  # resume after a mid-matrix tunnel death
                if time.time() + timeout_s > deadline:
                    log(f"{name}: skipped (never attempted) — its "
                        f"{timeout_s}s timeout crosses the watcher "
                        "deadline")
                    skipped.add(name)
                    continue
                if run_bench(name, argv, timeout_s):
                    done.add(name)
                elif probe_alive():
                    failed.add(name)
                    log(f"{name}: failed with tunnel alive — not retrying")
                else:
                    log("tunnel died mid-matrix; resuming watch")
                    break
            if len(done) + len(failed) + len(skipped) == len(matrix):
                log(f"matrix finished: ok={json.dumps(sorted(done))} "
                    f"failed={json.dumps(sorted(failed))} "
                    f"skipped={json.dumps(sorted(skipped))}")
                return
        remaining = deadline - time.time()
        if remaining <= 0:
            break
        time.sleep(min(PROBE_INTERVAL_S, remaining))
    log(f"deadline reached: ok={json.dumps(sorted(done))} "
        f"failed={json.dumps(sorted(failed))} "
        f"skipped={json.dumps(sorted(skipped))}")
