"""Shared start-up for the tools/ entry points (and bench.py's twin block).

One place for the JAX environment dance every standalone script needs:
honor a JAX_PLATFORMS=cpu pin set after interpreter start (the container
sitecustomize imports jax first, so the env var alone is not enough), and
wire the persistent compilation cache when configured.
"""

from __future__ import annotations

import os


def init_jax_env() -> None:
    import sys

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # Shared compile-cache wiring (utils/xla_cache.py — the same helper
    # the cli entry points use). Tools keep their historical env-only
    # contract: no cache unless JAX_COMPILATION_CACHE_DIR is set (the
    # watcher sets it explicitly per round).
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from novel_view_synthesis_3d_tpu.utils.xla_cache import (
        setup_compilation_cache)

    setup_compilation_cache(default_dir=None, min_entry_bytes=0)


# --- TPU bench watcher machinery (round watchers supply only a MATRIX) ---
#
# Probe/run/resume lessons accumulated over rounds 2-3 (see
# docs/DESIGN.md and the r2/r3 watcher files for history):
#   - probe with a REAL computation in a disposable child and ABANDON a
#     stuck child (a process touching the wedged tunnel enters
#     uninterruptible sleep; SIGKILL doesn't reap it until the syscall
#     returns, so communicate()/wait() without timeout blocks forever);
#   - refuse CPU-fallback output as TPU evidence BEFORE persisting it;
#   - resume across watcher restarts via the presence of {name}.json;
#   - never start a bench whose timeout crosses the watcher deadline —
#     the driver's end-of-round `python bench.py` needs the
#     single-process-exclusive TPU free.

PROBE_INTERVAL_S = 180
PROBE_TIMEOUT_S = 120


def run_watcher(out_dir: str, matrix, max_wait_h: float,
                cache_dir: str, max_attempts: int = 2,
                probe_fn=None) -> None:
    """Wait for the TPU tunnel, then run `matrix` entries sequentially.

    matrix: [(name, argv-after-python relative to the repo, timeout_s)].
    Artifacts land in out_dir: {name}.out (full output), {name}.json (the
    last platform-tagged JSON line, written only for a non-CPU rc=0 run),
    {name}.attempts.json (persistent failure ledger), log.txt.

    Retry semantics (VERDICT r4 item 7): a failure with the tunnel ALIVE
    (OOM, timeout, bad rc) increments a persistent attempt counter and the
    entry is retried on the NEXT matrix pass, until max_attempts; the
    counter file survives watcher restarts, so a new watcher process
    neither forgets hopeless entries nor re-queues them indefinitely. A
    tunnel death mid-run does NOT count as an attempt (not the entry's
    fault; the persistent compile cache makes the re-run cheap).
    """
    import json
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def attempts_path(name: str) -> str:
        return os.path.join(out_dir, f"{name}.attempts.json")

    def load_attempts(name: str) -> int:
        try:
            with open(attempts_path(name)) as fh:
                return int(json.load(fh).get("attempts", 0))
        except (OSError, ValueError):
            return 0

    def record_attempt(name: str, reason: str) -> int:
        n = load_attempts(name) + 1
        os.makedirs(out_dir, exist_ok=True)
        with open(attempts_path(name), "w") as fh:
            json.dump({"attempts": n, "last_failure": reason,
                       "ts": time.strftime("%Y-%m-%d %H:%M:%S")}, fh)
        return n

    def log(msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "log.txt"), "a") as fh:
            fh.write(line + "\n")

    def probe_alive() -> bool:
        if probe_fn is not None:  # injected by tests (no real tunnel)
            return probe_fn()
        # Shared probe primitive (parallel/dist.probe_backend): a real
        # computation in a disposable, abandonable child. JAX_PLATFORMS is
        # popped so an ambient CPU pin doesn't shadow the accelerator, and
        # require_accelerator rejects CPU answers (not TPU evidence).
        sys.path.insert(0, repo)
        from novel_view_synthesis_3d_tpu.parallel.dist import probe_backend

        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # probe the real accelerator
        ok, reason = probe_backend(PROBE_TIMEOUT_S,
                                   require_accelerator=True, env=env)
        log(f"probe OK: {reason}" if ok else f"probe failed: {reason}")
        return ok

    def run_bench(name: str, argv: list, timeout_s: int):
        """Run one entry; returns None on success, else a failure reason."""
        log(f"running {name}: {' '.join(argv)}")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # use the real accelerator
        env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        # The watcher's probe already ran here; don't let the bench burn
        # its full default budget re-probing a tunnel we just saw alive.
        env.setdefault("NVS3D_PROBE_BUDGET_S", "120")
        out_path = os.path.join(out_dir, f"{name}.out")
        script, script_args = argv[0], argv[1:]
        with open(out_path, "w") as fh:
            proc = subprocess.Popen(
                [sys.executable, os.path.join(repo, script)] + script_args,
                stdout=fh, stderr=subprocess.STDOUT, env=env, cwd=repo)
            try:
                rc = proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                log(f"{name}: TIMED OUT after {timeout_s}s "
                    f"(output in {out_path})")
                return f"timeout after {timeout_s}s"
        tail = open(out_path).read().strip().splitlines()
        result = next(
            (ln for ln in reversed(tail) if ln.startswith("{")), None)
        log(f"{name}: rc={rc} result={result}")
        platform = None
        if result:
            try:
                platform = json.loads(result).get("platform")
            except json.JSONDecodeError:
                pass
        if platform == "cpu":
            # Reject BEFORE persisting: a CPU-fallback .json in out_dir
            # would be indistinguishable from TPU evidence (the .out
            # keeps the full output for debugging).
            log(f"{name}: completed on CPU — not TPU evidence; counting "
                "as failure")
            return "completed on cpu (not TPU evidence)"
        if rc != 0:
            return f"rc={rc}"
        if not result:
            # Every matrix entry prints a platform-tagged JSON line; its
            # absence means the run died oddly — do NOT persist evidence
            # or count it done.
            log(f"{name}: rc=0 but no JSON line — counting as failure")
            return "rc=0 but no JSON line"
        with open(os.path.join(out_dir, f"{name}.json"), "w") as fh:
            fh.write(result + "\n")
        # Success clears the failure ledger: a later intentional re-measure
        # (delete the artifact, restart the watcher) gets a fresh retry
        # budget instead of inheriting this run's transient failures.
        try:
            os.remove(attempts_path(name))
        except OSError:
            pass
        return None

    deadline = time.time() + max_wait_h * 3600
    log(f"watcher: waiting for TPU (max {max_wait_h:.1f}h)")
    done, skipped = set(), set()
    for name, _, _ in matrix:
        if os.path.exists(os.path.join(out_dir, f"{name}.json")):
            done.add(name)
    if done:
        log(f"resuming: {len(done)} entries already have artifacts "
            f"({json.dumps(sorted(done))})")
    prior = {n for n, _, _ in matrix
             if n not in done and load_attempts(n) > 0}
    if prior:
        log(f"prior attempts on record: {json.dumps(sorted(prior))}")

    def exhausted() -> set:
        return {n for n, _, _ in matrix
                if n not in done and load_attempts(n) >= max_attempts}

    def summary() -> str:
        """Every entry accounted for — including partially-attempted ones
        the deadline cut off before their retry pass."""
        partial = {n: load_attempts(n) for n, _, _ in matrix
                   if n not in done and n not in skipped
                   and 0 < load_attempts(n) < max_attempts}
        return (f"ok={json.dumps(sorted(done))} "
                f"failed={json.dumps(sorted(exhausted()))} "
                f"skipped={json.dumps(sorted(skipped))} "
                f"partial_attempts={json.dumps(partial)}")

    while time.time() < deadline:
        if probe_alive():
            log("TPU alive — running matrix")
            for name, argv, timeout_s in matrix:
                if (name in done or name in skipped
                        or load_attempts(name) >= max_attempts):
                    continue  # resume after a mid-matrix tunnel death
                if time.time() + timeout_s > deadline:
                    n_prior = load_attempts(name)
                    log(f"{name}: skipped "
                        f"({n_prior} prior attempt(s) on record) — its "
                        f"{timeout_s}s timeout crosses the watcher "
                        "deadline")
                    skipped.add(name)
                    continue
                reason = run_bench(name, argv, timeout_s)
                if reason is None:
                    done.add(name)
                elif probe_alive():
                    n = record_attempt(name, reason)
                    log(f"{name}: failed ({reason}) with tunnel alive — "
                        f"attempt {n}/{max_attempts}"
                        + ("; will retry next pass" if n < max_attempts
                           else "; giving up"))
                else:
                    log("tunnel died mid-matrix; resuming watch "
                        "(no attempt charged)")
                    break
            if len(done) + len(exhausted()) + len(skipped) == len(matrix):
                log(f"matrix finished: {summary()}")
                return
        remaining = deadline - time.time()
        if remaining <= 0:
            break
        time.sleep(min(PROBE_INTERVAL_S, remaining))
    log(f"deadline reached: {summary()}")
