"""Shared start-up for the tools/ entry points (and bench.py's twin block).

One place for the JAX environment dance every standalone script needs:
honor a JAX_PLATFORMS=cpu pin set after interpreter start (the container
sitecustomize imports jax first, so the env var alone is not enough), and
wire the persistent compilation cache when configured.
"""

from __future__ import annotations

import os


def init_jax_env() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
