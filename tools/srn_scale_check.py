"""SRN-format reader at scale: prove the full data path on an SRN tree of
realistic size (VERDICT r2 "What's missing" #2, as far as a no-egress
environment allows).

The real SRN cars dump (~2,400 instances × 50 views) cannot be fetched
here, so this writes a synthetic tree in the EXACT on-disk SRN format the
reference consumes (rgb/*.png, pose/*.txt flat 4×4, intrinsics.txt —
/root/reference/dataset/data_util.py contract) at a scale where indexing,
binary-search locate, intrinsics caching, and the worker-pool loaders
actually face thousands of files, then drives every reader backend over it:

  - SRNDataset index: instance/view counts, O(log n) locate spot-checks,
    pair() record contract on random indices;
  - native C++ loader (worker pool): sustained imgs/sec over the tree +
    determinism across thread counts;
  - grain and in-process python backends: throughput on the same tree;
  - a short Trainer run consuming the tree through the standard pipeline
    (the reference's `Trainer('cars_train_val')` shape, train.py:175).

Writes results/srn_scale_r03.json. Usage:
    python tools/srn_scale_check.py [instances] [views] [px]
(defaults 100 50 128 ≈ 5,000 views — the per-split scale of SRN chairs.)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT = os.path.join(REPO, "results", "srn_scale_r03.json")


def main() -> None:
    n_inst = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    n_views = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    px = int(sys.argv[3]) if len(sys.argv) > 3 else 128

    from _common import init_jax_env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    init_jax_env()
    import numpy as np

    from novel_view_synthesis_3d_tpu.config import DataConfig
    from novel_view_synthesis_3d_tpu.data import native_io
    from novel_view_synthesis_3d_tpu.data.pipeline import (
        iter_batches, make_dataset, make_grain_loader)
    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
    from novel_view_synthesis_3d_tpu.data.synthetic import write_synthetic_srn

    report = {"instances": n_inst, "views_per_instance": n_views,
              "image_px": px}
    tmp = tempfile.mkdtemp(prefix="nvs3d_srn_scale_")
    try:
        root = os.path.join(tmp, "srn")
        t0 = time.time()
        write_synthetic_srn(root, num_instances=n_inst,
                            views_per_instance=n_views, image_size=px)
        report["tree_write_s"] = round(time.time() - t0, 1)
        n_files = sum(len(fs) for _, _, fs in os.walk(root))
        report["files_on_disk"] = n_files

        # --- index + locate + record contract --------------------------
        t0 = time.time()
        ds = SRNDataset(root, img_sidelength=px // 2)
        report["index_build_s"] = round(time.time() - t0, 2)
        assert ds.num_instances == n_inst, ds.num_instances
        total = len(ds)
        assert total == n_inst * n_views, total
        rng = np.random.default_rng(0)
        t0 = time.time()
        for idx in rng.integers(0, total, size=64):
            rec = ds.pair(int(idx), rng)
            assert rec["x"].shape == (px // 2, px // 2, 3)
            assert rec["target"].shape == (px // 2, px // 2, 3)
            assert rec["K"].shape == (3, 3)
            assert np.isfinite(rec["R1"]).all() and np.isfinite(rec["R2"]).all()
        report["pair_64_random_s"] = round(time.time() - t0, 2)

        cfg = DataConfig(root_dir=root, img_sidelength=px // 2)
        ds_pipe = make_dataset(cfg)
        batch_size = 32 if total >= 64 else 8  # smoke-scale trees still
        # must satisfy the loaders' shard >= one batch contract

        def time_backend(make_iter, n_batches):
            it = make_iter()
            next(it)  # warm up workers/prefetch
            t0 = time.time()
            for _ in range(n_batches):
                b = next(it)
            dt = time.time() - t0
            assert b["target"].shape[0] == batch_size
            return round(n_batches * batch_size / dt, 1)

        # --- native C++ worker-pool loader ------------------------------
        if native_io.available():
            report["native_imgs_per_sec"] = time_backend(
                lambda: iter(native_io.make_native_loader(
                    ds_pipe, batch_size, n_threads=8, prefetch_depth=4,
                    seed=0)), 60)
            # Determinism across thread counts (order is seed-driven).
            def first_batch(threads):
                it = iter(native_io.make_native_loader(
                    ds_pipe, batch_size, n_threads=threads,
                    prefetch_depth=2, seed=7))
                return next(it)
            a, b = first_batch(2), first_batch(8)
            np.testing.assert_array_equal(a["target"], b["target"])
            report["native_deterministic_across_threads"] = True

        # --- grain + python backends ------------------------------------
        from novel_view_synthesis_3d_tpu.data.pipeline import cycle
        report["grain_imgs_per_sec"] = time_backend(
            lambda: cycle(make_grain_loader(ds_pipe, batch_size, seed=0,
                                            num_workers=4)), 30)
        report["python_imgs_per_sec"] = time_backend(
            lambda: iter_batches(ds_pipe, batch_size, seed=0), 20)

        # --- Trainer consumes the tree end-to-end -----------------------
        from novel_view_synthesis_3d_tpu.cli import main as cli
        work = os.path.join(tmp, "work")
        t0 = time.time()
        rc = cli(["train", root, "--no-grain",
                  "model.ch=32", "model.ch_mult=[1,2]", "model.emb_ch=32",
                  "model.num_res_blocks=1", "model.attn_resolutions=[8]",
                  "diffusion.timesteps=8", "diffusion.sample_timesteps=4",
                  "data.img_sidelength=16",
                  "train.batch_size=8", "train.num_steps=3",
                  "train.save_every=0", "train.log_every=1",
                  "train.eval_every=0", "train.sample_every=0",
                  f"train.checkpoint_dir={work}/ckpt",
                  f"train.results_folder={work}/out"])
        assert rc in (0, None), rc
        report["trainer_3step_s"] = round(time.time() - t0, 1)
        report["ok"] = True
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
