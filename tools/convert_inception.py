"""Convert the public pytorch-fid InceptionV3 checkpoint to .npz.

Usage: python tools/convert_inception.py pt_inception-2015-12-05.pth out.npz

One-time, offline-friendly conversion: reads the torch state_dict (torch is
only needed HERE, never by the JAX feature extractor), drops the
classifier/aux tensors, validates every remaining tensor against
eval/inception.expected_param_shapes(), and writes a plain .npz with the
state_dict key names verbatim. The eval CLI then takes it via
--inception-npz and reports paper-comparable "fid" instead of
"fid_random".

The checkpoint is the standard FID one (TF-slim inception export,
distributed by the pytorch-fid project as pt_inception-2015-12-05). This
container has no network egress, so fetching it is up to the user.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import init_jax_env  # noqa: E402


def convert(pth_path: str, npz_path: str) -> int:
    from novel_view_synthesis_3d_tpu.eval.inception import (
        expected_param_shapes)

    import torch

    state = torch.load(pth_path, map_location="cpu", weights_only=True)
    if hasattr(state, "state_dict"):
        state = state.state_dict()
    expected = expected_param_shapes()
    out = {}
    for key, shape in expected.items():
        if key not in state:
            print(f"error: checkpoint missing {key!r}", file=sys.stderr)
            return 1
        arr = state[key].detach().cpu().numpy()
        if tuple(arr.shape) != shape:
            print(f"error: {key} has shape {tuple(arr.shape)}, "
                  f"expected {shape}", file=sys.stderr)
            return 1
        out[key] = arr.astype(np.float32)
    dropped = sorted(k for k in state
                     if k not in expected and "num_batches_tracked" not in k)
    if dropped:
        print(f"dropped {len(dropped)} non-feature tensors "
              f"(fc/aux): first {dropped[:3]}")
    np.savez_compressed(npz_path, **out)
    print(f"wrote {len(out)} tensors to {npz_path}")
    return 0


if __name__ == "__main__":
    init_jax_env()
    if len(sys.argv) != 3:
        print(__doc__)
        sys.exit(2)
    sys.exit(convert(sys.argv[1], sys.argv[2]))
