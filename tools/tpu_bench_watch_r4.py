"""Round-4 TPU watcher: wait for the tunnel, run the VERDICT-r3 matrix.

The probe/run/resume machinery lives in tools/_common.run_watcher (shared
across round watchers); this file is only the round-4 MATRIX, ordered by
VERDICT r3 "Next round":
  1. the judged BASELINE metrics first (tiny64 train = the driver's exact
     invocation, 256-step sampler sec/view);
  2. paper256: analyze (16G fit check) then first-ever execution (item 5);
  3. the two Pallas kernels A/B on hardware at tiny64 AND base128
     (item 4): flash off vs default-auto-on, fused-GN on vs default-off;
  4. the 20k-step 64px quality run (item 2) + sampler comparison;
  5. k=2 vs k=1 conditioning quality runs at matched budget (item 8).

Usage: python tools/tpu_bench_watch_r4.py [max_wait_hours]
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "results", "tpu_r04")
# Single source of truth for the warm-up↔judged-bench cache handoff: the
# SAME default bench.py resolves when JAX_COMPILATION_CACHE_DIR is unset.
sys.path.insert(0, REPO)
from bench import CACHE_DIR as CACHE  # noqa: E402
from _common import run_watcher  # noqa: E402

MATRIX = [
    # (name, argv after `python`, timeout_s), judged metrics first.
    # 1. The driver's exact end-of-round invocation (tiny64 30 steps):
    #    banks the headline AND warms .jax_cache for the judged bench.
    ("tiny64_train", ["bench.py"], 1800),
    # 2. BASELINE metric 2 (DDPM 256-step sec/view) — never landed on TPU.
    ("sample_tiny64_256", ["bench.py", "sample", "tiny64", "256"], 2400),
    # 3. The north-star config: compile-only analyze FIRST (validates the
    #    16G fit claim via memory_analysis even if the train bench then
    #    fails, and its cached executable warms the train compile), then
    #    the first-ever paper256 execution.
    ("analyze_paper256", ["bench.py", "analyze", "paper256"], 3600),
    ("paper256_train", ["bench.py", "paper256", "10"], 5400),
    ("sample_base128_256", ["bench.py", "sample", "base128", "256"], 2400),
    # 4. Pallas kernel A/B on hardware (VERDICT r3 item 4). Defaults:
    #    flash='auto' (ON on TPU), fused-GN=False (OFF) — so the pairs are
    #    (default vs flash-off) and (fused-on vs default).
    ("base128_train", ["bench.py", "base128", "20"], 2400),
    ("tiny64_noflash", ["bench.py", "tiny64", "30",
                        "model.use_flash_attention=False"], 1800),
    ("tiny64_fusedgn", ["bench.py", "tiny64", "30",
                        "model.use_fused_groupnorm=True"], 1800),
    ("base128_noflash", ["bench.py", "base128", "20",
                         "model.use_flash_attention=False"], 2400),
    ("base128_fusedgn", ["bench.py", "base128", "20",
                         "model.use_fused_groupnorm=True"], 2400),
    ("base128_bs16", ["bench.py", "base128", "20",
                      "train.batch_size=16"], 2400),
    # Fast-sampler points for the speed/quality story.
    ("sample_dpmpp32_tiny64", ["bench.py", "sample", "tiny64", "32",
                               "diffusion.sampler=dpm++"], 1800),
    ("sample_ar_tiny64", ["bench.py", "sample-ar", "tiny64", "8"], 2400),
    # 5. The 20k-step 64px quality run (VERDICT r3 item 2): held-out PSNR
    #    must clear the ~9.7 dB mean-image floor decisively (≥18 dB bar).
    ("quality_tpu_64px", ["tools/quality_run.py",
                          os.path.join("results", "quality_tpu_r04"),
                          "20000", "64"], 14400),
    # Sampler quality/speed table on that run's retained checkpoint.
    ("sampler_comparison_quality64",
     ["tools/sampler_comparison.py", "results/quality_tpu_r04/work/val",
      "results/quality_tpu_r04/sampler_comparison.json",
      "--config", "results/quality_tpu_r04/work/config.json",
      "--num-instances", "6", "--views-per-instance", "2"], 3600),
    # 6. k=2 conditioning vs the k=1 baseline (VERDICT r3 item 8) at
    #    matched budget/size: does a second conditioning frame lift
    #    held-out PSNR? (extra argv → quality_run.py config overrides).
    ("quality_tpu_k2", ["tools/quality_run.py",
                        os.path.join("results", "quality_tpu_r04_k2"),
                        "8000", "64", "model.num_cond_frames=2"], 10800),
    ("quality_tpu_k1_matched", ["tools/quality_run.py",
                                os.path.join("results",
                                             "quality_tpu_r04_k1m"),
                                "8000", "64"], 10800),
    ("profile_base128", ["bench.py", "profile", "base128", "5"], 2400),
]


if __name__ == "__main__":
    max_wait_h = float(sys.argv[1]) if len(sys.argv) > 1 else 11.0
    run_watcher(OUT, MATRIX, max_wait_h, CACHE)
