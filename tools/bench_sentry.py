"""Perf-regression sentry over the banked BENCH_r*/MULTICHIP_r* rounds.

The bench archives are append-only JSON snapshots the round driver
banks at the repo root; until now nothing READ them adversarially —
BENCH_r09 landed at vs_baseline=0.973 (a 2.7% regression against the
CPU-lane trajectory) with rc=0 and nobody noticed. This tool judges the
NEWEST judgeable round of each trajectory against the rolling median of
its predecessors and exits loudly on a regression:

  - rc 0: newest round of every trajectory is healthy (or nothing is
    judgeable yet — an empty archive is not a regression);
  - rc ``REGRESSION_RC`` (4): the newest judgeable round regressed.
    DISTINCT from bench.py's rc=3 (infra refusal: backend probe failed,
    nothing was measured) — a sentry trip means the bench RAN and the
    number got worse, which is a different on-call page.

Judging rules:

  - BENCH_r*: a round is judgeable when rc==0 and ``parsed`` carries a
    numeric ``vs_baseline`` (rc=3/124 probe/timeout rounds with
    ``parsed: null`` are infra, skipped with a note). The newest
    judgeable round regresses when vs_baseline < 1.0 (slower than its
    own baseline — absolute) OR vs_baseline < median(prior judgeable
    rounds) * (1 - tolerance) (drifting below its own trajectory).
  - MULTICHIP_r*: no parsed metric to compare, so the contract is
    judged instead: rc==0 rounds regress when ok!=true, skipped==true,
    or n_devices shrank below the largest previously demonstrated mesh.

Usage:
    python tools/bench_sentry.py                  # judge repo-root archives
    python tools/bench_sentry.py --dir DIR --json
    python tools/bench_sentry.py --fresh-vs 0.98  # judge an un-banked
                                                  # datapoint as round +1

bench.py runs this in-process after emitting its judged line (exits 4
only under NVS3D_BENCH_SENTRY=1 so archived trajectories keep their rc
semantics), and tools/tpu_bench_watch.py prints the verdict after a
matrix completes. tests/test_bench_sentry.py pins the rc contract
against synthetic trajectories and the real r01–r09 archive.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rc=3 is bench.py's "infra refused to measure"; the sentry's "measured
# and got slower" must never be conflated with it.
REGRESSION_RC = 4
DEFAULT_TOLERANCE_PCT = 2.0

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def load_rounds(dirpath: str, prefix: str) -> List[dict]:
    """[{round, path, doc}] for ``{prefix}_r*.json``, oldest first.
    Unreadable/torn files become unjudgeable rounds, not crashes."""
    out = []
    for path in glob.glob(os.path.join(dirpath, f"{prefix}_r*.json")):
        m = _ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            doc = None
        out.append({"round": int(m.group(1)), "path": path, "doc": doc})
    out.sort(key=lambda r: r["round"])
    return out


def bench_verdicts(rounds: List[dict],
                   tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
                   fresh_vs: Optional[float] = None) -> List[dict]:
    """Per-round verdicts over a BENCH trajectory. ``fresh_vs`` judges
    an un-banked datapoint (the round bench.py just measured) as the
    newest round without writing it anywhere."""
    points = []
    for r in rounds:
        doc = r["doc"] or {}
        parsed = doc.get("parsed") or {}
        vs = parsed.get("vs_baseline")
        if doc.get("rc") != 0 or not isinstance(vs, (int, float)):
            points.append({
                "round": r["round"], "judged": False,
                "note": (f"rc={doc.get('rc')}"
                         + ("" if parsed else ", parsed=null")
                         + " — infra, not judged")})
            continue
        points.append({"round": r["round"], "judged": True,
                       "vs_baseline": float(vs),
                       "lane": parsed.get("lane")
                       or parsed.get("platform")})
    if fresh_vs is not None:
        last = points[-1]["round"] if points else 0
        points.append({"round": last + 1, "judged": True,
                       "vs_baseline": float(fresh_vs), "lane": "fresh"})
    prior: List[float] = []
    for p in points:
        if not p["judged"]:
            continue
        vs = p["vs_baseline"]
        floor = None
        if prior:
            floor = statistics.median(prior) * (1.0
                                                - tolerance_pct / 100.0)
        p["median_prior"] = (round(statistics.median(prior), 3)
                             if prior else None)
        p["regressed"] = bool(vs < 1.0
                              or (floor is not None and vs < floor))
        why = []
        if vs < 1.0:
            why.append(f"vs_baseline {vs} < 1.0")
        if floor is not None and vs < floor:
            why.append(f"{vs} < median({p['median_prior']}) "
                       f"- {tolerance_pct:g}%")
        p["note"] = "; ".join(why) if why else "ok"
        prior.append(vs)
    return points


def multichip_verdicts(rounds: List[dict]) -> List[dict]:
    """MULTICHIP rounds carry no parsed metric; the judged contract is
    ok/skipped/n_devices (the mesh must not silently shrink)."""
    points = []
    best_devices = 0
    for r in rounds:
        doc = r["doc"] or {}
        if doc.get("rc") != 0:
            points.append({"round": r["round"], "judged": False,
                           "note": f"rc={doc.get('rc')} — infra, "
                                   "not judged"})
            continue
        n_dev = int(doc.get("n_devices") or 0)
        ok = bool(doc.get("ok"))
        skipped = bool(doc.get("skipped"))
        why = []
        if not ok:
            why.append("ok=false")
        if skipped:
            why.append("skipped=true")
        if best_devices and n_dev < best_devices:
            why.append(f"n_devices shrank {best_devices} -> {n_dev}")
        points.append({"round": r["round"], "judged": True,
                       "n_devices": n_dev, "ok": ok, "skipped": skipped,
                       "regressed": bool(why),
                       "note": "; ".join(why) if why else "ok"})
        best_devices = max(best_devices, n_dev)
    return points


def doctor_attribution(prior_docs: List[dict],
                       newest_doc: Optional[dict]) -> dict:
    """Trip attribution, delegated to the obs regression doctor
    (novel_view_synthesis_3d_tpu/obs/doctor.py — the ranked diagnosis
    engine this tool's ad-hoc attribute_regression grew into). Returns
    {"summary": one-liner or None, "findings": ranked list} so the
    rc=4 page can embed the doctor's top findings, not just one line."""
    if not newest_doc:
        return {"summary": None, "findings": []}
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    try:
        from novel_view_synthesis_3d_tpu.obs import doctor as doctor_lib
    except ImportError:
        return {"summary": ("obs.doctor unavailable (package not "
                            "importable from this checkout) — no "
                            "attribution"), "findings": []}
    return doctor_lib.attribute_fresh(prior_docs, newest_doc)


def judge(dirpath: str,
          tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
          fresh_vs: Optional[float] = None,
          fresh_doc: Optional[dict] = None) -> dict:
    """Whole-archive verdict: ``regressed`` is True iff the NEWEST
    judgeable round of either trajectory regressed (older regressions
    are history — they already had their round to page). ``fresh_doc``
    (the judged record bench.py just built, when judging ``fresh_vs``)
    feeds the trip attribution its span/costmap telemetry."""
    rounds = load_rounds(dirpath, "BENCH")
    bench = bench_verdicts(rounds, tolerance_pct, fresh_vs=fresh_vs)
    multichip = multichip_verdicts(load_rounds(dirpath, "MULTICHIP"))

    def newest(points):
        judged = [p for p in points if p["judged"]]
        return judged[-1] if judged else None

    nb, nm = newest(bench), newest(multichip)
    attribution = None
    doctor: List[dict] = []
    if nb and nb["regressed"]:
        judged_docs = [(r["doc"] or {}).get("parsed") or {}
                       for r in rounds
                       if (r["doc"] or {}).get("rc") == 0]
        if fresh_vs is not None:
            diag = doctor_attribution(judged_docs, fresh_doc)
        elif judged_docs:
            diag = doctor_attribution(judged_docs[:-1], judged_docs[-1])
        else:
            diag = {"summary": None, "findings": []}
        attribution = diag["summary"]
        doctor = diag["findings"]
    return {
        "bench": bench,
        "multichip": multichip,
        "newest_bench": nb,
        "newest_multichip": nm,
        "regressed": bool((nb and nb["regressed"])
                          or (nm and nm["regressed"])),
        "attribution": attribution,
        "doctor": doctor,
        "tolerance_pct": tolerance_pct,
    }


def _print_points(label: str, points: List[dict]) -> None:
    print(f"{label}:")
    if not points:
        print("  (no rounds)")
    for p in points:
        if not p["judged"]:
            print(f"  r{p['round']:02d}  -        SKIP   {p['note']}")
            continue
        flag = "REGRESS" if p["regressed"] else "ok"
        val = (f"{p['vs_baseline']:.3f}" if "vs_baseline" in p
               else f"{p['n_devices']}dev")
        print(f"  r{p['round']:02d}  {val:<8s} {flag:<6s} {p['note']}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--dir", default=REPO,
                        help="archive dir holding BENCH_r*.json / "
                             "MULTICHIP_r*.json (default: repo root)")
    parser.add_argument("--tolerance-pct", type=float,
                        default=float(os.environ.get(
                            "NVS3D_SENTRY_TOLERANCE_PCT",
                            DEFAULT_TOLERANCE_PCT)),
                        help="allowed drift below the rolling median "
                             "before flagging (default 2)")
    parser.add_argument("--fresh-vs", type=float, default=None,
                        help="judge this un-banked vs_baseline as the "
                             "newest BENCH round")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    verdict = judge(args.dir, args.tolerance_pct,
                    fresh_vs=args.fresh_vs)
    if args.json:
        print(json.dumps(verdict))
    else:
        _print_points("BENCH", verdict["bench"])
        _print_points("MULTICHIP", verdict["multichip"])
        print("verdict: "
              + ("REGRESSION (newest round below trajectory)"
                 if verdict["regressed"] else "healthy"))
        if verdict["regressed"] and verdict.get("attribution"):
            print(f"attribution: {verdict['attribution']}")
        # Doctor embedding: the rc=4 page carries the top ranked
        # findings, so the on-call reads WHAT moved without re-running
        # anything.
        for i, f in enumerate(verdict.get("doctor") or [], 1):
            if i > 3:
                break
            print(f"doctor {i}. [{f.get('severity', '?').upper()}] "
                  f"{f.get('title', '')}")
    return REGRESSION_RC if verdict["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
