"""Trained-weight parity: train the `reference` preset, export, and verify
forward parity against the ACTUAL reference model source (VERDICT r2 item 6).

The golden tests (tests/test_reference_ckpt.py) pin parity at *random init*;
init-scale weights can hide drift in branches that only matter once weights
leave the init distribution (e.g. GroupNorm statistics interacting with
grown activations, attention logit scales). So: train this repo's model a
few hundred steps, `export_reference_params`, feed the exported tree to the
reference's own `model/xunet.py` (run under current flax with the visu3d
shim from tools/make_reference_goldens.py), and require the two models to
agree on a fixed batch to float tolerance.

Writes results/parity_r03/trained_parity.json (steps, loss curve endpoints,
max abs/rel forward deviation) and a fresh golden
tests/golden/reference_xunet_trained.npz so the parity-on-trained-weights
claim stays testable WITHOUT the reference checkout.

Usage: python tools/trained_parity.py [steps]   (default 300; CPU-friendly,
16px inputs like the goldens)
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT_DIR = os.path.join(REPO, "results", "parity_r03")
GOLDEN_OUT = os.path.join(REPO, "tests", "golden",
                          "reference_xunet_trained.npz")


def main() -> None:
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 300

    from _common import init_jax_env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    init_jax_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import make_reference_goldens as mrg
    from novel_view_synthesis_3d_tpu.compat.reference_ckpt import (
        export_reference_params)
    from novel_view_synthesis_3d_tpu.config import get_preset
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch
    from novel_view_synthesis_3d_tpu.diffusion import make_schedule
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib
    from novel_view_synthesis_3d_tpu.train.state import create_train_state
    from novel_view_synthesis_3d_tpu.train.step import make_train_step
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch

    # --- train the reference-preset model on 16px synthetic batches -------
    cfg = get_preset("reference").override(**{
        "data.img_sidelength": 16,
        "train.batch_size": 8,
        "train.num_steps": steps,
        # Plain SGD-shaped run: EMA off so the exported tree is exactly the
        # online params the loss curve describes.
        "train.ema_decay": 0.0,
    })
    cfg.validate()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1, model=1, seq=1),
                              devices=jax.devices()[:1])
    schedule = make_schedule(cfg.diffusion)
    model = XUNet(cfg.model)
    batch = make_example_batch(batch_size=cfg.train.batch_size,
                               sidelength=16, seed=0)
    state = create_train_state(cfg.train, model, _sample_model_batch(batch))
    state = mesh_lib.replicate(mesh, state)
    step = make_train_step(cfg, model, schedule, mesh)
    losses = []
    t0 = time.time()
    for i in range(steps):
        # Fresh synthetic batch per step so the weights travel a real
        # optimization trajectory instead of memorizing one batch.
        b = make_example_batch(batch_size=cfg.train.batch_size,
                               sidelength=16, seed=i)
        state, m = step(state, mesh_lib.shard_batch(mesh, b))
        if i % 25 == 0 or i == steps - 1:
            loss = float(jax.device_get(m["loss"]))
            losses.append((i, loss))
            print(f"step {i}: loss {loss:.4f}", flush=True)
    train_s = time.time() - t0
    params = jax.device_get(state.params)

    # --- export to reference format, run the reference source on it -------
    exported = export_reference_params(params)
    mrg._install_visu3d_shim()
    ref = mrg._load_reference_model()
    ref_model = ref.XUNet()  # reference defaults == `reference` preset
    eval_batch = mrg.make_batch(B=2, S=16, seed=123)
    cond_mask = np.array([1.0, 0.0], np.float32)
    jb = {k: jnp.asarray(v) for k, v in eval_batch.items()}
    ref_out = np.asarray(ref_model.apply(
        {"params": jax.tree.map(jnp.asarray, exported)}, jb,
        cond_mask=jnp.asarray(cond_mask), train=False))
    our_out = np.asarray(model.apply(
        {"params": jax.tree.map(jnp.asarray, params)}, jb,
        cond_mask=jnp.asarray(cond_mask), train=False))

    abs_dev = float(np.max(np.abs(ref_out - our_out)))
    rel_dev = float(np.max(np.abs(ref_out - our_out) /
                           (np.abs(ref_out) + 1e-6)))
    scale = float(np.max(np.abs(ref_out)))
    # Scale-aware bound: element-wise rtol alone rejects float-reassociation
    # noise at near-zero outputs (FrameConv reduces in a different order
    # than the reference's 3-D conv), so compare against the OUTPUT SCALE:
    # 1e-4 × max|out| is ~10 float32 ulps of the largest activation.
    ok = bool(abs_dev <= 1e-4 * scale)
    print(f"trained-weight parity: max|Δ|={abs_dev:.3e} "
          f"(output scale {scale:.3e}), max rel={rel_dev:.3e}, ok={ok}")

    # --- persist: JSON artifact + a trained golden for offline testing ----
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "trained_parity.json"), "w") as fh:
        json.dump({
            "steps": steps,
            "train_seconds": round(train_s, 1),
            "loss_first": losses[0][1],
            "loss_last": losses[-1][1],
            "max_abs_deviation": abs_dev,
            "max_rel_deviation": rel_dev,
            "output_scale": scale,
            "parity_ok": ok,
            "platform": jax.default_backend(),
        }, fh, indent=1)

    if not ok:
        # Do NOT touch the committed golden on failure: a drifted npz in
        # the working tree could ride along into an unrelated commit. The
        # JSON diagnostic above is the failure record.
        raise SystemExit("PARITY FAILURE on trained weights — golden NOT "
                         "rewritten")

    flat = {}
    def flatten(tree, prefix=""):
        for k, v in tree.items():
            p = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                flatten(v, p)
            else:
                flat[f"param:{p}"] = np.asarray(v)
    flatten(exported)
    arrays = dict(flat)
    for k, v in eval_batch.items():
        arrays[f"batch:{k}"] = v
    arrays["cond_mask"] = cond_mask
    arrays["output"] = ref_out  # the REFERENCE source's output
    np.savez_compressed(GOLDEN_OUT, **arrays)
    print(f"wrote {GOLDEN_OUT} "
          f"({os.path.getsize(GOLDEN_OUT) / 1e6:.2f} MB)")


if __name__ == "__main__":
    main()
