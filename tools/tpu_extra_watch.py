"""Phase-2 TPU experiments: run after tools/tpu_bench_watch.py finishes.

Waits until the phase-1 watcher's log says the matrix is finished (or its
deadline passed), then reuses its probe/run machinery on a second matrix:
batch-scaling on base128 and the fast dpm++ sampling benches.

Usage: python tools/tpu_extra_watch.py [max_wait_h]
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import tpu_bench_watch as tbw  # noqa: E402

PHASE1_LOG = os.path.join(tbw.OUT, "log.txt")

EXTRA = [
    ("base128_bs16", ["bench.py", "base128", "20",
                      "train.batch_size=16"], 2400),
    ("sample_dpmpp32_tiny64", ["bench.py", "sample", "tiny64", "32",
                               "diffusion.sampler=dpm++"], 2400),
    ("sample_dpmpp32_base128", ["bench.py", "sample", "base128", "32",
                                "diffusion.sampler=dpm++"], 2400),
    ("sample_base128_256", ["bench.py", "sample", "base128", "256"], 2400),
    # bf16 A/B on the f32 tiny64 preset (train + 256-step sample): the
    # compute-dtype lever measured at the small end of the ladder.
    ("tiny64_bf16_train", ["bench.py", "tiny64", "30",
                           "model.dtype=bfloat16"], 1800),
    ("sample_bf16_tiny64_256", ["bench.py", "sample", "tiny64", "256",
                                "model.dtype=bfloat16"], 2400),
    # Sampler quality/speed table on the checkpoint the phase-1 quality run
    # retained under its out_dir; --config reloads the exact resolved model
    # shape that run trained (checkpoint dir included). Runs as its own
    # process AFTER quality_run exited — libtpu is single-process-exclusive.
    ("sampler_comparison_quality64",
     ["tools/sampler_comparison.py", "results/quality_tpu_r02/work/val",
      "results/quality_tpu_r02/sampler_comparison.json",
      "--config", "results/quality_tpu_r02/work/config.json",
      "--num-instances", "6", "--views-per-instance", "2"], 3600),
]


def phase1_running() -> bool:
    # Module-name substring, not a path: matches any launch spelling
    # ("python tools/tpu_bench_watch.py", "cd tools && python
    # tpu_bench_watch.py", ...). Our own cmdline (tpu_extra_watch.py)
    # does not contain it.
    try:
        return subprocess.run(
            ["pgrep", "-f", "tpu_bench_watch"],
            stdout=subprocess.DEVNULL).returncode == 0
    except OSError:
        return False  # no pgrep: assume dead rather than waiting forever


PIDFILE = os.path.join(tbw.OUT, "extra_watch.pid")


def another_phase2_running() -> bool:
    """True if a DIFFERENT tpu_extra_watch process is alive (double-launch
    guard: two instances would run the EXTRA matrix concurrently on one
    chip and truncate each other's result files). Pidfile-based: a pgrep
    pattern would match the `sh -c` wrapper of our own launch command."""
    try:
        pid = int(open(PIDFILE).read().strip())
    except (OSError, ValueError):
        return False
    if pid == os.getpid():
        return False
    try:
        cmdline = open(f"/proc/{pid}/cmdline", "rb").read().decode(
            "utf-8", "replace")
    except OSError:
        return False  # stale pidfile: process is gone
    return "tpu_extra_watch" in cmdline and "sh" != os.path.basename(
        cmdline.split("\0", 1)[0])


_START = time.time()
_SEEN_PHASE1 = False
GRACE_S = 600.0


def phase1_finished() -> bool:
    # A dead phase-1 process is finished no matter what its log says (it
    # may have been killed mid-matrix without writing a terminal marker) —
    # the process check also covers "phase-1 never ran at all", since by
    # the time this is polled our own tbw.log() banner has already created
    # the log file.
    global _SEEN_PHASE1
    if phase1_running():
        _SEEN_PHASE1 = True
    elif _SEEN_PHASE1 or time.time() - _START > GRACE_S:
        # Either we watched it die, or it never appeared within the grace
        # window. The grace period covers the launch race: phase-2 started
        # before (or during a restart gap of) phase-1 must not conclude
        # "finished" and run its matrix concurrently on the
        # single-process-exclusive TPU.
        return True
    else:
        return False
    try:
        text = open(PHASE1_LOG).read()
    except OSError:
        return True
    # Only count markers after the LAST "watching for TPU" banner (earlier
    # sessions' deadline lines would otherwise satisfy the check).
    i = text.rfind("watching for TPU")
    tail = text if i < 0 else text[i:]
    return "matrix finished" in tail or "deadline reached" in tail


def main() -> None:
    max_wait_h = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    if another_phase2_running():
        print("another tpu_extra_watch instance is alive — exiting",
              flush=True)
        return
    os.makedirs(tbw.OUT, exist_ok=True)
    with open(PIDFILE, "w") as fh:
        fh.write(str(os.getpid()))
    tbw.MATRIX = EXTRA
    tbw.log(f"phase-2: waiting for phase-1 matrix (max {max_wait_h:.1f}h)")
    deadline = time.time() + max_wait_h * 3600
    while time.time() < deadline and not phase1_finished():
        time.sleep(120)
    if not phase1_finished():
        tbw.log("phase-2: gave up waiting for phase-1")
        return
    remaining_h = max((deadline - time.time()) / 3600, 0.1)
    sys.argv = [sys.argv[0], f"{remaining_h:.2f}"]
    tbw.main()


if __name__ == "__main__":
    main()
