"""Command-line entry points: train / sample / serve / eval / prep / pack
/ config.

The reference's entry points are two hardwired scripts with zero flags
(`/root/reference/train.py:174-176` — dataset path literal 'cars_train_val';
`/root/reference/sampling.py` — a flat script with an infinite cv2.imshow
loop). Here every capability is a subcommand of

    python -m novel_view_synthesis_3d_tpu <command> [options] [key=value ...]

with config presets (BASELINE.json ladder) + dotted-key overrides, PNG output
instead of GUI display, and checkpoint restore that actually matches what
training saves (the reference's prefixes don't — SURVEY.md §3.5).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

import numpy as np

from novel_view_synthesis_3d_tpu.config import (
    Config, PRESET_NAMES, get_preset)
from novel_view_synthesis_3d_tpu.utils.xla_cache import (
    setup_compilation_cache)


def build_config(args, overrides: Sequence[str]) -> Config:
    """preset → optional JSON file → dotted CLI overrides, later wins."""
    if getattr(args, "config", None):
        with open(args.config) as fh:
            cfg = Config.from_json(fh.read())
        if getattr(args, "preset", None):
            raise SystemExit("--preset and --config are mutually exclusive")
    else:
        cfg = get_preset(args.preset or "tiny64")
    if overrides:
        try:
            cfg = cfg.apply_cli(overrides)
        except KeyError as e:
            raise SystemExit(f"config error: {e.args[0]}") from e
    try:
        return cfg.validate()
    except ValueError as e:
        raise SystemExit(str(e)) from e


def _split_overrides(rest: List[str]) -> List[str]:
    bad = [a for a in rest if "=" not in a]
    if bad:
        raise SystemExit(f"unrecognized arguments: {' '.join(bad)} "
                         "(overrides look like model.ch=64)")
    return rest


# ---------------------------------------------------------------------------
# train
# ---------------------------------------------------------------------------
def cmd_train(args, overrides: List[str]) -> int:
    from novel_view_synthesis_3d_tpu.utils import faultinject

    armed = faultinject.armed()
    if armed:
        # Loud, not fatal: chaos drills on real hardware are legitimate,
        # but a production run must never discover injected faults only by
        # dying — and injected anomalies in metrics.csv must be
        # distinguishable from real ones.
        print(f"warning: FAULT INJECTION ARMED ({', '.join(armed)}) — this "
              "run will experience deliberate failures; unset NVS3D_FI_* "
              "for production training")
    cfg = build_config(args, overrides)
    if args.folder:
        cfg = cfg.override(**{"data.root_dir": args.folder})

    if getattr(args, "supervise", False):
        # Supervisor mode: hold no JAX state in THIS process (it must stay
        # responsive while a child wedges); the child runs the same train
        # command minus --supervise and is restarted on crash or stall.
        from novel_view_synthesis_3d_tpu.train.supervisor import (
            supervise, train_child_argv)

        return supervise(
            train_child_argv(args, overrides),
            results_folder=cfg.train.results_folder,
            max_restarts=cfg.train.max_restarts)

    # Fail fast on an unreachable backend: a structured sub-60s diagnosis
    # (exit code 3 + reason line) instead of a silent hang inside the
    # first jax call (BENCH_r0* postmortems). CPU runs skip the probe.
    from novel_view_synthesis_3d_tpu.parallel import dist
    from novel_view_synthesis_3d_tpu.utils.watchdog import EXIT_STALL

    dist.require_backend()
    # Persistent compilation cache BEFORE the first jitted dispatch:
    # until this call only bench/tests/tools had it wired, so every CLI
    # train run paid the full XLA compile (utils/xla_cache.py).
    setup_compilation_cache()

    if cfg.train.ladder:
        # Resolution ladder (train/ladder.py): consecutive rung runs over
        # one checkpoint_dir; rung selection and mid-rung fast-forward
        # both derive from the restored step, so plain re-invocation
        # resumes exactly where the last run stopped.
        from novel_view_synthesis_3d_tpu.train.ladder import run_ladder

        last = run_ladder(cfg, use_grain=not args.no_grain)
        return (EXIT_STALL if last is not None and last.stalled else 0)

    from novel_view_synthesis_3d_tpu.train.trainer import Trainer

    trainer = Trainer(config=cfg, use_grain=not args.no_grain)
    trainer.train()
    if trainer.stalled:
        # Distinct exit code: the supervisor (or any operator tooling)
        # can tell "completed" from "checkpointed and bailed on a stall".
        return EXIT_STALL
    return 0


# ---------------------------------------------------------------------------
# sample
# ---------------------------------------------------------------------------
def _restore_params(cfg: Config, model, sample_batch: dict, step: Optional[int],
                    reference_ckpt: Optional[str] = None):
    """Latest (or `step`) checkpoint → params (EMA if trained with EMA).

    `reference_ckpt`: path to a reference-format flax msgpack file (e.g.
    the published pretrained model) — imported via compat/reference_ckpt.py
    instead of reading this repo's Orbax checkpoints. Use with
    `--preset reference` so the model carries the quirks the weights were
    trained under.
    """
    import jax

    if reference_ckpt is not None:
        # Before the Orbax/optax imports below — this path needs neither.
        from novel_view_synthesis_3d_tpu.compat.reference_ckpt import (
            load_reference_checkpoint)
        if cfg.model.groupnorm_per_frame or cfg.model.attn_out_proj:
            print("warning: --reference-ckpt weights were trained under the "
                  "reference quirks (shared-frame GroupNorm stats, no attn "
                  "out-projection) but the active config disables them — "
                  "outputs will differ from the reference; use "
                  "--preset reference")
        return load_reference_checkpoint(reference_ckpt), 0

    from novel_view_synthesis_3d_tpu.train.checkpoint import CheckpointManager
    from novel_view_synthesis_3d_tpu.train.state import create_train_state

    template = create_train_state(cfg.train, model, sample_batch)
    if cfg.train.ema_host and cfg.train.ema_decay > 0:
        # Host-EMA checkpoints carry the (host f32) EMA tree in ema_params
        # even though the live TrainState keeps it None — mirror that
        # structure or StandardRestore rejects the tree.
        template = template.replace(ema_params=jax.tree.map(
            lambda p: np.zeros(p.shape, np.float32), template.params))
    ckpt = CheckpointManager(cfg.train.checkpoint_dir)
    if ckpt.latest_step() is None:
        raise FileNotFoundError(
            f"no checkpoint under {cfg.train.checkpoint_dir!r} — train first "
            "(the reference fails the same way: sampling.py:111-112)")
    # Growth-compat restore (train/ladder.py): a pre-num_classes
    # checkpoint loads into the grown template with the category table's
    # zero-init spliced in (asserted neutral).
    from novel_view_synthesis_3d_tpu.train.ladder import restore_with_growth

    state = restore_with_growth(ckpt, template, step=step)
    ckpt.close()
    params = state.ema_params if state.ema_params is not None else state.params
    return jax.device_get(params), int(jax.device_get(state.step))


def cmd_sample(args, overrides: List[str]) -> int:
    from novel_view_synthesis_3d_tpu.parallel import dist

    dist.require_backend()  # sub-60s structured failure on a dead tunnel
    setup_compilation_cache()  # warm repeat samples skip the XLA compile

    import jax
    import jax.numpy as jnp

    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
    from novel_view_synthesis_3d_tpu.diffusion.schedules import sampling_schedule
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.sample.ddpm import (
        autoregressive_generate, make_sampler)
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch
    from novel_view_synthesis_3d_tpu.utils.geometry import (
        interpolate_poses, orbit_poses)
    from novel_view_synthesis_3d_tpu.utils.images import (
        save_animation, save_image, save_image_grid)

    if args.stochastic and args.denoise_gif:
        # Fail fast — before dataset IO and checkpoint restore.
        raise SystemExit("--denoise-gif is not supported with --stochastic")
    if args.trajectory and (args.stochastic or args.denoise_gif):
        raise SystemExit(
            "--trajectory is the serving-grade device-resident orbit "
            "path (stepper ring + frame bank); it does not combine with "
            "--stochastic (the offline autoregressive sampler) or "
            "--denoise-gif")
    if args.pool_views < 1:
        # Unconditional: with --stochastic, 0/negative would silently
        # behave as 1 (the seeding branch only fires for pool_views > 1).
        raise SystemExit("--pool-views must be >= 1")
    if args.pool_views != 1 and not args.stochastic:
        raise SystemExit("--pool-views requires --stochastic (it seeds the "
                         "stochastic-conditioning pool)")
    cfg = build_config(args, overrides)
    dcfg = cfg.diffusion
    if args.trajectory:
        args.num_views = args.trajectory
    ds = SRNDataset(args.folder or cfg.data.root_dir,
                    img_sidelength=cfg.data.img_sidelength)
    inst = ds.instances[args.instance % ds.num_instances]
    x, pose1 = inst.view(args.cond_view % len(inst))

    # Target poses: dataset ground-truth poses, a synthetic orbit, or a
    # smooth slerp path through the instance's dataset poses.
    if args.poses == "dataset":
        idcs = [v for v in range(len(inst))
                if v != args.cond_view % len(inst)][:args.num_views]
        poses2 = np.stack([inst.view(v)[1] for v in idcs])
    elif args.poses == "interp":
        # Poses only — inst.view() would decode every RGB just to drop it.
        from novel_view_synthesis_3d_tpu.data.srn import load_pose
        keyframes = np.stack([load_pose(p) for p in inst.pose_paths])
        poses2 = interpolate_poses(keyframes, args.num_views)
    else:
        radius = float(np.linalg.norm(pose1[:3, 3]))
        poses2 = orbit_poses(args.num_views, radius=radius,
                             elevation=args.elevation)

    model = XUNet(cfg.model)
    first_view = {
        "x": jnp.asarray(x)[None],
        "R1": jnp.asarray(pose1[:3, :3])[None],
        "t1": jnp.asarray(pose1[:3, 3])[None],
        "K": jnp.asarray(inst.K)[None],
    }
    sample_batch = _sample_model_batch({
        "x": x[None], "target": x[None],
        "R1": pose1[None, :3, :3], "t1": pose1[None, :3, 3],
        "R2": poses2[0][None, :3, :3], "t2": poses2[0][None, :3, 3],
        "K": inst.K[None],
    })
    params, step = _restore_params(cfg, model, sample_batch, args.step,
                                   reference_ckpt=args.reference_ckpt)
    print(f"restored checkpoint at step {step}")

    schedule = sampling_schedule(dcfg, args.sample_steps)
    key = jax.random.PRNGKey(args.seed)

    if args.trajectory:
        # Serving-grade orbit: ONE TrajectoryRequest through the stepper
        # ring — the frame bank stays device-resident, each denoise step
        # conditions stochastically on it, frames stream back as they
        # finish (docs/DESIGN.md "Trajectory serving & stochastic
        # conditioning"). The offline twin of `nvs3d serve --trajectory`.
        import dataclasses

        from novel_view_synthesis_3d_tpu.sample.service import (
            SamplingService)
        from novel_view_synthesis_3d_tpu.utils.images import (
            save_image_strip)

        scfg = cfg.serve
        if scfg.scheduler != "step" or scfg.k_max < 1:
            scfg = dataclasses.replace(scfg, scheduler="step",
                                       k_max=max(8, scfg.k_max))
            print(f"note: --trajectory enables serve.scheduler='step', "
                  f"serve.k_max={scfg.k_max} (set serve.k_max to size "
                  "the conditioning window)")
        os.makedirs(args.out, exist_ok=True)
        service = SamplingService(model, params, dcfg, scfg,
                                  results_folder=args.out,
                                  model_version=f"ckpt:{step}")
        try:
            ticket = service.submit_trajectory(
                {"x": x, "R1": pose1[:3, :3], "t1": pose1[:3, 3],
                 "K": inst.K},
                poses=poses2, seed=args.seed,
                sample_steps=args.sample_steps)
            frames = []
            for i, img in ticket.frames(timeout=600):
                frames.append(img)
                print(json.dumps({"frame_index": i,
                                  "model_version": ticket.model_version}))
            imgs = np.stack(frames)
        finally:
            service.stop()
        save_image_strip(imgs, os.path.join(args.out, "orbit_strip.png"))
    elif args.stochastic:
        # Autoregressive 3DiM sampling: each generated view joins the
        # conditioning pool for the next (sample/ddpm.py). --pool-views
        # seeds the pool with that many REAL dataset views (cond_view
        # first, then views that are not sampling targets).
        if args.pool_views > 1:
            cand = [args.cond_view % len(inst)]
            targets = set(idcs) if args.poses == "dataset" else set()
            cand += [v for v in range(len(inst))
                     if v not in cand and v not in targets]
            if len(cand) < args.pool_views:
                print(f"note: only {len(cand)} non-target views available "
                      f"for --pool-views {args.pool_views}")
            pool_views = [inst.view(v) for v in cand[:args.pool_views]]
            first_view = {
                "x": jnp.asarray(np.stack([x for x, _ in pool_views]))[None],
                "R1": jnp.asarray(np.stack(
                    [p[:3, :3] for _, p in pool_views]))[None],
                "t1": jnp.asarray(np.stack(
                    [p[:3, 3] for _, p in pool_views]))[None],
                "K": first_view["K"],
            }
        target_poses = {
            "R2": jnp.asarray(poses2[None, :, :3, :3]),
            "t2": jnp.asarray(poses2[None, :, :3, 3]),
        }
        imgs = autoregressive_generate(
            model, schedule, dcfg, params, key, first_view, target_poses)
        imgs = np.asarray(jax.device_get(imgs))[0]  # (N, H, W, 3)
    else:
        # One batched reverse process: the conditioning view broadcasts over
        # all N target poses (same pattern as eval/evaluate.py).
        traj_every = 0
        if args.denoise_gif:
            # Aim for ~32 frames of the reverse process. The sampler accepts
            # any stride (remainder steps are flat-scanned and the final
            # state appended), so a near-uniform stride works for prime step
            # counts too — no divisor hunt, never a single-frame "animation".
            T = schedule.num_timesteps
            traj_every = max(1, round(T / 32))
        sampler = make_sampler(model, schedule, dcfg,
                               trajectory_every=traj_every,
                               trajectory_views=1)
        N = len(poses2)
        cond = {k: jnp.broadcast_to(v, (N,) + v.shape[1:])
                for k, v in first_view.items()}
        cond["R2"] = jnp.asarray(poses2[:, :3, :3])
        cond["t2"] = jnp.asarray(poses2[:, :3, 3])
        out = sampler(params, key, cond)
        if traj_every:
            out, traj = out  # traj is (frames, 1, H, W, 3): view 0 only
            save_animation(
                np.asarray(jax.device_get(traj))[:, 0],
                os.path.join(args.out, "denoise.gif"), fps=args.gif_fps)
        imgs = np.asarray(jax.device_get(out))

    os.makedirs(args.out, exist_ok=True)
    for i, img in enumerate(imgs):
        save_image(img, os.path.join(args.out, f"view_{i:03d}.png"))
    save_image_grid(imgs, os.path.join(args.out, "grid.png"))
    save_image(x, os.path.join(args.out, "cond.png"))
    if args.gif:
        save_animation(imgs, os.path.join(args.out, "orbit.gif"),
                       fps=args.gif_fps)
    print(f"wrote {len(imgs)} views to {args.out}")
    return 0


# ---------------------------------------------------------------------------
# serve
# ---------------------------------------------------------------------------
# Canonical implementation lives in sample/client.py so the CLI client
# and the fleet router (serve/router.py) share one retry/backoff/jitter
# loop; re-exported here because tests and external callers import it
# from cli.
from novel_view_synthesis_3d_tpu.sample.client import (  # noqa: F401
    submit_with_retry)


def cmd_serve(args, overrides: List[str]) -> int:
    """Micro-batched sampling service (sample/service.py).

    Requests come from --requests (a JSON-lines file; each line selects a
    conditioning view and a target pose by dataset index and may override
    seed / sample_steps / guidance_weight / deadline_ms) or, with no
    file, a --num-requests demo sweep over the instance's poses. Every
    request's image lands in --out; a JSON summary line (requests/sec,
    queue-wait and device-time percentiles, program-cache counters)
    closes the run — the serving twin of eval's result line.
    """
    from novel_view_synthesis_3d_tpu.parallel import dist

    dist.require_backend()  # sub-60s structured failure on a dead tunnel
    setup_compilation_cache()  # the warm-traffic contract starts on disk

    import jax

    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.sample.service import (
        Rejected, SamplingService)
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch
    from novel_view_synthesis_3d_tpu.utils.images import save_image

    cfg = build_config(args, overrides)
    ds = SRNDataset(args.folder or cfg.data.root_dir,
                    img_sidelength=cfg.data.img_sidelength)
    model = XUNet(cfg.model)
    inst0 = ds.instances[0]
    x0, pose0 = inst0.view(0)
    sample_batch = _sample_model_batch({
        "x": x0[None], "target": x0[None],
        "R1": pose0[None, :3, :3], "t1": pose0[None, :3, 3],
        "R2": pose0[None, :3, :3], "t2": pose0[None, :3, 3],
        "K": inst0.K[None],
    })
    # int8-requires-registry-staging: a quantized deployment serves
    # gate-probed registry versions only (the PSNR gate scores candidates
    # AT the serving precision, so quantization loss is part of what the
    # gate_margin_db admitted) — a raw checkpoint has no such lineage.
    if cfg.serve.precision == "int8" and not args.registry:
        raise SystemExit(
            "serve.precision='int8' requires --registry: quantized "
            "serving only deploys versions whose promotion gate probed "
            "them at int8 (registry/gate.py) — serve a checkpoint at "
            "'float32'/'bfloat16', or publish + promote it first "
            "(nvs3d registry publish/promote)")
    # Weights: either a checkpoint (the pre-registry path) or a registry
    # channel subscription — the service then HOT-RELOADS whenever the
    # channel pointer moves (registry/watcher.py), with zero downtime.
    store = watcher = None
    if args.registry:
        from novel_view_synthesis_3d_tpu.registry import RegistryStore

        store = RegistryStore(args.registry)
        channel = args.channel or cfg.registry.channel
        vid = store.read_channel(channel)
        if vid is None:
            raise SystemExit(
                f"registry {args.registry!r} channel {channel!r} points at "
                "no version — publish and promote first (nvs3d registry "
                "publish/promote)")
        manifest = store.verify(vid)
        params, step = store.load_params(vid, verify=False), manifest.step
        model_version = vid
        print(f"serving registry version {vid} (step {step}, channel "
              f"{channel})")
    else:
        params, step = _restore_params(cfg, model, sample_batch, args.step,
                                       reference_ckpt=args.reference_ckpt)
        model_version = f"ckpt:{step}"
        print(f"restored checkpoint at step {step}")

    # Multi-chip: one coalesced batch serves data-parallel through the
    # mesh (buckets that divide the data axis shard via shard_batch).
    mesh = None
    if len(jax.devices()) > 1:
        from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.fit_local_mesh(cfg.mesh)

    def build_request(spec: dict) -> tuple:
        """(cond, poses): poses is None for single-frame specs, an
        (N, 4, 4) stack for trajectory specs (`poses` = explicit pose
        matrices, `orbit` = N synthetic orbit poses at the conditioning
        camera's radius)."""
        from novel_view_synthesis_3d_tpu.utils.geometry import orbit_poses

        inst = ds.instances[int(spec.get("instance", 0)) % ds.num_instances]
        cx, cpose = inst.view(int(spec.get("cond_view", 0)) % len(inst))
        poses = None
        if spec.get("poses") is not None:
            poses = np.asarray(spec["poses"], np.float32)
        elif spec.get("orbit"):
            poses = orbit_poses(
                int(spec["orbit"]),
                radius=float(np.linalg.norm(cpose[:3, 3])),
                elevation=float(spec.get("elevation", 0.3)))
        if poses is not None:
            return {"x": cx, "R1": cpose[:3, :3], "t1": cpose[:3, 3],
                    "K": inst.K}, poses
        _, tpose = inst.view(int(spec.get("target_view", 1)) % len(inst))
        return {
            "x": cx, "R1": cpose[:3, :3], "t1": cpose[:3, 3],
            "R2": tpose[:3, :3], "t2": tpose[:3, 3], "K": inst.K,
        }, None

    if args.requests:
        with open(args.requests) as fh:
            specs = [json.loads(ln) for ln in fh if ln.strip()]
    elif args.trajectory:
        # Trajectory demo sweep: each request is an N-frame orbit; the
        # frames stream back per request and land as an orbit PNG strip.
        specs = [{"instance": args.instance + i,
                  "cond_view": args.cond_view, "orbit": args.trajectory,
                  "seed": args.seed + i}
                 for i in range(args.num_requests)]
    else:
        specs = [{"instance": args.instance, "cond_view": args.cond_view,
                  "target_view": i + 1, "seed": args.seed + i}
                 for i in range(args.num_requests)]
    if not specs:
        raise SystemExit("no requests (empty --requests file)")
    wants_traj = any(s.get("poses") is not None or s.get("orbit")
                     for s in specs)
    if wants_traj and (cfg.serve.k_max < 1
                       or cfg.serve.scheduler != "step"):
        import dataclasses

        cfg = dataclasses.replace(cfg, serve=dataclasses.replace(
            cfg.serve, scheduler="step",
            k_max=max(8, cfg.serve.k_max)))
        print(f"note: trajectory requests enable serve.scheduler='step',"
              f" serve.k_max={cfg.serve.k_max} (set serve.k_max to size "
              "the frame bank)")

    os.makedirs(args.out, exist_ok=True)
    # Unified telemetry (obs/): the service's pipeline spans (queue_wait →
    # batch_form → compile/device → respond) land in trace.json next to
    # the request PNGs, and the /metrics endpoint — when obs.metrics_port
    # is set — exposes the same registry the spans' histograms feed.
    from novel_view_synthesis_3d_tpu import obs

    telemetry = obs.RunTelemetry.create(cfg.obs, args.out)
    profiler = (obs.make_profiler(cfg.obs.profile, args.out, cfg.model,
                                  telemetry.bus, telemetry.registry,
                                  unit="dispatch")
                if cfg.obs.enabled else None)
    service = SamplingService(model, params, cfg.diffusion, cfg.serve,
                              mesh=mesh, results_folder=args.out,
                              tracer=telemetry.tracer,
                              flight=telemetry.flight,
                              profiler=profiler,
                              model_version=model_version)
    if telemetry.server is not None:
        # /healthz progress facts: last_dispatch_age_s + the live
        # model_version, so a probe (or the registry rollback runbook)
        # reads the serving plane's heartbeat without scraping.
        telemetry.server.set_health_provider(service.health_snapshot)
    if store is not None:
        from novel_view_synthesis_3d_tpu.registry import RegistryWatcher

        bus = telemetry.bus
        watcher = RegistryWatcher(
            service, store, args.channel or cfg.registry.channel,
            poll_s=cfg.registry.poll_s,
            event_cb=lambda s, kind, detail, version: bus.event(
                s, kind, detail, model_version=version,
                echo="[registry]"))
    # Rolling-restart contract: SIGTERM/SIGINT flips the service into
    # drain mode — new admissions get a retryable reject (clients fail
    # over to a peer), in-flight and queued work finishes, telemetry
    # flushes, and the process exits 0 so the orchestrator's restart
    # counts as clean.
    import signal
    import threading

    drain_requested = threading.Event()

    def _on_term(signum, frame):
        drain_requested.set()
        service.begin_drain(reason=signal.Signals(signum).name)

    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev_handlers[sig] = signal.signal(sig, _on_term)
        except ValueError:
            pass  # non-main thread (embedded use): no signal hooks
    try:
        from novel_view_synthesis_3d_tpu.utils.images import (
            save_image_strip)

        tickets = []
        for i, spec in enumerate(specs):
            if drain_requested.is_set():
                print(f"draining: requests {i}..{len(specs) - 1} not "
                      "submitted")
                break
            try:
                cond, poses = build_request(spec)
                if poses is not None:
                    def _submit(cond=cond, poses=poses, spec=spec, i=i):
                        return service.submit_trajectory(
                            cond, poses=poses,
                            seed=int(spec.get("seed", args.seed + i)),
                            sample_steps=spec.get("sample_steps",
                                                  args.sample_steps),
                            guidance_weight=spec.get("guidance_weight"),
                            deadline_ms=spec.get("deadline_ms"),
                            k_max=spec.get("k_max"),
                            trace_id=spec.get("trace_id"))
                else:
                    def _submit(cond=cond, spec=spec, i=i):
                        return service.submit(
                            cond,
                            seed=int(spec.get("seed", args.seed + i)),
                            sample_steps=spec.get("sample_steps",
                                                  args.sample_steps),
                            guidance_weight=spec.get("guidance_weight"),
                            deadline_ms=spec.get("deadline_ms"),
                            trace_id=spec.get("trace_id"))
                # Brownout/queue-full rejects are retryable with a
                # server-suggested retry_after_s; honor it before giving
                # up on the request.
                tickets.append((i, submit_with_retry(_submit)))
            except Rejected as e:
                print(f"request {i}: rejected ({e})")
        served = 0
        orbits = 0
        for i, ticket in tickets:
            if hasattr(ticket, "frames"):  # TrajectoryTicket: stream
                frames = []
                try:
                    # Per-frame streaming: each response line carries
                    # frame_index + model_version the moment the frame
                    # finishes — clients render while the rest of the
                    # orbit is still on device.
                    for j, img in ticket.frames(timeout=args.timeout):
                        save_image(img, os.path.join(
                            args.out, f"request_{i:04d}_frame_{j:03d}.png"))
                        print(json.dumps({
                            "request": i, "frame_index": j,
                            "model_version": ticket.model_version}))
                        frames.append(img)
                        served += 1
                except Exception as e:
                    print(f"request {i}: failed after {len(frames)} "
                          f"frame(s) ({e})")
                if frames:
                    save_image_strip(np.stack(frames), os.path.join(
                        args.out, f"request_{i:04d}_orbit.png"))
                if len(frames) == ticket.num_frames:
                    orbits += 1
                continue
            try:
                # Bounded wait: a dispatch wedged on the device must
                # surface as a per-request TimeoutError, not an eternal
                # hang (the serving-side analog of the run watchdog).
                img = ticket.result(timeout=args.timeout)
            except Exception as e:
                print(f"request {i}: failed ({e})")
                continue
            save_image(img, os.path.join(args.out, f"request_{i:04d}.png"))
            served += 1
    finally:
        if watcher is not None:
            watcher.stop()
        if drain_requested.is_set():
            # Drain already rejected new admissions; wait (bounded by
            # serve.drain_timeout_s) for the in-flight tail, then stop.
            clean = service.drain(reason="signal")
            print(f"drain {'complete' if clean else 'TIMED OUT'}; "
                  "exiting 0")
        else:
            service.stop()
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)
        telemetry.finalize()  # trace.json + gauges flushed into --out
    summary = dict(service.summary(), served=served,
                   submitted=len(specs), checkpoint_step=step)
    if wants_traj:
        summary["orbits_completed"] = orbits
    print(json.dumps(summary))
    return 0


# ---------------------------------------------------------------------------
# eval
# ---------------------------------------------------------------------------
def cmd_eval(args, overrides: List[str]) -> int:
    from novel_view_synthesis_3d_tpu.parallel import dist

    dist.require_backend()  # sub-60s structured failure on a dead tunnel
    setup_compilation_cache()  # repeat evals skip the XLA compile

    import jax

    from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
    from novel_view_synthesis_3d_tpu.eval.evaluate import evaluate_dataset
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch

    cfg = build_config(args, overrides)
    ds = SRNDataset(args.folder or cfg.data.root_dir,
                    img_sidelength=cfg.data.img_sidelength)
    model = XUNet(cfg.model)

    rec = ds.pair(0, np.random.default_rng(0))
    sample_batch = _sample_model_batch(
        {k: v[None] for k, v in rec.items()})
    params, step = _restore_params(cfg, model, sample_batch, args.step,
                                   reference_ckpt=args.reference_ckpt)
    print(f"restored checkpoint at step {step}")

    # Multi-chip: shard the sampling batch over the mesh 'data' axis; the
    # data axis is refit to the LOCAL device count so a training config's
    # mesh (e.g. mesh.data=32) doesn't crash an eval on a smaller host.
    mesh = None
    batch_size = args.batch_size
    if len(jax.devices()) > 1:
        from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.fit_local_mesh(cfg.mesh)
        if mesh is None:
            print(f"note: {len(jax.devices())} devices not divisible by "
                  f"mesh.model×mesh.seq claims; evaluating on the default "
                  "device")
        else:
            shards = mesh_lib.num_data_shards(mesh)
            batch_size = ((batch_size + shards - 1) // shards) * shards
            if batch_size != args.batch_size:
                print(f"note: rounding eval batch {args.batch_size} -> "
                      f"{batch_size} (multiple of data axis {shards})")

    fid_feature_fn = None
    if args.inception_npz:
        from novel_view_synthesis_3d_tpu.eval.inception import (
            load_inception_features)
        fid_feature_fn = load_inception_features(args.inception_npz)

    result = evaluate_dataset(
        cfg, model, params, ds,
        key=jax.random.PRNGKey(args.seed),
        num_instances=args.num_instances,
        views_per_instance=args.views_per_instance,
        cond_view=args.cond_view,
        sample_steps=args.sample_steps,
        batch_size=batch_size,
        compute_fid=args.fid or fid_feature_fn is not None,
        fid_feature_fn=fid_feature_fn,
        protocol=args.protocol,
        mesh=mesh,
        dump_comparisons=args.dump_comparisons,
    )
    print(json.dumps(dict(result.to_dict(), checkpoint_step=step)))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as fh:
            # The eval protocol parameters ride along so downstream
            # analysis (tools/pose_generalization.py) can reconstruct the
            # exact (instance, view) pairing of per_view_psnr instead of
            # guessing it from counts.
            json.dump(dict(result.to_dict(), checkpoint_step=step,
                           cond_view=args.cond_view,
                           num_instances=args.num_instances,
                           views_per_instance=args.views_per_instance,
                           per_view_psnr=result.per_view_psnr.tolist(),
                           per_view_ssim=result.per_view_ssim.tolist()), fh)
    return 0


# ---------------------------------------------------------------------------
# prep / config
# ---------------------------------------------------------------------------
def cmd_prep(args, overrides: List[str]) -> int:
    del overrides
    from novel_view_synthesis_3d_tpu.data import prep

    if args.prep_command == "split-object":
        n_train, n_val = prep.train_val_split(
            args.object_dir, args.train_dir, args.val_dir,
            symlink=args.symlink, invert=args.invert)
        print(f"{n_train} train / {n_val} val views")
    elif args.prep_command == "shapenet":
        placed = prep.shapenet_train_test_split(
            args.shapenet_path, args.synset_id, args.name, args.csv_path,
            symlink=args.symlink)
        print(json.dumps({k: len(v) for k, v in placed.items()}))
    else:
        raise SystemExit(f"unknown prep command {args.prep_command!r}")
    return 0


def cmd_pack(args, overrides: List[str]) -> int:
    """Pack an SRN tree into sharded records, or verify a packed corpus.

    Two modes:
      nvs3d pack SRN_DIR --out PACKED_DIR [--shard-mb N] [--verify]
        walks the SRN layout once, writes shard-*.nvsrec + index.json
        (sharded by scene at a target shard size), optionally verifying
        the result before reporting;
      nvs3d pack PACKED_DIR --verify
        integrity sweep over an existing corpus: re-hash every shard,
        cross-check footers against index.json, unpack every record,
        decode a probe view per scene. rc=1 if anything fails — the
        pre-flight for pointing data.backend='packed' at a corpus.
    """
    del overrides
    from novel_view_synthesis_3d_tpu.data import records

    def run_verify(root: str) -> int:
        problems = records.verify_packed(
            root, decode="all" if args.deep else "first")
        print(json.dumps({
            "verified": not problems, "dir": root,
            "problems": problems[:50],
            "num_problems": len(problems)}))
        if problems:
            print(f"verification FAILED: {len(problems)} problem(s)",
                  file=sys.stderr)
            return 1
        return 0

    if os.path.exists(os.path.join(args.src, records.INDEX_NAME)) \
            and not args.out:
        if not args.verify:
            raise SystemExit(
                f"{args.src!r} is already a packed corpus; pass --verify "
                "to check it, or --out DIR to re-pack somewhere else")
        return run_verify(args.src)
    if not args.out:
        raise SystemExit("--out DIR is required when packing")
    index = records.pack_srn(
        args.src, args.out, shard_mb=args.shard_mb,
        max_num_instances=args.max_instances,
        name=args.name, classes=args.classes,
        progress=((lambda name, views, shard: print(
            f"  packed {name} ({views} views) -> shard {shard}"))
            if args.progress else None))
    print(json.dumps({
        "packed": args.out,
        "shards": len(index["shards"]),
        "instances": index["num_instances"],
        "views": index["num_views"],
        "bytes": sum(s["bytes"] for s in index["shards"]),
        "meta": index.get("meta"),
    }))
    if args.verify:
        return run_verify(args.out)
    return 0


def cmd_config(args, overrides: List[str]) -> int:
    print(build_config(args, overrides).to_json())
    return 0


# ---------------------------------------------------------------------------
# export (checkpoint → reference format)
# ---------------------------------------------------------------------------
def cmd_export(args, overrides: List[str]) -> int:
    """Write a trained checkpoint as a reference-format flax msgpack file.

    The inverse of --reference-ckpt: a file the reference codebase's
    restore path (sampling.py:104-114) can consume — bare param dict,
    3-D (1,3,3) conv kernels, reference module naming. EMA params are
    exported when present (they are what you sample with).

    Default-step selection rides the checkpoint integrity walk-back
    (train/checkpoint.restore with step=None): after a torn save the
    export picks the newest VERIFIED checkpoint, never blindly the
    latest step. With --registry the converted snapshot is also
    published as a registry version (fmt='reference' in the manifest —
    inspectable and gc-able, but never servable by mistake).
    """
    import jax
    import numpy as np

    from flax import serialization

    from novel_view_synthesis_3d_tpu.compat.reference_ckpt import (
        export_reference_params)
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.train.trainer import _sample_model_batch

    cfg = build_config(args, overrides)
    if cfg.model.num_cond_frames != 1:
        raise SystemExit(
            "export: the reference format is strictly two-frame (k=1); "
            f"model.num_cond_frames={cfg.model.num_cond_frames}")
    model = XUNet(cfg.model)
    sample_batch = _sample_model_batch(make_example_batch(
        batch_size=1, sidelength=cfg.data.img_sidelength))
    params, step = _restore_params(cfg, model, sample_batch, args.step)
    ref_tree = export_reference_params(jax.tree.map(np.asarray, params))
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "wb") as fh:
        fh.write(serialization.msgpack_serialize(ref_tree))
    n = sum(np.asarray(leaf).size
            for leaf in jax.tree.leaves(ref_tree))
    print(f"exported step-{step} params ({n:,} values) to {args.out} "
          "(reference flax msgpack layout)")
    if args.registry:
        from novel_view_synthesis_3d_tpu.registry import RegistryStore
        from novel_view_synthesis_3d_tpu.registry.manifest import (
            config_digest)

        with open(args.out, "rb") as fh:
            payload = fh.read()
        m = RegistryStore(args.registry).publish_bytes(
            payload, step=step, ema=cfg.train.ema_decay > 0,
            fmt="reference", config_digest=config_digest(cfg),
            notes=f"nvs3d export of {args.out}",
            channel=args.channel)
        print(f"published as registry version {m.version} "
              f"(fmt=reference, channel {args.channel})")
    return 0


# ---------------------------------------------------------------------------
# distill (progressive distillation: teacher -> few-step student)
# ---------------------------------------------------------------------------
def cmd_distill(args, overrides: List[str]) -> int:
    """Progressive distillation rounds against a registry teacher.

    Reads the teacher from --teacher-version (or the --teacher-channel
    pointer), runs config.distill step-halving rounds
    (train/distill.run_distill), publishes each student generation as a
    registry version on --channel, and — with --promote-channel — runs
    the existing fixed-seed PSNR gate (registry/gate.py) on the FINAL
    student and advances that channel on a pass. The gate probes at the
    student's final step count: the comparison is "serving at N steps
    with the candidate vs the incumbent", the few-step serving regime
    the distillation exists for. Prints one JSON line per round and a
    closing summary line.
    """
    from novel_view_synthesis_3d_tpu.parallel import dist

    dist.require_backend()  # sub-60s structured failure on a dead tunnel
    setup_compilation_cache()

    import jax

    from novel_view_synthesis_3d_tpu.models.xunet import XUNet
    from novel_view_synthesis_3d_tpu.registry import (
        RegistryError, RegistryStore, promote)
    from novel_view_synthesis_3d_tpu.train.distill import run_distill

    cfg = build_config(args, overrides)
    store = RegistryStore(args.registry)
    vid = args.teacher_version or store.read_channel(args.teacher_channel)
    if vid is None:
        raise SystemExit(
            f"registry {args.registry!r} channel "
            f"{args.teacher_channel!r} points at no version — publish "
            "and promote a teacher first (nvs3d registry publish)")
    manifest = store.verify(vid)
    teacher_params = store.load_params(vid, verify=False)
    print(f"teacher: {vid} (step {manifest.step}, channel "
          f"{args.teacher_channel})")
    model = XUNet(cfg.model)
    event_cb = _registry_event_cb(args.registry)

    data_iter = None
    root = args.folder or cfg.data.root_dir
    if root and os.path.isdir(root):
        try:
            import dataclasses

            from novel_view_synthesis_3d_tpu.data.pipeline import (
                iter_batches, make_dataset)

            ds = make_dataset(dataclasses.replace(cfg.data, root_dir=root))
            if len(ds) > 0:
                data_iter = iter_batches(ds, cfg.distill.batch_size,
                                         seed=cfg.distill.seed)
                print(f"distilling on {root} ({len(ds)} records)")
        except Exception as e:
            print(f"note: falling back to synthetic distill batches ({e})")
    try:
        results = run_distill(
            cfg, model, teacher_params, data_iter=data_iter, store=store,
            publish_channel=args.channel, base_step=manifest.step,
            event_cb=event_cb)
    except (ValueError, FloatingPointError) as e:
        raise SystemExit(f"distill error: {e}")
    for r in results:
        print(json.dumps(dict(r.to_dict(), teacher=vid)))
    final = results[-1]
    if args.promote_channel:
        # The gate probes AT the student's serving step count; with
        # registry.gate_trajectory_frames set, the multi-view
        # consistency gate ALSO runs — a few-step student whose orbit
        # drifts is refused even when its single frames gate clean.
        try:
            passed, gate = _run_gates(
                cfg, model, store, final.version, args.promote_channel,
                _gate_probe_batch(cfg, args.folder),
                psnr_sample_steps=final.student_steps,
                event_cb=event_cb)
        except RegistryError as e:
            raise SystemExit(f"gate error: {e}")
        if not passed:
            print(f"promotion REFUSED: {gate.reason} (channel "
                  f"{args.promote_channel} untouched)")
            return 1
        promote(store, final.version, channel=args.promote_channel,
                gate=gate, event_cb=event_cb)
        print(f"promoted {final.version} -> channel "
              f"{args.promote_channel}")
    print(f"distilled {cfg.distill.start_steps} -> "
          f"{final.student_steps} steps over {len(results)} round(s); "
          f"serve with sample_steps={final.student_steps}")
    return 0


# ---------------------------------------------------------------------------
# registry (model lifecycle: publish / promote / rollback / gc)
# ---------------------------------------------------------------------------
def _registry_event_cb(registry_dir: str):
    """EventBus-routed audit log in the registry root: every lifecycle
    decision (publish, gate verdicts, promote, rollback, gc) is a row in
    <dir>/events.csv + telemetry.jsonl — same single write path as the
    trainer and the service."""
    from novel_view_synthesis_3d_tpu import obs

    bus = obs.EventBus(registry_dir, jsonl=True)
    return lambda step, kind, detail, version="": bus.event(
        step, kind, detail, model_version=version, echo="[registry]")


def _gate_probe_batch(cfg, folder: Optional[str]) -> dict:
    """Fixed-seed conditioning batch for the promotion gate: real SRN
    views when a dataset is reachable (the honest probe), else the
    synthetic harness (still a valid candidate-vs-incumbent comparator —
    both versions see identical conditioning and noise)."""
    rcfg = cfg.registry
    root = folder or cfg.data.root_dir
    if root and os.path.isdir(root):
        try:
            from novel_view_synthesis_3d_tpu.data.pipeline import (
                iter_batches, make_dataset)

            import dataclasses

            ds = make_dataset(dataclasses.replace(cfg.data, root_dir=root))
            if len(ds) > 0:
                bs = min(rcfg.gate_batch, len(ds))
                return next(iter_batches(ds, bs, seed=rcfg.gate_seed,
                                         num_cond=cfg.model.num_cond_frames))
        except Exception as e:
            print(f"note: gate falling back to synthetic probe data ({e})")
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch

    return make_example_batch(batch_size=rcfg.gate_batch,
                              sidelength=cfg.data.img_sidelength,
                              seed=rcfg.gate_seed)


def _gate_matrix_cells(cfg, model, folder, *, psnr_sample_steps: int):
    """Probe cells for the (corpus × rung-resolution) gate matrix.

    One PSNR probe per corpus of `data.mix` (or the single training
    root) at EVERY resolution the run trains at (train/ladder.py
    `ladder_resolutions`) — a candidate that regressed at the 64px rung
    must not ship on the strength of its 128px cells, and vice versa.
    Each cell's batch is drawn fixed-seed from that corpus at that
    resolution, falling back to the synthetic harness per cell."""
    from novel_view_synthesis_3d_tpu.registry import make_psnr_probe
    from novel_view_synthesis_3d_tpu.train.ladder import ladder_resolutions

    rcfg = cfg.registry
    if cfg.data.mix:
        from novel_view_synthesis_3d_tpu.data.corpus import parse_mix_spec

        corpora = [(s.name, s.path) for s in parse_mix_spec(cfg.data.mix)]
    else:
        corpora = [("train", folder or cfg.data.root_dir)]
    cells = []
    for name, root in corpora:
        for res in ladder_resolutions(cfg):
            ccfg = cfg.override(**{
                "data.root_dir": root or "",
                "data.img_sidelength": res,
                "data.mix": "",
            })
            cells.append({
                "corpus": name,
                "resolution": res,
                "metric": "psnr",
                "probe_fn": make_psnr_probe(
                    model, cfg.diffusion, _gate_probe_batch(ccfg, None),
                    sample_steps=psnr_sample_steps, seed=rcfg.gate_seed,
                    precision=cfg.serve.precision),
            })
    return cells


def _run_gates(cfg, model, store, vid: str, channel: str, batch: dict,
               *, psnr_sample_steps: int, event_cb, folder=None):
    """Run every configured promotion gate for one candidate.

    Always the fixed-seed single-frame PSNR probe; additionally, when
    registry.gate_trajectory_frames > 0, the multi-view CONSISTENCY
    probe (adjacent-frame PSNR over a fixed stochastic-conditioning
    orbit, registry/gate.make_trajectory_probe) under the SAME
    gate_margin_db — so distilled/quantized candidates are judged on
    trajectory quality, not just single-frame fidelity. A `data.mix` or
    `train.ladder` run additionally gates on the per-corpus ×
    per-rung-resolution PSNR MATRIX (registry/gate.run_gate_matrix; one
    regressed cell refuses the promotion), with the matrix landed as
    gate_matrix.json in the registry root for summarize_bench. Prints
    one JSON line per gate; returns (all_passed,
    gate_result_for_promote)."""
    from novel_view_synthesis_3d_tpu.registry import (
        GateResult, make_psnr_probe, make_trajectory_probe, run_gate,
        run_gate_matrix)

    rcfg = cfg.registry
    probes = [("psnr", make_psnr_probe(
        model, cfg.diffusion, batch, sample_steps=psnr_sample_steps,
        seed=rcfg.gate_seed, precision=cfg.serve.precision))]
    if rcfg.gate_trajectory_frames:
        probes.append(("trajectory_consistency", make_trajectory_probe(
            model, cfg.diffusion, batch,
            frames=rcfg.gate_trajectory_frames,
            sample_steps=rcfg.gate_sample_steps, seed=rcfg.gate_seed,
            precision=cfg.serve.precision,
            k_max=cfg.serve.k_max or None)))
    last = None
    for metric, probe in probes:
        gate = run_gate(store, vid, channel=channel, probe_fn=probe,
                        margin_db=rcfg.gate_margin_db,
                        event_cb=event_cb, metric=metric)
        print(json.dumps({
            "metric": metric,
            "candidate": gate.candidate, "incumbent": gate.incumbent,
            "candidate_psnr": round(gate.candidate_psnr, 3),
            "incumbent_psnr": (None if gate.incumbent_psnr is None
                               else round(gate.incumbent_psnr, 3)),
            "margin_db": gate.margin_db,
            "passed": gate.passed, "reason": gate.reason}))
        last = gate
        if not gate.passed:
            return False, gate
    if cfg.data.mix or cfg.train.ladder:
        matrix = run_gate_matrix(
            store, vid, channel=channel,
            cells=_gate_matrix_cells(cfg, model, folder,
                                     psnr_sample_steps=psnr_sample_steps),
            margin_db=cfg.registry.gate_margin_db, event_cb=event_cb)
        artifact = os.path.join(store.root, "gate_matrix.json")
        with open(artifact, "w") as fh:
            json.dump({
                "candidate": matrix.candidate,
                "incumbent": matrix.incumbent,
                "margin_db": matrix.margin_db,
                "passed": matrix.passed,
                "cells": list(matrix.cells),
            }, fh, indent=2)
        print(json.dumps({
            "metric": "matrix", "passed": matrix.passed,
            "cells": len(matrix.cells),
            "failed": sum(1 for c in matrix.cells if not c["passed"]),
            "artifact": artifact}))
        if not matrix.passed:
            worst = min((c for c in matrix.cells if not c["passed"]),
                        key=lambda c: (c["delta_db"]
                                       if c["delta_db"] is not None
                                       else 0.0))
            return False, GateResult(
                passed=False, candidate=vid, incumbent=matrix.incumbent,
                candidate_psnr=worst["candidate_psnr"],
                incumbent_psnr=worst["incumbent_psnr"],
                margin_db=matrix.margin_db,
                reason=(f"matrix cell {worst['corpus']}@"
                        f"{worst['resolution']}px: {worst['reason']}"))
    return True, last


def cmd_registry(args, overrides: List[str]) -> int:
    """Model lifecycle verbs over a registry directory.

    publish: newest VERIFIED checkpoint (integrity walk-back) → a
    content-hashed version on the `latest` channel. promote: fixed-seed
    PSNR gate vs the incumbent, then advance the stable channel —
    auto-reject (rc=1, pointer untouched) on regression beyond
    registry.gate_margin_db. rollback: previous stable version (a
    subscribed service hot-reloads it on the next poll). gc: keep the
    newest registry.keep versions; channel-pinned versions survive.
    """
    from novel_view_synthesis_3d_tpu.registry import (
        RegistryError, RegistryStore)

    store = RegistryStore(args.dir)
    sub = args.registry_command

    if sub == "list":
        versions = store.list_versions()
        channels = store.channels()
        if args.json:
            import dataclasses

            print(json.dumps({
                "versions": [dataclasses.asdict(m) for m in versions],
                "channels": channels}))
            return 0
        if not versions:
            print(f"(empty registry at {store.root})")
        by_version = {}
        for name, vid in channels.items():
            by_version.setdefault(vid, []).append(name)
        for m in versions:
            tags = ",".join(sorted(by_version.get(m.version, []))) or "-"
            print(f"{m.version}  step={m.step:<8d} ema={int(m.ema)} "
                  f"fmt={m.fmt:<9s} channels={tags}")
        for name, vid in sorted(channels.items()):
            print(f"channel {name} -> {vid}")
        return 0

    event_cb = _registry_event_cb(args.dir)

    if sub == "publish":
        from novel_view_synthesis_3d_tpu.data.synthetic import (
            make_example_batch)
        from novel_view_synthesis_3d_tpu.models.xunet import XUNet
        from novel_view_synthesis_3d_tpu.registry.manifest import (
            config_digest)
        from novel_view_synthesis_3d_tpu.train.trainer import (
            _sample_model_batch)

        cfg = build_config(args, overrides)
        model = XUNet(cfg.model)
        sample_batch = _sample_model_batch(make_example_batch(
            batch_size=1, sidelength=cfg.data.img_sidelength))
        # step=None rides the checkpoint integrity walk-back: a torn
        # newest save publishes the newest VERIFIED step instead.
        params, step = _restore_params(cfg, model, sample_batch, args.step)
        m = store.publish_params(
            params, step=step, ema=cfg.train.ema_decay > 0,
            config_digest=config_digest(cfg), channel=args.channel,
            notes=args.notes)
        event_cb(step, "model_publish",
                 f"channel {args.channel} <- {m.version} (cli)", m.version)
        print(f"published {m.version} (step {step}, "
              f"channel {args.channel})")
        return 0

    if sub == "promote":
        from novel_view_synthesis_3d_tpu.registry import promote

        cfg = build_config(args, overrides)
        channel = args.channel or cfg.registry.channel
        vid = args.version or store.read_channel(args.from_channel)
        if vid is None:
            raise SystemExit(
                f"nothing to promote: channel {args.from_channel!r} is "
                "empty and no --version was given")
        gate_result = None
        if not args.force:
            from novel_view_synthesis_3d_tpu.models.xunet import XUNet

            # Probe AT the serving precision (serve.precision): a
            # version promoted into a bf16/int8 deployment is gated on
            # what that deployment actually computes with. With
            # registry.gate_trajectory_frames set, the multi-view
            # consistency gate runs too (same margin).
            try:
                passed, gate_result = _run_gates(
                    cfg, XUNet(cfg.model), store, vid, channel,
                    _gate_probe_batch(cfg, args.folder),
                    psnr_sample_steps=cfg.registry.gate_sample_steps,
                    event_cb=event_cb)
            except RegistryError as e:
                raise SystemExit(f"gate error: {e}")
            if not passed:
                print(f"promotion REFUSED: {gate_result.reason} "
                      f"(channel {channel} still -> "
                      f"{store.read_channel(channel)})")
                return 1
        try:
            promote(store, vid, channel=channel, gate=gate_result,
                    event_cb=event_cb)
        except RegistryError as e:
            raise SystemExit(str(e))
        print(f"promoted {vid} -> channel {channel}")
        return 0

    if sub == "rollback":
        from novel_view_synthesis_3d_tpu.registry import rollback

        try:
            restored = rollback(store, channel=args.channel,
                                event_cb=event_cb)
        except RegistryError as e:
            raise SystemExit(str(e))
        print(f"channel {args.channel} rolled back to {restored}")
        return 0

    if sub == "gc":
        from novel_view_synthesis_3d_tpu.config import RegistryConfig

        keep = args.keep if args.keep is not None else RegistryConfig().keep
        try:
            deleted = store.gc(keep)
        except ValueError as e:
            raise SystemExit(str(e))
        for vid in deleted:
            event_cb(0, "gc", f"deleted version {vid} (keep={keep})", vid)
        print(json.dumps({"deleted": deleted, "keep": keep,
                          "kept": [m.version
                                   for m in store.list_versions()]}))
        return 0

    raise SystemExit(f"unknown registry command {sub!r}")


# ---------------------------------------------------------------------------
# obs (offline observability: trace reconstruction, run diff, SLO score)
# ---------------------------------------------------------------------------
def cmd_obs(args, overrides: List[str]) -> int:
    """Postmortem tooling over a finished run's telemetry.jsonl.

    `trace`: reconstruct per-request causal timelines (which dispatches
    a request rode, co-rider counts, step debt, swap drains) and verify
    the trace invariants; `diff`: span-percentile drift between two
    runs; `slo`: whole-run SLO attainment per step class; `numerics`:
    per-layer-group training stats + spike/anomaly triage from
    numerics.jsonl; `compiles`: the jit build ledger with recompile
    culprits from compiles.jsonl. No JAX, no device — these read what
    obs/ defines and the run emitted, so they work on a laptop against
    rsync'd artifacts.
    """
    from novel_view_synthesis_3d_tpu.obs import reqtrace

    sub = args.obs_command

    if sub == "trace":
        # Fleet layout (<run>/router/ + <run>/replica_<name>/ — the
        # `nvs3d route` / serve_bench --fleet convention): reconstruct
        # cross-replica timelines keyed by the trace_id the router
        # threaded through every hop, then verify the fleet invariants
        # (hop/failover accounting, replica-side joins).
        per_source = reqtrace.load_fleet_rows(args.run)
        if per_source.get("router"):
            fleet = reqtrace.reconstruct_fleet(per_source)
            problems = reqtrace.verify_fleet(fleet, per_source)
            if args.trace_id:
                fleet = {t: tl for t, tl in fleet.items()
                         if t == args.trace_id}
                if not fleet:
                    raise SystemExit(
                        f"trace {args.trace_id!r} not found in fleet "
                        f"dir {args.run!r}")
            if args.json:
                print(json.dumps({"fleet": True,
                                  "timelines": list(fleet.values()),
                                  "problems": problems}))
            else:
                for tid in sorted(fleet):
                    print(reqtrace.format_fleet_timeline(fleet[tid]))
                    print()
                for p in problems:
                    print(f"PROBLEM: {p}")
            return 1 if problems else 0
        rows = reqtrace.load_rows(args.run)
        if not rows:
            raise SystemExit(
                f"no telemetry rows under {args.run!r} — was the run "
                "recorded with obs.jsonl=true?")
        timelines = reqtrace.reconstruct(rows)
        if not timelines:
            raise SystemExit(
                f"{len(rows)} telemetry rows but no request_submit "
                "spans — not a serving run, or pre-tracing telemetry")
        problems = reqtrace.verify_timelines(timelines, rows)
        if args.trace_id:
            sel = {t: tl for t, tl in timelines.items()
                   if t == args.trace_id}
            if not sel:
                raise SystemExit(
                    f"trace {args.trace_id!r} not found (known: "
                    f"{', '.join(sorted(timelines)[:10])}...)")
        else:
            sel = timelines
        if args.json:
            print(json.dumps({"timelines": list(sel.values()),
                              "problems": problems}))
        else:
            for tid in sorted(sel):
                print(reqtrace.format_timeline(sel[tid]))
                print()
            for p in problems:
                print(f"PROBLEM: {p}")
        if args.perfetto:
            if args.trace_id:
                out = reqtrace.export_perfetto(
                    sel[args.trace_id], args.perfetto)
                print(f"wrote {out}")
            else:
                os.makedirs(args.perfetto, exist_ok=True)
                for tid, tl in sorted(sel.items()):
                    reqtrace.export_perfetto(tl, os.path.join(
                        args.perfetto, f"request_{tid}.json"))
                print(f"wrote {len(sel)} per-request tracks under "
                      f"{args.perfetto}")
        return 1 if problems else 0

    if sub == "diff":
        pa = reqtrace.span_percentiles(reqtrace.load_rows(args.a))
        pb = reqtrace.span_percentiles(reqtrace.load_rows(args.b))
        if not pa or not pb:
            raise SystemExit("no span rows in "
                             + (args.a if not pa else args.b))
        diff = reqtrace.diff_percentiles(
            pa, pb, threshold_pct=args.threshold_pct)
        drifted = [d for d in diff if d["drift"]]
        if args.json:
            print(json.dumps({"diff": diff,
                              "drifted": [d["name"] for d in drifted]}))
        else:
            for d in diff:
                flag = "DRIFT" if d["drift"] else "     "
                deltas = " ".join(
                    f"{k.split('_')[0]}{v:+.1f}%"
                    for k, v in d["deltas_pct"].items()) or d.get(
                        "note", "")
                print(f"{flag} {d['name']:<24s} {deltas}")
            print(f"{len(drifted)}/{len(diff)} span names drifted "
                  f">{args.threshold_pct:.0f}% (B vs A)")
        return 1 if drifted else 0

    if sub == "slo":
        from novel_view_synthesis_3d_tpu.obs import slo as slo_lib

        spec = args.targets
        if spec is None:
            cfg = build_config(args, overrides)
            spec = cfg.serve.slo.targets
        targets = slo_lib.parse_targets(spec)
        if not targets:
            raise SystemExit(
                "no SLO targets: pass --targets '4:500,64:2000' or set "
                "serve.slo.targets")
        rows = reqtrace.load_rows(args.run)
        snap = slo_lib.attainment_from_rows(rows, targets)
        print(json.dumps({"run": args.run, "slo": snap}))
        missed = [c for c, s in snap.items()
                  if s["total"] and s["attainment"] < s["objective"]]
        return 1 if missed else 0

    if sub == "numerics":
        return _obs_numerics(args)

    if sub == "compiles":
        return _obs_compiles(args)

    if sub == "roofline":
        return _obs_roofline(args)

    if sub == "doctor":
        return _obs_doctor(args)

    raise SystemExit(f"unknown obs command {sub!r}")


def _obs_roofline(args) -> int:
    """Roofline a run: measured per-group device time (profile_window
    rows) × analytic costmap FLOPs/bytes × chip peaks → per-group MFU,
    bandwidth utilization, bound class, and the top-k headroom list
    (the aim list for the ROADMAP perf arcs)."""
    from novel_view_synthesis_3d_tpu.obs import roofline as roofline_lib

    report = roofline_lib.analyze_run(
        args.run, peak_flops=args.peak_flops,
        peak_bytes_per_s=args.peak_bytes)
    if not report["rows"]:
        raise SystemExit(
            f"nothing to roofline under {args.run!r}: no costmap.json "
            "and no profile_window rows in telemetry.jsonl (run with "
            "obs.profile.enabled and obs.cost_analysis)")
    if args.json:
        print(json.dumps(report))
    else:
        print(roofline_lib.render(report, k=args.top))
    return 0


def _obs_doctor(args) -> int:
    """The regression doctor: rank every artifact-backed finding. Two
    modes — `doctor RUN_A RUN_B` diffs two results folders; `doctor
    --trajectory [ROOT]` reads the banked BENCH_r*/MULTICHIP_r* archive
    via the run index. rc=1 when any page-severity finding lands (the
    sentry's embedding reads the same ranked list)."""
    from novel_view_synthesis_3d_tpu.obs import doctor as doctor_lib

    if args.trajectory:
        root = args.run_a or "."
        doc = doctor_lib.diagnose_trajectory(
            root, tolerance_pct=args.tolerance_pct)
    else:
        if not args.run_a or not args.run_b:
            raise SystemExit(
                "doctor needs RUN_A RUN_B (pair mode) or --trajectory "
                "[ROOT] (archive mode)")
        doc = doctor_lib.diagnose_pair(args.run_a, args.run_b)
    if args.out:
        path = doctor_lib.write_doctor(args.out, doc)
        print(f"wrote {path}")
    if args.json:
        print(json.dumps(doc))
    else:
        print(doctor_lib.render(doc, limit=args.limit))
    pages = [f for f in doc.get("findings", [])
             if f.get("severity") == "page"]
    return 1 if pages else 0


def _obs_numerics(args) -> int:
    """Render a run's numerics.jsonl: per-group latest stats, the spike
    timeline, and anomaly provenance from events.csv. rc=1 when a spike
    or anomaly is UNRESOLVED — the loss-spike triage runbook's exit code
    (docs/TPU_VM_SETUP.md)."""
    from novel_view_synthesis_3d_tpu import obs

    path = obs.numerics_path(args.run)
    rows, spikes = [], []
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn trailing line
                if rec.get("kind") == "numerics":
                    rows.append(rec)
                elif rec.get("kind") == "numerics_spike":
                    spikes.append(rec)
    if not rows:
        raise SystemExit(
            f"no numerics rows under {args.run!r} — was the run trained "
            "with train.numerics.enabled=true?")
    anomalies = [ev for ev in obs.read_events(args.run)
                 if ev.get("event") == "anomaly"]

    latest = rows[-1]
    # A spike is RESOLVED once any later row shows that group's grad
    # norm back below the spiking sample; otherwise it is still burning.
    def resolved(spike) -> bool:
        for row in rows:
            if row["step"] <= spike["step"]:
                continue
            g = row["groups"].get(spike["group"], {})
            gn = g.get("grad_norm")
            if gn is not None and gn < spike["grad_norm"]:
                return True
        return False

    unresolved_spikes = [s for s in spikes if not resolved(s)]
    # An anomaly is resolved once a LATER numerics row is clean (every
    # group finite) — i.e. training demonstrably recovered after it.
    def clean_after(step: int) -> bool:
        for row in rows:
            if row["step"] <= step:
                continue
            if all((g.get("nonfinite") or 0) == 0
                   for g in row["groups"].values()):
                return True
        return False

    def anomaly_step(ev) -> int:
        try:
            return int(ev.get("step", -1))
        except (TypeError, ValueError):
            return -1

    unresolved_anoms = [e for e in anomalies
                        if not clean_after(anomaly_step(e))]

    if args.json:
        print(json.dumps({
            "run": args.run, "rows": len(rows),
            "last_step": latest["step"], "groups": latest["groups"],
            "spikes": spikes,
            "unresolved_spikes": unresolved_spikes,
            "anomalies": [dict(e) for e in anomalies],
            "unresolved_anomalies": [dict(e) for e in unresolved_anoms],
        }))
        return 1 if unresolved_spikes or unresolved_anoms else 0

    print(f"numerics: {len(rows)} rows, last step {latest['step']} "
          f"({len(latest['groups'])} layer groups)")
    print(f"{'group':<16s} {'grad_norm':>10s} {'param_norm':>10s} "
          f"{'upd_ratio':>10s} {'grad_max':>10s} {'nonfin':>6s}")
    for label, g in latest["groups"].items():
        print(f"{label:<16s} {g.get('grad_norm', 0.0):>10.3e} "
              f"{g.get('param_norm', 0.0):>10.3e} "
              f"{g.get('update_ratio', 0.0):>10.3e} "
              f"{g.get('grad_max', 0.0):>10.3e} "
              f"{int(g.get('nonfinite') or 0):>6d}")
    if spikes:
        print(f"\nspike timeline ({len(spikes)}):")
        for s in spikes:
            state = ("resolved" if s not in unresolved_spikes
                     else "UNRESOLVED")
            print(f"  step {s['step']:>8d} {s['group']:<16s} "
                  f"z={s['z']:.1f} grad_norm={s['grad_norm']:.3e} "
                  f"[{state}]")
    if anomalies:
        print(f"\nanomaly events ({len(anomalies)}):")
        for e in anomalies:
            state = ("resolved" if e not in unresolved_anoms
                     else "UNRESOLVED")
            print(f"  step {e.get('step', '?'):>8s} "
                  f"{e.get('detail', '')} [{state}]")
    if unresolved_spikes or unresolved_anoms:
        print(f"\nUNRESOLVED: {len(unresolved_spikes)} spike(s), "
              f"{len(unresolved_anoms)} anomaly(ies) — triage per "
              "docs/TPU_VM_SETUP.md 'Loss-spike triage'")
        return 1
    return 0


def _obs_compiles(args) -> int:
    """Render a run's compile ledger (compiles.jsonl): every jit build
    with its wall time and HLO hash, recompiles with the argument that
    changed. rc=1 when the ledger records any recompile."""
    from novel_view_synthesis_3d_tpu import obs

    entries = obs.load_ledger(args.run)
    if not entries:
        raise SystemExit(
            f"no compile ledger under {args.run!r} — nothing jit-built "
            "there, or a pre-ledger run")
    recompiles = [e for e in entries if e.get("kind") == "recompile"]

    if args.why is not None:
        if not 1 <= args.why <= len(recompiles):
            raise SystemExit(
                f"--why {args.why}: run has {len(recompiles)} "
                "recompile(s)")
        e = recompiles[args.why - 1]
        print(f"recompile {args.why}/{len(recompiles)}: {e['name']}")
        for line in e.get("diff", []):
            print(f"  {line}")
        return 1

    if args.json:
        print(json.dumps({"run": args.run, "entries": entries,
                          "recompiles": len(recompiles)}))
        return 1 if recompiles else 0

    print(f"{'#':>3s} {'kind':<10s} {'name':<18s} {'wall_s':>8s} "
          f"{'hlo':<12s} changed")
    for i, e in enumerate(entries):
        wall = e.get("wall_s")
        print(f"{i:>3d} {e.get('kind', '?'):<10s} "
              f"{e.get('name', '?'):<18s} "
              f"{wall if wall is not None else '':>8} "
              f"{e.get('hlo_hash', ''):<12s} {e.get('changed', '')}")
    print(f"{len(entries)} build(s), {len(recompiles)} recompile(s)"
          + (" — `--why N` shows the Nth recompile's full diff"
             if recompiles else ""))
    return 1 if recompiles else 0


# ---------------------------------------------------------------------------
def cmd_route(args, overrides: List[str]) -> int:
    """Fleet front-end operations against running replica processes
    (serve/replica_main.py, or any ReplicaServer).

    `status`: poll every replica's /healthz through a FleetRouter and
    print the aggregated fleet snapshot (dispatch eligibility, step
    debt, breaker states, live SLO burn); rc=1 unless every replica is
    dispatchable. `deploy`: zero-downtime rolling deploy — move the
    registry channel, then per replica quiesce → drain-to-idle → poke
    the watcher → verify the swap → readmit → SLO-burn probation, with
    fleet-wide auto-rollback on any gate failure (serve/deploy.py);
    rc=0 only when the report says 'deployed'. Replicas are named
    `--replica name=http://host:port` (bare URLs get r0, r1, ...).
    """
    from novel_view_synthesis_3d_tpu.serve import (
        FleetRouter,
        HttpReplica,
        rolling_deploy,
    )

    cfg = build_config(args, overrides)
    handles = []
    for i, spec in enumerate(args.replica or []):
        name, sep, url = spec.partition("=")
        if not sep:
            name, url = f"r{i}", spec
        handles.append(HttpReplica(name, url))
    if not handles:
        raise SystemExit("no replicas: pass --replica name=URL "
                         "(repeatable)")
    journal = getattr(args, "journal", None)
    router = FleetRouter(handles, rcfg=cfg.router, journal=journal)
    sub = args.route_command

    if sub == "status":
        router.poll_health()
        snap = router.fleet_snapshot()
        snap["slo"] = router.fleet_slo()
        print(json.dumps(snap, indent=None if args.json else 2,
                         sort_keys=True))
        rec = snap.get("recovery")
        if rec:
            rc = rec.get("recovered_steps") or {}
            print(f"# journal {rec['journal']}: {rec['records']} "
                  f"record(s), {rec['pins_restored']} override pin(s) "
                  f"restored, {sum(rc.values())} pre-poll step(s) over "
                  f"{len(rc)} replica(s), "
                  f"{len(rec.get('reconciled') or {})} reconciled "
                  f"against live /healthz"
                  + (f", {rec['torn']} torn line(s)"
                     if rec.get("torn") else ""),
                  file=sys.stderr)
        return 0 if snap["healthy"] == snap["total"] else 1

    if sub == "deploy":
        from novel_view_synthesis_3d_tpu.registry import RegistryStore

        store = RegistryStore(args.dir)
        version = args.version or store.read_channel(args.from_channel)
        if not version:
            raise SystemExit(
                f"no deploy target: --version not given and channel "
                f"{args.from_channel!r} points at no version")
        router.poll_health()
        report = rolling_deploy(router, store, args.channel, version,
                                rcfg=cfg.router)
        print(json.dumps(report))
        return 0 if report["status"] == "deployed" else 1

    raise SystemExit(f"unknown route command {sub!r}")


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------
def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", default=None, choices=PRESET_NAMES,
                   help="config preset")
    p.add_argument("--config", default=None, help="config JSON file")


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m novel_view_synthesis_3d_tpu",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("train", help="train the X-UNet (reference train.py)")
    _add_common(p)
    p.add_argument("folder", nargs="?", default=None,
                   help="SRN dataset root (overrides data.root_dir)")
    p.add_argument("--no-grain", action="store_true",
                   help="in-process data loading (no worker processes)")
    p.add_argument("--supervise", action="store_true",
                   help="run training in a supervised child process: "
                        "restart on crash or watchdog-declared stall with "
                        "exponential backoff (train.max_restarts), "
                        "resuming from the newest intact checkpoint")

    p = sub.add_parser("sample",
                       help="sample novel views (reference sampling.py, PNGs "
                            "instead of cv2 windows)")
    _add_common(p)
    p.add_argument("folder", nargs="?", default=None)
    p.add_argument("--out", default="./samples")
    p.add_argument("--instance", type=int, default=0)
    p.add_argument("--cond-view", type=int, default=0)
    p.add_argument("--num-views", type=int, default=8)
    p.add_argument("--poses", choices=("dataset", "orbit", "interp"),
                   default="dataset",
                   help="targets: dataset ground-truth poses, a synthetic "
                        "orbit, or a smooth slerp path through the "
                        "instance's poses")
    p.add_argument("--pool-views", type=int, default=1,
                   help="with --stochastic: seed the conditioning pool "
                        "with this many REAL dataset views (default 1, "
                        "the 3DiM paper protocol)")
    p.add_argument("--elevation", type=float, default=0.3,
                   help="orbit elevation (radians), --poses orbit only")
    p.add_argument("--stochastic", action="store_true",
                   help="3DiM autoregressive stochastic conditioning")
    p.add_argument("--trajectory", type=int, default=0, metavar="N",
                   help="serving-grade N-frame orbit: one "
                        "TrajectoryRequest through the stepper ring — "
                        "device-resident frame bank, stochastic "
                        "conditioning per step, frames streamed as they "
                        "finish; writes orbit_strip.png beside the views")
    p.add_argument("--sample-steps", type=int, default=None,
                   help="respaced DDPM steps (default: config)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--reference-ckpt", default=None,
                   help="load a reference-format flax msgpack checkpoint "
                        "(e.g. the published pretrained model) instead of "
                        "this repo's checkpoints; pair with "
                        "--preset reference")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--gif", action="store_true",
                   help="also write a looping orbit.gif of the views")
    p.add_argument("--gif-fps", type=float, default=8.0)
    p.add_argument("--denoise-gif", action="store_true",
                   help="also write denoise.gif showing the reverse "
                        "diffusion of the first view (not with --stochastic)")

    p = sub.add_parser("serve",
                       help="micro-batched sampling service: coalesce "
                            "concurrent requests into padded power-of-two "
                            "buckets served from a compiled-program cache")
    _add_common(p)
    p.add_argument("folder", nargs="?", default=None)
    p.add_argument("--out", default="./serve",
                   help="request PNGs + the service events.csv land here")
    p.add_argument("--requests", default=None, metavar="JSONL",
                   help="JSON-lines request file (fields: instance, "
                        "cond_view, target_view, seed, sample_steps, "
                        "guidance_weight, deadline_ms, trace_id "
                        "(client-chosen id for nvs3d obs trace); "
                        "trajectory "
                        "requests add poses=[[4x4],...] or orbit=N plus "
                        "optional k_max — responses then stream one "
                        "line per frame with frame_index/model_version);"
                        " default: a --num-requests demo sweep")
    p.add_argument("--trajectory", type=int, default=0, metavar="N",
                   help="demo sweep serves N-frame ORBITS instead of "
                        "single views: --num-requests trajectories "
                        "stream per-frame responses and write per-"
                        "request orbit PNG strips")
    p.add_argument("--num-requests", type=int, default=8)
    p.add_argument("--instance", type=int, default=0)
    p.add_argument("--cond-view", type=int, default=0)
    p.add_argument("--sample-steps", type=int, default=None,
                   help="respaced steps (default: serve.sample_steps or "
                        "diffusion.sample_timesteps)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--reference-ckpt", default=None,
                   help="serve a reference-format flax msgpack checkpoint; "
                        "pair with --preset reference")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-request wall-clock budget in seconds "
                        "(queue wait + compile + device); a wedged "
                        "dispatch reports TimeoutError per request "
                        "instead of hanging the CLI forever")
    p.add_argument("--registry", default=None, metavar="DIR",
                   help="serve from a model registry instead of a "
                        "checkpoint: load the subscribed channel's "
                        "version and HOT-RELOAD (zero downtime) whenever "
                        "the pointer moves")
    p.add_argument("--channel", default=None,
                   help="registry channel to subscribe "
                        "(default: registry.channel, i.e. 'stable')")

    p = sub.add_parser("eval", help="PSNR/SSIM/FID over held-out views")
    _add_common(p)
    p.add_argument("folder", nargs="?", default=None)
    p.add_argument("--out", default=None, help="write result JSON here")
    p.add_argument("--num-instances", type=int, default=None)
    p.add_argument("--views-per-instance", type=int, default=1)
    p.add_argument("--cond-view", type=int, default=0)
    p.add_argument("--sample-steps", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--reference-ckpt", default=None,
                   help="load a reference-format flax msgpack checkpoint; "
                        "pair with --preset reference")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--protocol", choices=("single", "autoregressive"),
                   default="single",
                   help="'single': every target conditioned on the fixed "
                        "view; 'autoregressive': 3DiM stochastic "
                        "conditioning over the growing view pool")
    p.add_argument("--fid", action="store_true",
                   help="also compute Fréchet distance — reported as "
                        "'fid_random' (deterministic random-conv features, "
                        "NOT comparable to published Inception-FID; see "
                        "eval/metrics.py)")
    p.add_argument("--inception-npz", default=None,
                   help="InceptionV3 weights (.npz from "
                        "tools/convert_inception.py): compute the Fréchet "
                        "distance over pool3 features and report it as the "
                        "paper-comparable 'fid' (implies --fid)")
    p.add_argument("--dump-comparisons", default=None, metavar="PNG",
                   help="write a [conditioning | ground truth | synthesis] "
                        "row per scored pair (first 8) — the human-legible "
                        "form of the PSNR table")

    p = sub.add_parser("prep", help="offline dataset preparation")
    prep_sub = p.add_subparsers(dest="prep_command", required=True)
    q = prep_sub.add_parser("split-object",
                            help="SRN per-object 1-in-3 train/val split")
    q.add_argument("object_dir")
    q.add_argument("train_dir")
    q.add_argument("val_dir")
    q.add_argument("--symlink", action="store_true")
    q.add_argument("--invert", action="store_true",
                   help="train on the 2-in-3 slice, hold out 1-in-3 "
                        "(default mirrors the reference: train on the "
                        "sparse third)")
    q = prep_sub.add_parser("shapenet", help="CSV-driven ShapeNet split")
    q.add_argument("shapenet_path")
    q.add_argument("synset_id")
    q.add_argument("name")
    q.add_argument("csv_path")
    q.add_argument("--symlink", action="store_true")

    p = sub.add_parser(
        "pack",
        help="pack an SRN tree into sharded records (data.backend="
             "'packed'), or --verify an existing packed corpus")
    p.add_argument("src",
                   help="SRN dataset root to pack, or a packed corpus "
                        "dir with --verify and no --out")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="output corpus dir (shard-*.nvsrec + index.json)")
    p.add_argument("--shard-mb", type=float, default=64.0,
                   help="target shard size in MB; shards close at the "
                        "scene boundary past this (default 64). Pack "
                        "with at least as many shards as training hosts "
                        "— per-host reads slice at shard granularity")
    p.add_argument("--max-instances", type=int, default=-1,
                   help="pack only the first N instances (-1 = all)")
    p.add_argument("--name", default=None,
                   help="corpus name recorded in index.json meta (default: "
                        "the source dir's basename); the mixer's stats and "
                        "gauges use it")
    p.add_argument("--class", dest="classes", action="append", default=None,
                   metavar="NAME",
                   help="scene-class vocab entry for index.json meta "
                        "(repeatable; default: the corpus name)")
    p.add_argument("--verify", action="store_true",
                   help="after packing (or on an existing corpus with no "
                        "--out): re-hash every shard, cross-check "
                        "footers vs index.json, unpack every record, "
                        "decode a probe view per scene; rc=1 on failure")
    p.add_argument("--deep", action="store_true",
                   help="with --verify: decode EVERY view, not one per "
                        "scene")
    p.add_argument("--progress", action="store_true",
                   help="print one line per packed instance")

    p = sub.add_parser("config", help="print the resolved config JSON")
    _add_common(p)

    p = sub.add_parser("export",
                       help="write a checkpoint as a reference-format flax "
                            "msgpack file (inverse of --reference-ckpt)")
    _add_common(p)
    p.add_argument("--out", required=True,
                   help="output path (e.g. checkpoints_ref/model50000)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest VERIFIED step — "
                        "the checkpoint integrity walk-back skips torn "
                        "saves)")
    p.add_argument("--registry", default=None, metavar="DIR",
                   help="also publish the converted snapshot as a "
                        "registry version (manifest fmt=reference)")
    p.add_argument("--channel", default="latest",
                   help="registry channel for --registry (default latest)")

    p = sub.add_parser(
        "distill",
        help="progressive distillation: halve the teacher's sampling "
             "steps per round (registry teacher -> published few-step "
             "students, optional PSNR-gated promotion)")
    _add_common(p)
    p.add_argument("folder", nargs="?", default=None,
                   help="SRN tree for distillation batches (default "
                        "data.root_dir; synthetic fallback)")
    p.add_argument("--registry", required=True, metavar="DIR",
                   help="registry holding the teacher; students are "
                        "published here")
    p.add_argument("--teacher-channel", default="stable",
                   help="channel supplying the teacher (default stable)")
    p.add_argument("--teacher-version", default=None,
                   help="explicit teacher version id (overrides "
                        "--teacher-channel)")
    p.add_argument("--channel", default="distill",
                   help="channel each student generation is published to "
                        "(default 'distill')")
    p.add_argument("--promote-channel", default=None,
                   help="after the final round, run the PSNR gate and "
                        "advance this channel to the few-step student "
                        "(rc=1 + pointer untouched on a gate fail)")

    p = sub.add_parser(
        "registry",
        help="model lifecycle: versioned publish, quality-gated promote, "
             "rollback, gc over a registry directory")
    reg_sub = p.add_subparsers(dest="registry_command", required=True)
    q = reg_sub.add_parser("list", help="versions + channel pointers")
    q.add_argument("--dir", required=True, help="registry root directory")
    q.add_argument("--json", action="store_true")
    q = reg_sub.add_parser(
        "publish", help="newest verified checkpoint -> a registry version")
    _add_common(q)
    q.add_argument("--dir", required=True)
    q.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest VERIFIED step)")
    q.add_argument("--channel", default="latest")
    q.add_argument("--notes", default="")
    q = reg_sub.add_parser(
        "promote",
        help="run the PSNR gate vs the incumbent, then advance the "
             "stable channel (auto-reject on regression)")
    _add_common(q)
    q.add_argument("--dir", required=True)
    q.add_argument("--version", default=None,
                   help="candidate version id (default: the latest "
                        "channel's pointer)")
    q.add_argument("--from-channel", default="latest",
                   help="channel supplying the candidate when no "
                        "--version is given")
    q.add_argument("--channel", default=None,
                   help="destination channel (default registry.channel)")
    q.add_argument("--folder", default=None,
                   help="SRN tree for the gate probe (default "
                        "data.root_dir, synthetic fallback)")
    q.add_argument("--force", action="store_true",
                   help="skip the gate (operator override; the promote "
                        "event still lands in the audit log)")
    q = reg_sub.add_parser(
        "rollback", help="point the channel back at its previous version")
    q.add_argument("--dir", required=True)
    q.add_argument("--channel", default="stable")
    q = reg_sub.add_parser(
        "gc", help="delete all but the newest K versions "
                   "(channel-pinned versions always survive)")
    q.add_argument("--dir", required=True)
    q.add_argument("--keep", type=int, default=None,
                   help="versions to keep (default registry.keep)")

    p = sub.add_parser(
        "obs",
        help="postmortem tooling over a run's telemetry.jsonl: "
             "per-request trace reconstruction, cross-run span-"
             "percentile diff, whole-run SLO attainment")
    obs_sub = p.add_subparsers(dest="obs_command", required=True)
    q = obs_sub.add_parser(
        "trace",
        help="reconstruct per-request causal timelines (dispatches "
             "ridden, co-riders, step debt, swap drains) and verify "
             "the trace invariants; rc=1 on a broken trace")
    q.add_argument("run", help="run dir holding telemetry.jsonl")
    q.add_argument("--trace-id", default=None,
                   help="show one request (default: all)")
    q.add_argument("--json", action="store_true")
    q.add_argument("--perfetto", default=None, metavar="PATH",
                   help="export Perfetto/Chrome-trace track(s): a file "
                        "with --trace-id, else a directory of "
                        "per-request tracks")
    q = obs_sub.add_parser(
        "diff",
        help="span-percentile drift between two runs (p50/p90/p99 per "
             "span name); rc=1 when any span drifted past the "
             "threshold")
    q.add_argument("a", help="baseline run dir")
    q.add_argument("b", help="candidate run dir")
    q.add_argument("--threshold-pct", type=float, default=20.0)
    q.add_argument("--json", action="store_true")
    q = obs_sub.add_parser(
        "slo",
        help="whole-run SLO attainment per step class from the "
             "request_respond spans; rc=1 when a class missed its "
             "objective")
    _add_common(q)
    q.add_argument("run", help="run dir holding telemetry.jsonl")
    q.add_argument("--targets", default=None,
                   help="step-class targets, e.g. '4:500,64:2000' "
                        "(default: serve.slo.targets from config)")

    q = obs_sub.add_parser(
        "numerics",
        help="per-layer-group training numerics from numerics.jsonl: "
             "latest stats, spike timeline, anomaly provenance; rc=1 "
             "when a spike/anomaly is unresolved")
    q.add_argument("run", help="run dir holding numerics.jsonl")
    q.add_argument("--json", action="store_true",
                   help="machine-readable output")

    q = obs_sub.add_parser(
        "compiles",
        help="compile ledger from compiles.jsonl: every jit build with "
             "wall time + HLO hash, recompiles diffed to the argument "
             "that changed; rc=1 when any recompile is recorded")
    q.add_argument("run", help="run dir holding compiles.jsonl")
    q.add_argument("--json", action="store_true",
                   help="machine-readable output")
    q.add_argument("--why", type=int, default=None, metavar="N",
                   help="show the Nth recompile's full fingerprint diff")

    q = obs_sub.add_parser(
        "roofline",
        help="per-op-group roofline: measured device time (profile "
             "windows) × costmap FLOPs/bytes × chip peaks → MFU, "
             "bandwidth utilization, compute/memory/comm-bound class, "
             "top-k headroom")
    q.add_argument("run", help="run dir holding telemetry.jsonl "
                               "(+ costmap.json)")
    q.add_argument("--top", type=int, default=3,
                   help="top-k groups by headroom (default 3)")
    q.add_argument("--peak-flops", type=float, default=None,
                   help="override chip peak FLOPs/s (default: this "
                        "process's devices via obs.devmon)")
    q.add_argument("--peak-bytes", type=float, default=None,
                   help="override chip peak HBM bytes/s")
    q.add_argument("--json", action="store_true")

    q = obs_sub.add_parser(
        "doctor",
        help="ranked cross-run diagnosis: span drift, recompiles, "
             "numerics spikes, costmap drift, profile-window group "
             "drift (pair mode), or the whole banked BENCH_r* archive "
             "(--trajectory); rc=1 on a page-severity finding")
    q.add_argument("run_a", nargs="?", default=None,
                   help="baseline run dir (pair mode) or archive root "
                        "(--trajectory; default '.')")
    q.add_argument("run_b", nargs="?", default=None,
                   help="candidate run dir (pair mode)")
    q.add_argument("--trajectory", action="store_true",
                   help="diagnose the banked BENCH_r*/MULTICHIP_r* "
                        "archive instead of a run pair")
    q.add_argument("--tolerance-pct", type=float, default=2.0,
                   help="bench_sentry's rolling-median tolerance "
                        "(trajectory mode, default 2)")
    q.add_argument("--out", default=None, metavar="DIR",
                   help="also land the diagnosis as doctor.json in DIR")
    q.add_argument("--limit", type=int, default=0,
                   help="show at most N findings (0 = all)")
    q.add_argument("--json", action="store_true")

    p = sub.add_parser(
        "route",
        help="fleet front-end: aggregated replica health/SLO status "
             "and zero-downtime registry-channel rolling deploys "
             "with SLO-gated auto-rollback")
    route_sub = p.add_subparsers(dest="route_command", required=True)
    q = route_sub.add_parser(
        "status",
        help="poll every replica's /healthz and print the fleet "
             "snapshot (eligibility, step debt, breaker, SLO burn); "
             "rc=1 unless every replica is dispatchable")
    _add_common(q)
    q.add_argument("--replica", action="append", default=[],
                   metavar="NAME=URL",
                   help="replica endpoint (repeatable); bare URLs get "
                        "names r0, r1, ...")
    q.add_argument("--json", action="store_true",
                   help="single-line JSON (default: indented)")
    q.add_argument("--journal", default=None, metavar="PATH",
                   help="router journal to replay first: the snapshot "
                        "then carries the crash-restart reconstruction "
                        "provenance (records replayed, pins restored, "
                        "ledger steps reconciled against live /healthz)")
    q = route_sub.add_parser(
        "deploy",
        help="rolling deploy: move the registry channel, then per "
             "replica quiesce -> drain -> swap -> SLO-burn probation; "
             "auto-rollback on any gate failure; rc=0 only on "
             "'deployed'")
    _add_common(q)
    q.add_argument("--replica", action="append", default=[],
                   metavar="NAME=URL")
    q.add_argument("--dir", required=True, help="registry root directory")
    q.add_argument("--channel", default="stable",
                   help="channel the fleet subscribes to")
    q.add_argument("--version", default=None,
                   help="target version id (default: head of "
                        "--from-channel)")
    q.add_argument("--from-channel", default="latest",
                   help="channel supplying the target when no "
                        "--version is given")

    return parser


_COMMANDS = {
    "train": cmd_train,
    "sample": cmd_sample,
    "serve": cmd_serve,
    "eval": cmd_eval,
    "prep": cmd_prep,
    "pack": cmd_pack,
    "config": cmd_config,
    "export": cmd_export,
    "registry": cmd_registry,
    "distill": cmd_distill,
    "obs": cmd_obs,
    "route": cmd_route,
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = make_parser()
    args, rest = parser.parse_known_args(argv)
    # The optional positional `folder` would otherwise swallow the first
    # key=value override when no folder is given.
    if getattr(args, "folder", None) and "=" in args.folder:
        rest.insert(0, args.folder)
        args.folder = None
    overrides = _split_overrides(rest)
    return _COMMANDS[args.command](args, overrides)


if __name__ == "__main__":
    sys.exit(main())
