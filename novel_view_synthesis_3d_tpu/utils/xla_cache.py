"""Persistent XLA compilation-cache wiring, shared by every entry point.

Before this module, only bench.py, tests/conftest.py, and the tools
watcher enabled `jax_compilation_cache_dir` — each with its own copy of
the three config updates — while the cli.py train/sample/eval entry
points paid a full XLA recompile on every run (minutes at base128+
through a remote tunnel). One helper, called by all of them:

  - `JAX_COMPILATION_CACHE_DIR` (env) wins when set — the contract the
    tools watcher and bench already rely on;
  - otherwise a caller-supplied default directory (the CLI uses a
    per-user cache dir, bench keeps its repo-local `.jax_cache`);
  - `NVS3D_NO_COMPILE_CACHE=1` disables entirely (debugging cold
    compiles, read-only home directories in exotic CI).

Knobs (env-overridable because the right floor differs between a laptop
CPU run and a pod): `NVS3D_CACHE_MIN_COMPILE_S` — only compilations at
least this long are persisted (default 1.0 s, matching bench/tools);
`NVS3D_CACHE_MIN_ENTRY_BYTES` — minimum executable size persisted
(default -1 = everything, matching tests/conftest.py).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

# The CLI default: per-user, survives checkouts, never pollutes a
# read-only repo dir. Overridable via JAX_COMPILATION_CACHE_DIR.
DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "nvs3d_xla_cache")


def setup_compilation_cache(
        default_dir: Optional[str] = DEFAULT_CACHE_DIR,
        min_compile_secs: float = 1.0,
        min_entry_bytes: int = -1) -> Optional[str]:
    """Enable the persistent compilation cache; returns the active dir.

    Call before the first jitted dispatch (jax.config updates are
    effective any time before a program is compiled). Returns None —
    and leaves jax untouched — when caching is disabled or the cache
    directory cannot be created (a broken cache dir must never kill a
    run that would merely compile slower without it).
    """
    if os.environ.get("NVS3D_NO_COMPILE_CACHE") == "1":
        return None
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or default_dir
    if not cache_dir:
        return None
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        print(f"warning: compilation cache dir {cache_dir!r} unavailable "
              f"({e}); continuing without persistent cache", file=sys.stderr)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("NVS3D_CACHE_MIN_COMPILE_S", min_compile_secs)))
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ.get("NVS3D_CACHE_MIN_ENTRY_BYTES", min_entry_bytes)))
    return cache_dir
