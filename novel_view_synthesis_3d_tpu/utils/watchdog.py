"""Heartbeat watchdog: in-run detection of hangs and stalls.

Every judged-bench failure this repo has suffered was a fault that HANGS,
not one that raises (BENCH_r0* rc=3: unreachable backend; the 2400 s
base128 sampling stall an external watcher had to kill). PR 1's fault
ladder recovers from faults that raise or corrupt; this module is its
stall-shaped counterpart (docs/DESIGN.md "Stall recovery").

Model: the training loop marks which PHASE it is in (`data_fetch`,
`compile`, `train_step`, `checkpoint_save`, `eval`) via the `phase()`
context manager; a monitor thread checks armed phases against per-phase
wall-clock budgets (config.py `train.watchdog.*` — compile budgets
separate from steady-state step budgets). On expiry it:

  1. captures a DIAGNOSIS BUNDLE — every thread's stack, the age of every
     heartbeat ever seen, device memory stats if the backend answers —
     and writes it to `<results>/stall_<phase>_<n>.txt`;
  2. invokes `on_stall(phase, diagnosis_path)` exactly once per phase
     entry (the Trainer logs an events.csv `stall` row and either flags a
     cross-host-agreed checkpoint-and-exit or degrades, per phase);
  3. optionally HARD-EXITS: if the phase is still stuck `hard_exit_s`
     seconds past its budget — the main thread never returned to observe
     the soft flag, i.e. a true wedge such as uninterruptible tunnel IO —
     the monitor dumps a final bundle and `os._exit(EXIT_STALL)` so a
     supervisor (train/supervisor.py) can restart the host. One stuck
     host exiting beats one stuck host wedging the whole slice.

The monitor thread is a daemon sleeping on an Event between checks; with
no armed phase it costs one dict scan per `check_interval_s`.
"""

from __future__ import annotations

import io
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

# Process exit code for a watchdog-declared stall (soft checkpoint-and-exit
# in cli.cmd_train, or the monitor's hard exit). Distinct from
# parallel/dist.EXIT_BACKEND_UNREACHABLE (3): a stall mid-run is a
# different diagnosis than a backend that never answered at all.
EXIT_STALL = 74

# Canonical phase name -> config.WatchdogConfig budget field.
PHASE_BUDGET_FIELDS = {
    "data_fetch": "data_fetch_s",
    "compile": "compile_s",
    "train_step": "step_s",
    "checkpoint_save": "checkpoint_save_s",
    "eval": "eval_s",
}
PHASES = tuple(PHASE_BUDGET_FIELDS)


def thread_stacks() -> str:
    """Formatted stacks of every live thread (the core of the bundle)."""
    out = io.StringIO()
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        out.write(f"--- thread {names.get(ident, '?')} (id {ident}) ---\n")
        traceback.print_stack(frame, file=out)
    return out.getvalue()


def device_memory_stats(timeout_s: float = 2.0) -> str:
    """Best-effort per-device memory stats.

    Queried in a throwaway thread with a bounded join: on a wedged backend
    the query itself can hang, and the diagnosis bundle must never block
    the diagnosis."""
    result = {"text": f"(no answer within {timeout_s:.0f}s)"}

    def query():
        try:
            import jax

            lines = []
            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                if stats:
                    keep = {k: v for k, v in stats.items()
                            if "bytes" in k or "allocs" in k}
                    lines.append(f"{d}: {keep}")
                else:
                    lines.append(f"{d}: (no memory_stats)")
            result["text"] = "\n".join(lines) or "(no local devices)"
        except Exception as exc:
            result["text"] = f"(unavailable: {type(exc).__name__}: {exc})"

    t = threading.Thread(target=query, daemon=True, name="wd-memstats")
    t.start()
    t.join(timeout_s)
    return result["text"]


class Watchdog:
    """Monitor thread over named heartbeats and armed phase deadlines."""

    def __init__(self, budgets: Dict[str, float],
                 on_stall: Optional[Callable[[str, str], None]] = None,
                 *, check_interval_s: float = 2.0,
                 hard_exit_s: float = 0.0,
                 diagnosis_dir: str = ".",
                 query_device: bool = True,
                 _clock: Callable[[], float] = time.monotonic):
        self.budgets = dict(budgets)
        self.on_stall = on_stall
        self.check_interval_s = check_interval_s
        self.hard_exit_s = hard_exit_s
        self.diagnosis_dir = diagnosis_dir
        self.query_device = query_device
        self._clock = _clock
        self._lock = threading.Lock()
        # phase -> entry time while armed; absent when idle. The trainer is
        # single-threaded so at most a couple of phases nest (eval inside
        # nothing, data_fetch inside train() only) — a dict keeps it exact.
        self._armed: Dict[str, float] = {}
        self._flagged: Dict[str, bool] = {}  # on_stall fired for this entry
        self._last_beat: Dict[str, float] = {}
        self.stall_count = 0
        self.stalled_phases: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()  # restartable: train() may run twice
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- feeding -------------------------------------------------------
    def beat(self, name: str) -> None:
        """Record a named heartbeat (diagnosis context; no deadline)."""
        with self._lock:
            self._last_beat[name] = self._clock()

    def phase(self, name: str) -> "_PhaseGuard":
        """Arm `name`'s deadline for the duration of a with-block."""
        return _PhaseGuard(self, name)

    def _enter(self, name: str) -> None:
        with self._lock:
            self._armed[name] = self._clock()
            self._flagged[name] = False
            self._last_beat[name] = self._armed[name]

    def _exit(self, name: str) -> None:
        with self._lock:
            self._armed.pop(name, None)
            self._flagged.pop(name, None)
            self._last_beat[name] = self._clock()

    # -- monitoring ----------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            self.check()

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """One monitor pass; returns the phase that newly stalled, if any.

        Public for tests (and callable with an explicit `now` so drills
        need not actually sleep through production-scale budgets)."""
        now = self._clock() if now is None else now
        with self._lock:
            armed = dict(self._armed)
            flagged = dict(self._flagged)
        newly_stalled = None
        for name, since in armed.items():
            budget = self.budgets.get(f"{name}_s",
                                      self.budgets.get(name, 0.0))
            if not budget or budget <= 0:
                continue
            over = (now - since) - budget
            if over <= 0:
                continue
            if not flagged.get(name):
                with self._lock:
                    if self._flagged.get(name):  # raced another check()
                        continue
                    self._flagged[name] = True
                    self.stall_count += 1
                    self.stalled_phases.append(name)
                path = self._write_diagnosis(name, now - since, budget)
                newly_stalled = name
                if self.on_stall is not None:
                    try:
                        self.on_stall(name, path)
                    except Exception:
                        traceback.print_exc()
            if self.hard_exit_s and over > self.hard_exit_s:
                self._hard_exit(name, now - since, budget)
        return newly_stalled

    def heartbeat_ages(self, now: Optional[float] = None) -> Dict[str, float]:
        now = self._clock() if now is None else now
        with self._lock:
            return {k: now - v for k, v in sorted(self._last_beat.items())}

    def _bundle(self, name: str, elapsed: float, budget: float) -> str:
        lines = [
            f"STALL: phase {name!r} armed for {elapsed:.1f}s "
            f"(budget {budget:.1f}s)",
            f"wall time: {time.strftime('%Y-%m-%d %H:%M:%S')}",
            "",
            "heartbeat ages (s since last beat):",
        ]
        for k, age in self.heartbeat_ages().items():
            lines.append(f"  {k}: {age:.1f}")
        lines += ["", "device memory:",
                  device_memory_stats() if self.query_device
                  else "(device query disabled)",
                  "", "all-thread stacks:", thread_stacks()]
        return "\n".join(lines)

    def _write_diagnosis(self, name: str, elapsed: float,
                         budget: float) -> str:
        text = self._bundle(name, elapsed, budget)
        path = os.path.join(
            self.diagnosis_dir, f"stall_{name}_{self.stall_count}.txt")
        try:
            os.makedirs(self.diagnosis_dir, exist_ok=True)
            with open(path, "w") as fh:
                fh.write(text)
        except OSError as exc:  # diagnosis must never be the second fault
            print(f"watchdog: could not write {path!r} ({exc}); bundle "
                  "follows on stderr", file=sys.stderr)
            print(text, file=sys.stderr)
        return path

    def _hard_exit(self, name: str, elapsed: float, budget: float) -> None:
        print(f"watchdog: phase {name!r} still stuck {elapsed:.1f}s after "
              f"a {budget:.1f}s budget (+{self.hard_exit_s:.1f}s grace) — "
              f"hard-exiting {EXIT_STALL} for the supervisor",
              file=sys.stderr, flush=True)
        print(self._bundle(name, elapsed, budget), file=sys.stderr,
              flush=True)
        os._exit(EXIT_STALL)


class _PhaseGuard:
    def __init__(self, wd: Watchdog, name: str):
        self._wd, self._name = wd, name

    def __enter__(self):
        self._wd._enter(self._name)
        return self

    def __exit__(self, *exc) -> None:
        self._wd._exit(self._name)


class NullWatchdog:
    """Disabled watchdog with the same surface (train.watchdog.enabled=False
    keeps the Trainer free of `if wd is not None` at every phase)."""

    stall_count = 0
    stalled_phases: list = []

    def start(self) -> "NullWatchdog":
        return self

    def stop(self) -> None:
        pass

    def beat(self, name: str) -> None:
        pass

    def phase(self, name: str):
        return _NullGuard()

    def check(self, now=None):
        return None


class _NullGuard:
    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


def from_config(wcfg, on_stall=None, diagnosis_dir: str = ".",
                query_device: bool = True):
    """Watchdog (or NullWatchdog) from a config.WatchdogConfig."""
    if not wcfg.enabled:
        return NullWatchdog()
    budgets = {f"{p}_s": getattr(wcfg, field)
               for p, field in PHASE_BUDGET_FIELDS.items()}
    return Watchdog(budgets, on_stall,
                    check_interval_s=wcfg.check_interval_s,
                    hard_exit_s=wcfg.hard_exit_s,
                    diagnosis_dir=diagnosis_dir,
                    query_device=query_device)
