"""Deterministic fault injection for the fault-tolerance subsystem.

Every recovery path in the training stack (train/guard.py anomaly guard,
train/checkpoint.py integrity fallback, data/srn.py record quarantine,
trainer SIGTERM drill) is exercised by injecting the fault it recovers
from, on CPU, in tier-1 tests (tests/test_fault_injection.py). Injection
points are env-driven so a test — or a chaos drill on a real pod — can arm
them without touching config files; with no NVS3D_FI_* variable set, every
hook is inert and the hot path pays nothing (the NaN-loss hook is read at
TRACE time, so a clean build contains no injection ops at all).

Injection points:

  NVS3D_FI_NAN_LOSS_AT      comma list of global steps; the jitted train
                            step overwrites loss AND gradients with NaN at
                            those steps (read when make_train_step traces —
                            set it before the Trainer is built).
  NVS3D_FI_NAN_GRAD_GROUP   layer-group label (models/xunet.op_groups,
                            e.g. "XUNetBlock_1"); scopes the NaN-step
                            gradient poisoning above to that group's
                            params only (loss is still poisoned). The
                            NaN-provenance drill: the numerics
                            observatory must name exactly this group as
                            first_bad_layer. Trace-time read; inert
                            without NVS3D_FI_NAN_LOSS_AT.
  NVS3D_FI_RAISE_ON_RECORD  comma list of flat record indices;
                            SRNDataset.pair raises InjectedFault for them
                            (read per call).
  NVS3D_FI_SIGTERM_AT       single step; the Trainer sends itself SIGTERM
                            when the loop reaches it (read per call).
  NVS3D_FI_STALL_DATA_AT    "<step>[:<seconds>]"; the Trainer's host batch
  NVS3D_FI_STALL_STEP_AT    fetch / train-step dispatch / checkpoint save
  NVS3D_FI_STALL_SAVE_AT    SLEEPS for <seconds> (default 30) when the
                            loop is at exactly that global step — the hang
                            drill for utils/watchdog.py. Exact-step match,
                            so a supervised restart that resumes PAST the
                            armed step does not re-stall.
  NVS3D_FI_PROBE_HANG       "1": parallel/dist.probe_backend's disposable
                            child sleeps forever (wedged-tunnel drill);
  NVS3D_FI_PROBE_FAIL       "1": the probe child exits non-zero instead
                            (dead-backend drill, no timeout burn).
  NVS3D_FI_CORRUPT_SHARD_AT comma list of packed-shard ordinals; the
                            packed-record reader (data/records.py) sees a
                            FLIPPED BYTE in those shards' streams at open
                            (sha256 mismatch → shard quarantined). The
                            mutation is in-memory — disk is untouched.
  NVS3D_FI_TRUNCATE_SHARD_AT same, but the stream is cut in half (torn
                            tail → end marker missing → quarantined),
                            the shape a host dying mid-write leaves.

Serving-plane points (sample/service.py stepper ring, registry/watcher.py;
the chaos drills in tests/test_serve_chaos.py and `serve_bench --chaos`):

  NVS3D_FI_SERVE_NAN_AT     "<dispatch>[:<row>]" (row defaults to 0); the
                            stepper poisons ring row <row>'s carried z
                            with NaN just before ring dispatch number
                            <dispatch> — the device-side finite mask must
                            quarantine exactly that slot. Exact-dispatch
                            match, so it fires once.
  NVS3D_FI_SERVE_WORKER_DIE_AT
                            single dispatch ordinal; the service worker
                            thread raises InjectedFault OUTSIDE the ring
                            try-block at that dispatch (worker-death
                            drill for the serve supervisor). One shot:
                            cleared on fire so the restarted worker
                            lives.
  NVS3D_FI_SERVE_DISPATCH_RAISE_AT
                            comma list of dispatch ordinals; the ring
                            step / group dispatch raises InjectedFault
                            INSIDE the guarded region (fail-the-ring,
                            keep-serving drill).
  NVS3D_FI_SERVE_SWAP_FAIL  integer N; the next N registry swap attempts
                            (RegistryWatcher.poll_once) raise
                            InjectedFault before verify — the circuit
                            breaker / half-open-recovery drill. The
                            counter decrements per fire and the env var
                            is cleared at 0, so the (N+1)th attempt
                            succeeds.
  NVS3D_FI_SERVE_SLOW_STEP  "<dispatch>[:<seconds>]"; the stepper SLEEPS
                            for <seconds> (default 30) at exactly that
                            ring dispatch — the wedged-worker drill for
                            SamplingService.stop()'s join-timeout
                            diagnosis and the brownout step-debt drill.
                            "*[:<seconds>]" slows EVERY dispatch — the
                            gray-failure drill: the replica stays alive
                            and healthy-looking but its p99 inflates,
                            which the fleet router's demotion + hedging
                            defenses must absorb.
  NVS3D_FI_SERVE_HEARTBEAT_STOP
                            "1": the replica process's ready-file
                            heartbeat thread stops touching the file —
                            the wedged-process drill for the fleet
                            supervisor's heartbeat-age detector (the
                            process is alive, its event loop is not).

plus `truncate_checkpoint`, a direct helper that corrupts an on-disk Orbax
step the way a mid-write preemption does (the checkpoint-fallback drill).
"""

from __future__ import annotations

import os
import signal
from typing import List, Optional, Tuple


class InjectedFault(RuntimeError):
    """A fault raised by the injection harness (never by real code)."""


def _int_list(env: str) -> Tuple[int, ...]:
    raw = os.environ.get(env, "").strip()
    if not raw:
        return ()
    try:
        return tuple(int(v) for v in raw.split(",") if v.strip())
    except ValueError as e:
        raise ValueError(f"{env}={raw!r} must be a comma list of ints") from e


def nan_loss_steps() -> Tuple[int, ...]:
    """Steps whose loss/grads the train step poisons (trace-time read)."""
    return _int_list("NVS3D_FI_NAN_LOSS_AT")


def nan_grad_group() -> str:
    """Layer-group label scoping the NaN-step grad poisoning ("" = whole
    tree, the default). Trace-time read, like nan_loss_steps."""
    return os.environ.get("NVS3D_FI_NAN_GRAD_GROUP", "").strip()


def record_fault_indices() -> Tuple[int, ...]:
    return _int_list("NVS3D_FI_RAISE_ON_RECORD")


def maybe_raise_record(flat_idx: int) -> None:
    """Hook for SRNDataset.pair: raise for records armed via env."""
    if flat_idx in record_fault_indices():
        raise InjectedFault(
            f"injected data fault at record {flat_idx} "
            "(NVS3D_FI_RAISE_ON_RECORD)")


def sigterm_step() -> Optional[int]:
    steps = _int_list("NVS3D_FI_SIGTERM_AT")
    return steps[0] if steps else None


def maybe_sigterm(step: int) -> bool:
    """Hook for the Trainer loop: deliver SIGTERM to this process at the
    armed step (the preemption drill). Returns True if the signal fired."""
    at = sigterm_step()
    if at is not None and step >= at:
        os.kill(os.getpid(), signal.SIGTERM)
        # One shot: clear so the rescheduled (resumed) run isn't re-killed.
        os.environ.pop("NVS3D_FI_SIGTERM_AT", None)
        return True
    return False


def corrupt_shard_ordinals() -> Tuple[int, ...]:
    """Packed-shard ordinals whose open-time stream gets a flipped byte."""
    return _int_list("NVS3D_FI_CORRUPT_SHARD_AT")


def truncate_shard_ordinals() -> Tuple[int, ...]:
    """Packed-shard ordinals whose open-time stream is torn (truncated)."""
    return _int_list("NVS3D_FI_TRUNCATE_SHARD_AT")


def maybe_corrupt_shard_bytes(ordinal: int, data: bytes) -> bytes:
    """Hook for the packed-record reader (data/records.py): mutate shard
    `ordinal`'s byte stream AS READ at open. Truncation halves the stream
    (a torn tail — the end marker vanishes); corruption XORs one middle
    byte (the sha256 re-hash catches it). Disk is never touched, so the
    same corpus serves clean runs and drills; with neither env var set
    the stream passes through untouched."""
    if ordinal in truncate_shard_ordinals():
        data = data[: len(data) // 2]
    if ordinal in corrupt_shard_ordinals() and data:
        i = len(data) // 2
        data = data[:i] + bytes([data[i] ^ 0x01]) + data[i + 1:]
    return data


_STALL_ENVS = {
    "data": "NVS3D_FI_STALL_DATA_AT",
    "step": "NVS3D_FI_STALL_STEP_AT",
    "save": "NVS3D_FI_STALL_SAVE_AT",
}
_DEFAULT_STALL_S = 30.0


def stall_spec(kind: str) -> Optional[Tuple[int, float]]:
    """(step, seconds) armed for a stall kind ('data'|'step'|'save').

    Env format "<step>" (default 30 s) or "<step>:<seconds>"."""
    env = _STALL_ENVS[kind]
    raw = os.environ.get(env, "").strip()
    if not raw:
        return None
    step_s, _, dur_s = raw.partition(":")
    try:
        return int(step_s), float(dur_s) if dur_s else _DEFAULT_STALL_S
    except ValueError as e:
        raise ValueError(
            f"{env}={raw!r} must be '<step>' or '<step>:<seconds>'") from e


def maybe_stall(kind: str, step: int) -> float:
    """Hook for the Trainer's phases: sleep if a stall of `kind` is armed
    at exactly this step (the hang drill). Returns the seconds slept (0.0
    when inert). Exact match — a resumed run past the armed step runs
    clean, so supervised-restart drills terminate."""
    spec = stall_spec(kind)
    if spec is None or spec[0] != step:
        return 0.0
    import time

    print(f"[faultinject] stalling {kind} at step {step} for "
          f"{spec[1]:.1f}s ({_STALL_ENVS[kind]})", flush=True)
    time.sleep(spec[1])
    return spec[1]


def serve_nan_spec() -> Optional[Tuple[int, int]]:
    """(dispatch, row) armed for the ring NaN-poison drill.

    Env format "<dispatch>" (row 0) or "<dispatch>:<row>"."""
    raw = os.environ.get("NVS3D_FI_SERVE_NAN_AT", "").strip()
    if not raw:
        return None
    disp_s, _, row_s = raw.partition(":")
    try:
        return int(disp_s), int(row_s) if row_s else 0
    except ValueError as e:
        raise ValueError(
            f"NVS3D_FI_SERVE_NAN_AT={raw!r} must be '<dispatch>' or "
            "'<dispatch>:<row>'") from e


def maybe_serve_worker_die(dispatch: int) -> None:
    """Hook for the service worker loop (OUTSIDE the per-dispatch guard):
    raise at the armed ring dispatch, killing the thread. One shot — the
    env var is cleared so the supervisor's restarted worker runs clean."""
    ats = _int_list("NVS3D_FI_SERVE_WORKER_DIE_AT")
    if ats and dispatch >= ats[0]:
        os.environ.pop("NVS3D_FI_SERVE_WORKER_DIE_AT", None)
        raise InjectedFault(
            f"injected worker death at ring dispatch {dispatch} "
            "(NVS3D_FI_SERVE_WORKER_DIE_AT)")


def maybe_serve_dispatch_raise(dispatch: int) -> None:
    """Hook INSIDE the guarded ring-step/dispatch region: raise at the
    armed dispatch ordinals (fail-the-group, keep-serving drill)."""
    if dispatch in _int_list("NVS3D_FI_SERVE_DISPATCH_RAISE_AT"):
        raise InjectedFault(
            f"injected dispatch failure at ring dispatch {dispatch} "
            "(NVS3D_FI_SERVE_DISPATCH_RAISE_AT)")


def maybe_serve_swap_fail() -> None:
    """Hook for RegistryWatcher.poll_once: fail the next N swap attempts,
    decrementing the armed count so attempt N+1 succeeds (the half-open
    recovery drill)."""
    raw = os.environ.get("NVS3D_FI_SERVE_SWAP_FAIL", "").strip()
    if not raw:
        return
    try:
        n = int(raw)
    except ValueError as e:
        raise ValueError(
            f"NVS3D_FI_SERVE_SWAP_FAIL={raw!r} must be an int") from e
    if n <= 0:
        os.environ.pop("NVS3D_FI_SERVE_SWAP_FAIL", None)
        return
    if n - 1 <= 0:
        os.environ.pop("NVS3D_FI_SERVE_SWAP_FAIL", None)
    else:
        os.environ["NVS3D_FI_SERVE_SWAP_FAIL"] = str(n - 1)
    raise InjectedFault(
        "injected registry swap failure (NVS3D_FI_SERVE_SWAP_FAIL, "
        f"{n - 1} left)")


def serve_slow_step_spec() -> Optional[Tuple[Optional[int], float]]:
    """(dispatch, seconds) armed for the slow-ring-step drill; dispatch
    is None for the every-dispatch ("*") gray-failure form.

    Env format "<dispatch>" (default 30 s), "<dispatch>:<seconds>", or
    "*[:<seconds>]"."""
    raw = os.environ.get("NVS3D_FI_SERVE_SLOW_STEP", "").strip()
    if not raw:
        return None
    disp_s, _, dur_s = raw.partition(":")
    try:
        at = None if disp_s.strip() == "*" else int(disp_s)
        return at, float(dur_s) if dur_s else _DEFAULT_STALL_S
    except ValueError as e:
        raise ValueError(
            f"NVS3D_FI_SERVE_SLOW_STEP={raw!r} must be '<dispatch>', "
            "'<dispatch>:<seconds>', or '*[:<seconds>]'") from e


_slow_step_announced = False


def maybe_serve_slow_step(dispatch: int) -> float:
    """Hook for the stepper ring: sleep if armed at exactly this dispatch
    (the wedged-worker drill) or at EVERY dispatch ("*" — the
    gray-failure drill). Returns seconds slept (0.0 when inert)."""
    spec = serve_slow_step_spec()
    if spec is None or (spec[0] is not None and spec[0] != dispatch):
        return 0.0
    import time

    global _slow_step_announced
    if spec[0] is not None or not _slow_step_announced:
        _slow_step_announced = True
        print(f"[faultinject] slow ring step at dispatch {dispatch} for "
              f"{spec[1]:.1f}s (NVS3D_FI_SERVE_SLOW_STEP"
              f"{', every dispatch' if spec[0] is None else ''})",
              flush=True)
    time.sleep(spec[1])
    return spec[1]


def serve_heartbeat_stopped() -> bool:
    """Hook for the replica process's ready-file heartbeat thread: True
    while NVS3D_FI_SERVE_HEARTBEAT_STOP is armed, freezing the mtime so
    the fleet supervisor's heartbeat-age detector sees a wedged process
    that is still answering nothing-in-particular."""
    return os.environ.get(
        "NVS3D_FI_SERVE_HEARTBEAT_STOP", "").strip() == "1"


def armed() -> List[str]:
    """Names of the NVS3D_FI_* variables currently set (for loud logging:
    a production entry point should refuse to run silently with faults
    armed)."""
    return sorted(k for k in os.environ
                  if k.startswith("NVS3D_FI_") and os.environ[k].strip())


def truncate_checkpoint(directory: str, step: Optional[int] = None,
                        keep_bytes: int = 16) -> List[str]:
    """Corrupt an on-disk Orbax checkpoint step like a torn write would.

    Truncates every regular file under the step directory to `keep_bytes`
    (metadata and array data alike), which is what a host dying mid-save
    leaves behind. Returns the corrupted paths. `step=None` corrupts the
    NEWEST step dir — the auto-resume target, i.e. the worst case the
    fallback restore must handle.
    """
    directory = os.path.abspath(directory)
    step_dirs = sorted(
        (int(d), os.path.join(directory, d))
        for d in os.listdir(directory) if d.isdigit())
    if not step_dirs:
        raise FileNotFoundError(f"no checkpoint steps under {directory!r}")
    if step is None:
        _, target = step_dirs[-1]
    else:
        matches = [p for s, p in step_dirs if s == step]
        if not matches:
            raise FileNotFoundError(
                f"no step {step} under {directory!r} "
                f"(have {[s for s, _ in step_dirs]})")
        target = matches[0]
    corrupted = []
    for root, _, files in os.walk(target):
        for fn in files:
            path = os.path.join(root, fn)
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as fh:
                    fh.truncate(min(size, keep_bytes))
                corrupted.append(path)
            except OSError:
                continue
    return corrupted
