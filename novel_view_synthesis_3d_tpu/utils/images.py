"""Image output utilities (PNG files instead of the reference's blocking
`cv2.imshow` window, sampling.py:153-154)."""

from __future__ import annotations

import os

import numpy as np
from PIL import Image


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[-1, 1] float image → uint8 (the reference displays z/2 + 0.5)."""
    img = np.asarray(img)
    return np.clip(np.round((img / 2.0 + 0.5) * 255.0), 0, 255).astype(np.uint8)


def convert_image(img: np.ndarray) -> np.ndarray:
    """Model-space image (any layout, [-1, 1]) → displayable uint8 HWC RGB.

    Capability-parity with the reference's `convert_image`
    (dataset/util.py:26-37), minus its torch/BGR round-trip: squeezes batch
    dims and moves CHW to HWC if needed; range mapping via `to_uint8`.
    """
    img = np.asarray(img, dtype=np.float32).squeeze()
    if img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[-1] not in (1, 3):
        img = img.transpose(1, 2, 0)
    return to_uint8(img)


def normalize01(img: np.ndarray) -> np.ndarray:
    """Min-max normalize to [0, 1] (reference util.py:108-109)."""
    img = np.asarray(img, dtype=np.float32)
    lo, hi = img.min(), img.max()
    return (img - lo) / (hi - lo) if hi > lo else np.zeros_like(img)


def save_image(img: np.ndarray, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    Image.fromarray(to_uint8(img)).save(path)


def save_animation(imgs: np.ndarray, path: str, fps: float = 8.0) -> None:
    """(N, H, W, 3) in [-1, 1] → animated GIF (looping).

    Turntable/orbit export for sampled view sequences — the closest the
    reference gets is a blocking per-view cv2 window (sampling.py:153-154).
    """
    imgs = np.asarray(imgs)
    if imgs.ndim != 4 or imgs.shape[0] < 1:
        raise ValueError(f"expected (N, H, W, C), got {imgs.shape}")
    if not fps > 0:
        raise ValueError(f"fps must be positive, got {fps}")
    frames = [Image.fromarray(to_uint8(f)) for f in imgs]
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    frames[0].save(path, save_all=True, append_images=frames[1:],
                   duration=max(1, int(round(1000.0 / fps))), loop=0)


def save_image_strip(imgs: np.ndarray, path: str) -> None:
    """(N, H, W, 3) in [-1, 1] → one horizontal strip PNG — the orbit
    contact sheet the trajectory-serving demo writes (frame order reads
    left to right)."""
    save_image_grid(imgs, path, cols=np.asarray(imgs).shape[0])


def save_image_grid(imgs: np.ndarray, path: str, cols: int = 4) -> None:
    """(N, H, W, 3) in [-1, 1] → one tiled PNG."""
    imgs = np.asarray(imgs)
    n, h, w, c = imgs.shape
    cols = min(cols, n)
    rows = (n + cols - 1) // cols
    grid = np.full((rows * h, cols * w, c), 255, dtype=np.uint8)
    for i in range(n):
        r, col = divmod(i, cols)
        grid[r * h:(r + 1) * h, col * w:(col + 1) * w] = to_uint8(imgs[i])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    Image.fromarray(grid).save(path)
