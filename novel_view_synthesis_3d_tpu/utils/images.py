"""Image output utilities (PNG files instead of the reference's blocking
`cv2.imshow` window, sampling.py:153-154)."""

from __future__ import annotations

import os

import numpy as np
from PIL import Image


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[-1, 1] float image → uint8 (the reference displays z/2 + 0.5)."""
    img = np.asarray(img)
    return np.clip((img / 2.0 + 0.5) * 255.0, 0, 255).astype(np.uint8)


def save_image(img: np.ndarray, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    Image.fromarray(to_uint8(img)).save(path)


def save_image_grid(imgs: np.ndarray, path: str, cols: int = 4) -> None:
    """(N, H, W, 3) in [-1, 1] → one tiled PNG."""
    imgs = np.asarray(imgs)
    n, h, w, c = imgs.shape
    cols = min(cols, n)
    rows = (n + cols - 1) // cols
    grid = np.full((rows * h, cols * w, c), 255, dtype=np.uint8)
    for i in range(n):
        r, col = divmod(i, cols)
        grid[r * h:(r + 1) * h, col * w:(col + 1) * w] = to_uint8(imgs[i])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    Image.fromarray(grid).save(path)
