"""Profiling + debug instrumentation (SURVEY.md §5.1-5.2).

The reference has zero instrumentation (one print at train.py:157, an unused
tqdm import). Here:

  - `trace_window`: jax.profiler trace of a step window, viewable in
    TensorBoard/XProf (device + host timelines, HLO cost analysis);
  - `StepTimer`: lightweight wall-clock step timing with percentile summary
    (no profiler overhead, always-on capable);
  - `enable_nan_checks` / `check_finite`: jax_debug_nans config plus an
    explicit in-jit finite-check via `jax.debug` error checking for debug
    runs (the "sanitizer" role — the reference has no native code to TSAN,
    its failure mode is silent NaNs).
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading
import time
from typing import Dict, Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace_window(log_dir: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler trace context; no-op when disabled."""
    if not enabled:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock per-step timing with summary statistics.

    `units_per_measure` > 1 marks each measured region as covering that
    many steps (fused multi-step dispatch): recorded times are normalized
    to per-step so summaries stay comparable across dispatch widths
    (within-window per-step variation is unobservable, so each window
    contributes its mean).

    Retains only the most recent `window` measurements (the same
    deque(maxlen) pattern and count-vs-window semantics as ServiceStats:
    a million-step run must not grow host memory per step). `summary()`
    percentiles reflect the sliding window; `steps` is the total ever
    measured."""

    def __init__(self, units_per_measure: int = 1, window: int = 4096):
        self._times: "collections.deque" = collections.deque(
            maxlen=max(1, window))
        self._count = 0  # measures ever taken (window-independent)
        self._t0: Optional[float] = None
        self._units = max(1, units_per_measure)

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        assert self._t0 is not None, "start() not called"
        dt = (time.perf_counter() - self._t0) / self._units
        self._times.append(dt)
        self._count += 1
        self._t0 = None
        return dt

    @contextlib.contextmanager
    def measure(self) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def last_s(self) -> Optional[float]:
        """Most recent per-step seconds (None before the first stop) —
        the live step-rate estimate the MFU gauge divides by."""
        return self._times[-1] if self._times else None

    def summary(self) -> dict:
        if not self._times:
            return {}
        arr = np.asarray(self._times)
        return {
            "steps": self._count * self._units,
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p90_s": float(np.percentile(arr, 90)),
            "p99_s": float(np.percentile(arr, 99)),
        }


class ServiceStats:
    """Serving-side instrumentation: per-request span timings plus a
    requests-per-second counter (sample/service.py).

    Spans are named ('queue_wait', 'compile', 'device', …); each record is
    one request's seconds in that span. Thread-safe — the micro-batcher's
    worker thread records while callers read summaries. Percentiles use
    the same p50/p90/p99 ladder as StepTimer so serving and training
    timing read alike.

    Each span keeps only the most recent `window` records (a long-lived
    service serving millions of requests must not grow host memory per
    request): percentiles reflect that sliding window, while `count` is
    the total ever recorded for the span."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = max(1, window)
        self._spans: Dict[str, "collections.deque"] = {}
        self._span_totals: Dict[str, int] = {}
        self._requests = 0
        self._t0: Optional[float] = None

    def record_span(self, name: str, seconds: float) -> None:
        with self._lock:
            dq = self._spans.get(name)
            if dq is None:
                dq = self._spans[name] = collections.deque(
                    maxlen=self._window)
            dq.append(float(seconds))
            self._span_totals[name] = self._span_totals.get(name, 0) + 1

    def count_requests(self, n: int = 1) -> None:
        """Count completed requests; the RPS window opens at the first."""
        with self._lock:
            if self._t0 is None:
                self._t0 = time.perf_counter()
            self._requests += n

    def span_summary(self, name: str) -> dict:
        with self._lock:
            vals = list(self._spans.get(name, ()))
            total = self._span_totals.get(name, 0)
        if not vals:
            return {}
        arr = np.asarray(vals)
        return {
            "count": total,
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p90_s": float(np.percentile(arr, 90)),
            "p99_s": float(np.percentile(arr, 99)),
        }

    def summary(self) -> dict:
        with self._lock:
            names = sorted(self._spans)
            requests = self._requests
            elapsed = (time.perf_counter() - self._t0
                       if self._t0 is not None else 0.0)
        out: dict = {"requests": requests}
        if elapsed > 0:
            out["requests_per_sec"] = requests / elapsed
        for name in names:
            out[name] = self.span_summary(name)
        return out


_logged_once: set = set()


def log_once(key, msg: str) -> bool:
    """Emit `msg` on stderr the FIRST time `key` is seen; drop repeats.

    For conditions that are worth exactly one line per process — e.g. a
    fused kernel silently falling back to XLA (ops/fused_groupnorm.py via
    models/layers.py): the fallback fires per traced call site, and a log
    per trace would be noise while zero logs hides a perf cliff."""
    if key in _logged_once:
        return False
    _logged_once.add(key)
    print(msg, file=sys.stderr, flush=True)
    return True


def reset_log_once(key=None) -> None:
    """Forget `key` (or, with no argument, every key) so the next
    log_once fires again. For tests: the once-per-process set otherwise
    leaks one-shot state across cases — an assertion that a message WAS
    logged passes or fails depending on which test ran first."""
    if key is None:
        _logged_once.clear()
    else:
        _logged_once.discard(key)


def enable_nan_checks(enabled: bool = True) -> None:
    """Turn on jax_debug_nans: any NaN-producing jitted op re-runs op-by-op
    and raises with the originating primitive — the debug-mode default for
    this framework's tests and repro runs."""
    jax.config.update("jax_debug_nans", enabled)


def check_finite(tree, name: str = "tree") -> None:
    """Host-side finite assertion over a pytree (checkpoint/debug guard)."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(
            f"non-finite values in {name}: {', '.join(bad[:8])}")
