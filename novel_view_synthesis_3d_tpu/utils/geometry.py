"""Camera/rotation geometry helpers.

Capability-parity with the geometry utilities of the reference
(`/root/reference/dataset/data_util.py:145-201` — `euler2mat`, `look_at`,
`transform_viewpoint`), plus pose-trajectory generators the reference lacks
but sampling needs: the reference's sampler can only re-use dataset poses,
while novel-view *generation* wants arbitrary camera orbits.

All functions are plain numpy (host-side pose preparation); the on-device
geometry (ray generation) lives in models/rays.py.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def euler2mat(z: float = 0.0, y: float = 0.0, x: float = 0.0) -> np.ndarray:
    """Rotation matrix from Euler angles, composed as Rx @ Ry @ Rz.

    Matches the reference semantics (data_util.py:155-180, which reduces the
    [Rz, Ry, Rx] list reversed): angles are radians, zero angles contribute
    identity, and the z rotation is applied first (returned matrix Rx·Ry·Rz).
    """
    cz, sz = np.cos(z), np.sin(z)
    cy, sy = np.cos(y), np.sin(y)
    cx, sx = np.cos(x), np.sin(x)
    Rz = np.array([[cz, -sz, 0.0], [sz, cz, 0.0], [0.0, 0.0, 1.0]])
    Ry = np.array([[cy, 0.0, sy], [0.0, 1.0, 0.0], [-sy, 0.0, cy]])
    Rx = np.array([[1.0, 0.0, 0.0], [0.0, cx, -sx], [0.0, sx, cx]])
    return Rx @ Ry @ Rz


def look_at(position: np.ndarray, target: np.ndarray,
            up: Optional[np.ndarray] = None) -> np.ndarray:
    """cam→world rotation whose columns are the camera's (x, y, z) axes.

    z points from `position` toward `target`; x = z × up; y = x × z
    (reference data_util.py:183-199 uses up = +Y).
    """
    position = np.asarray(position, dtype=np.float64)
    target = np.asarray(target, dtype=np.float64)
    up = np.array([0.0, 1.0, 0.0]) if up is None else np.asarray(up, float)

    z = target - position
    z = z / np.linalg.norm(z)
    x = np.cross(z, up)
    x = x / np.linalg.norm(x)
    y = np.cross(x, z)
    y = y / np.linalg.norm(y)
    return np.stack([x, y, z], axis=1)


def pose_from_look_at(position: np.ndarray, target: np.ndarray,
                      up: Optional[np.ndarray] = None) -> np.ndarray:
    """4×4 cam→world pose (rotation from `look_at`, translation = position)."""
    pose = np.eye(4, dtype=np.float32)
    pose[:3, :3] = look_at(position, target, up)
    pose[:3, 3] = np.asarray(position, dtype=np.float32)
    return pose


def spherical_position(radius: float, azimuth: float,
                       elevation: float) -> np.ndarray:
    """Point on a sphere (Y-up convention: azimuth about +Y, elevation from
    the horizontal plane)."""
    ce = np.cos(elevation)
    return np.array([
        radius * ce * np.sin(azimuth),
        radius * np.sin(elevation),
        radius * ce * np.cos(azimuth),
    ])


def orbit_poses(num: int, radius: float, elevation: float = 0.0,
                target: Sequence[float] = (0.0, 0.0, 0.0),
                full_turns: float = 1.0) -> np.ndarray:
    """(num, 4, 4) cam→world poses on a circular orbit around `target`.

    The canonical novel-view sampling trajectory (the reference has no pose
    generator — its sampler only replays dataset poses). Azimuths are evenly
    spaced over `full_turns` revolutions at constant `elevation`.
    """
    target = np.asarray(target, dtype=np.float64)
    azimuths = np.linspace(0.0, 2.0 * np.pi * full_turns, num, endpoint=False)
    poses = [pose_from_look_at(target + spherical_position(radius, az, elevation),
                               target)
             for az in azimuths]
    return np.stack(poses).astype(np.float32)


def _mat_to_quat(R: np.ndarray) -> np.ndarray:
    """Rotation matrix → unit quaternion (w, x, y, z), Shepperd's method."""
    m = np.asarray(R, dtype=np.float64)
    t = np.trace(m)
    if t > 0:
        s = np.sqrt(t + 1.0) * 2.0
        q = np.array([0.25 * s, (m[2, 1] - m[1, 2]) / s,
                      (m[0, 2] - m[2, 0]) / s, (m[1, 0] - m[0, 1]) / s])
    else:
        i = int(np.argmax(np.diag(m)))
        j, k = (i + 1) % 3, (i + 2) % 3
        s = np.sqrt(max(m[i, i] - m[j, j] - m[k, k] + 1.0, 0.0)) * 2.0
        q = np.empty(4)
        q[0] = (m[k, j] - m[j, k]) / s
        q[1 + i] = 0.25 * s
        q[1 + j] = (m[j, i] + m[i, j]) / s
        q[1 + k] = (m[k, i] + m[i, k]) / s
    return q / np.linalg.norm(q)


def _quat_to_mat(q: np.ndarray) -> np.ndarray:
    w, x, y, z = q / np.linalg.norm(q)
    return np.array([
        [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
    ])


def _slerp(qa: np.ndarray, qb: np.ndarray, u: float) -> np.ndarray:
    """Spherical interpolation between unit quaternions (shortest arc)."""
    dot = float(np.dot(qa, qb))
    if dot < 0.0:  # q and -q are the same rotation; take the short way
        qb, dot = -qb, -dot
    if dot > 0.9995:  # nearly parallel: lerp avoids a 0/0
        q = qa + u * (qb - qa)
        return q / np.linalg.norm(q)
    theta = np.arccos(np.clip(dot, -1.0, 1.0))
    return (np.sin((1.0 - u) * theta) * qa
            + np.sin(u * theta) * qb) / np.sin(theta)


def interpolate_poses(keyframes: np.ndarray, num: int,
                      closed: bool = True) -> np.ndarray:
    """(num, 4, 4) smooth path through (M, 4, 4) keyframe cam→world poses.

    Rotations take the quaternion slerp shortest arc between consecutive
    keyframes; translations interpolate linearly. `closed=True` loops back
    to the first keyframe (seamless turntable GIFs); False ends at the last
    keyframe. Framework extension — the reference can only replay dataset
    poses (sampling.py uses the loader's poses verbatim).
    """
    keyframes = np.asarray(keyframes, dtype=np.float64)
    M = keyframes.shape[0]
    if M < 2:
        raise ValueError(f"need >= 2 keyframes, got {M}")
    if num < 1:
        raise ValueError(f"num must be >= 1, got {num}")
    quats = [_mat_to_quat(k[:3, :3]) for k in keyframes]
    n_seg = M if closed else M - 1
    # Global parameter s ∈ [0, n_seg): endpoint excluded when closed (the
    # loop wraps), included when open (end exactly at the last keyframe).
    s_vals = (np.arange(num) * n_seg / num if closed
              else np.linspace(0.0, n_seg, num))
    poses = []
    for s in s_vals:
        seg = min(int(np.floor(s)), n_seg - 1)
        u = s - seg
        a, b = seg % M, (seg + 1) % M
        pose = np.eye(4)
        pose[:3, :3] = _quat_to_mat(_slerp(quats[a], quats[b], u))
        pose[:3, 3] = ((1.0 - u) * keyframes[a][:3, 3]
                       + u * keyframes[b][:3, 3])
        poses.append(pose)
    return np.stack(poses).astype(np.float32)


def transform_viewpoint(v: np.ndarray) -> np.ndarray:
    """(N, 5) [x, y, z, yaw, pitch] → (N, 7) [x, y, z, cos/sin yaw, cos/sin
    pitch] — the consistent viewpoint representation of data_util.py:145-152."""
    v = np.asarray(v)
    return np.concatenate([
        v[:, :3],
        np.cos(v[:, 3:4]), np.sin(v[:, 3:4]),
        np.cos(v[:, 4:5]), np.sin(v[:, 4:5]),
    ], axis=1)


def rotation_angle(Ra: np.ndarray, Rb: np.ndarray) -> float:
    """Geodesic angle (radians) between two rotation matrices."""
    cos = (np.trace(Ra.T @ Rb) - 1.0) / 2.0
    return float(np.arccos(np.clip(cos, -1.0, 1.0)))
