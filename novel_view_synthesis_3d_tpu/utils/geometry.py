"""Camera/rotation geometry helpers.

Capability-parity with the geometry utilities of the reference
(`/root/reference/dataset/data_util.py:145-201` — `euler2mat`, `look_at`,
`transform_viewpoint`), plus pose-trajectory generators the reference lacks
but sampling needs: the reference's sampler can only re-use dataset poses,
while novel-view *generation* wants arbitrary camera orbits.

All functions are plain numpy (host-side pose preparation); the on-device
geometry (ray generation) lives in models/rays.py.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def euler2mat(z: float = 0.0, y: float = 0.0, x: float = 0.0) -> np.ndarray:
    """Rotation matrix from Euler angles, composed as Rx @ Ry @ Rz.

    Matches the reference semantics (data_util.py:155-180, which reduces the
    [Rz, Ry, Rx] list reversed): angles are radians, zero angles contribute
    identity, and the z rotation is applied first (returned matrix Rx·Ry·Rz).
    """
    cz, sz = np.cos(z), np.sin(z)
    cy, sy = np.cos(y), np.sin(y)
    cx, sx = np.cos(x), np.sin(x)
    Rz = np.array([[cz, -sz, 0.0], [sz, cz, 0.0], [0.0, 0.0, 1.0]])
    Ry = np.array([[cy, 0.0, sy], [0.0, 1.0, 0.0], [-sy, 0.0, cy]])
    Rx = np.array([[1.0, 0.0, 0.0], [0.0, cx, -sx], [0.0, sx, cx]])
    return Rx @ Ry @ Rz


def look_at(position: np.ndarray, target: np.ndarray,
            up: Optional[np.ndarray] = None) -> np.ndarray:
    """cam→world rotation whose columns are the camera's (x, y, z) axes.

    z points from `position` toward `target`; x = z × up; y = x × z
    (reference data_util.py:183-199 uses up = +Y).
    """
    position = np.asarray(position, dtype=np.float64)
    target = np.asarray(target, dtype=np.float64)
    up = np.array([0.0, 1.0, 0.0]) if up is None else np.asarray(up, float)

    z = target - position
    z = z / np.linalg.norm(z)
    x = np.cross(z, up)
    x = x / np.linalg.norm(x)
    y = np.cross(x, z)
    y = y / np.linalg.norm(y)
    return np.stack([x, y, z], axis=1)


def pose_from_look_at(position: np.ndarray, target: np.ndarray,
                      up: Optional[np.ndarray] = None) -> np.ndarray:
    """4×4 cam→world pose (rotation from `look_at`, translation = position)."""
    pose = np.eye(4, dtype=np.float32)
    pose[:3, :3] = look_at(position, target, up)
    pose[:3, 3] = np.asarray(position, dtype=np.float32)
    return pose


def spherical_position(radius: float, azimuth: float,
                       elevation: float) -> np.ndarray:
    """Point on a sphere (Y-up convention: azimuth about +Y, elevation from
    the horizontal plane)."""
    ce = np.cos(elevation)
    return np.array([
        radius * ce * np.sin(azimuth),
        radius * np.sin(elevation),
        radius * ce * np.cos(azimuth),
    ])


def orbit_poses(num: int, radius: float, elevation: float = 0.0,
                target: Sequence[float] = (0.0, 0.0, 0.0),
                full_turns: float = 1.0) -> np.ndarray:
    """(num, 4, 4) cam→world poses on a circular orbit around `target`.

    The canonical novel-view sampling trajectory (the reference has no pose
    generator — its sampler only replays dataset poses). Azimuths are evenly
    spaced over `full_turns` revolutions at constant `elevation`.
    """
    target = np.asarray(target, dtype=np.float64)
    azimuths = np.linspace(0.0, 2.0 * np.pi * full_turns, num, endpoint=False)
    poses = [pose_from_look_at(target + spherical_position(radius, az, elevation),
                               target)
             for az in azimuths]
    return np.stack(poses).astype(np.float32)


def transform_viewpoint(v: np.ndarray) -> np.ndarray:
    """(N, 5) [x, y, z, yaw, pitch] → (N, 7) [x, y, z, cos/sin yaw, cos/sin
    pitch] — the consistent viewpoint representation of data_util.py:145-152."""
    v = np.asarray(v)
    return np.concatenate([
        v[:, :3],
        np.cos(v[:, 3:4]), np.sin(v[:, 3:4]),
        np.cos(v[:, 4:5]), np.sin(v[:, 4:5]),
    ], axis=1)


def rotation_angle(Ra: np.ndarray, Rb: np.ndarray) -> float:
    """Geodesic angle (radians) between two rotation matrices."""
    cos = (np.trace(Ra.T @ Rb) - 1.0) / 2.0
    return float(np.arccos(np.clip(cos, -1.0, 1.0)))
