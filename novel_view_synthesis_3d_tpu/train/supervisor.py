"""Supervised training: restart a crashed or stalled child run.

`nvs3d train --supervise` wraps the actual training run in a child
process and restarts it on ANY abnormal exit — a crash (non-zero rc,
signal death) or a watchdog-declared stall (utils/watchdog.EXIT_STALL,
the soft checkpoint-and-exit or the monitor's hard exit) — with
exponential backoff, bounded by `train.max_restarts`. Each restart
resumes via the Trainer's auto-resume + PR 1's checkpoint-integrity
walk-back, so the run continues from the newest INTACT checkpoint even
when the fault tore the latest one.

The supervisor deliberately holds no JAX state: it must stay alive and
responsive while the child wedges on a dead backend. Restart provenance
is durable — every restart appends a `supervised_restart` row to the
run's events.csv (step -1 = "outside the step loop"), and the child is
told its restart generation via NVS3D_SUPERVISED_RESTARTS so the
`restarts` column lands in metrics.csv next to the loss curve
(tools/summarize_bench.py surfaces both).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from novel_view_synthesis_3d_tpu.obs import bus as obs_bus

RESTART_ENV = "NVS3D_SUPERVISED_RESTARTS"


def log_event(results_folder: str, kind: str, detail: str = "") -> None:
    """Event-log append via the obs bus (step -1 = "outside the step
    loop"), standalone — the supervisor must not construct a
    MetricsLogger (the child owns the metrics table), and obs.bus
    imports no jax (this process deliberately holds no JAX state)."""
    obs_bus.append_event(results_folder, -1, kind, detail)
    print(f"[supervisor] {kind}" + (f" ({detail})" if detail else ""),
          flush=True)


def supervise(argv: Sequence[str], *, results_folder: str,
              max_restarts: int = 3, backoff_s: float = 5.0,
              env: Optional[dict] = None,
              child_timeout_s: float = 0.0) -> int:
    """Run `argv` as a child; restart on abnormal exit. Returns the final
    exit code (0 = the child eventually completed cleanly).

    `backoff_s` is the base of the exponential restart delay
    (backoff_s · 2^(restart-1), capped at 300 s). `child_timeout_s` > 0
    additionally bounds each child's total wall clock — the supervisor's
    own last-resort hang guard for a child whose in-process watchdog is
    disabled or itself wedged; on expiry the child is killed and the
    restart path taken. SIGINT/SIGTERM to the supervisor forward to the
    child and stop the restart loop (an operator kill or a preemption of
    the supervisor host must not look like a crash to retry).
    """
    from novel_view_synthesis_3d_tpu.utils.watchdog import EXIT_STALL

    argv = list(argv)
    stop = {"requested": False}
    child: dict = {"proc": None}

    def forward(signum, frame):
        stop["requested"] = True
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except OSError:
                pass

    old_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old_handlers[sig] = signal.signal(sig, forward)
        except ValueError:  # not the main thread (tests)
            pass

    restarts = 0
    try:
        while True:
            child_env = dict(os.environ if env is None else env)
            child_env[RESTART_ENV] = str(restarts)
            proc = subprocess.Popen(argv, env=child_env)
            child["proc"] = proc
            try:
                rc = proc.wait(timeout=child_timeout_s or None)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass  # uninterruptible backend IO: abandon the child
                rc = EXIT_STALL
                log_event(results_folder, "supervised_timeout",
                          f"child exceeded {child_timeout_s:.0f}s; killed")
            child["proc"] = None
            if rc == 0:
                if restarts:
                    log_event(results_folder, "supervised_complete",
                              f"run completed after {restarts} restart(s)")
                return 0
            if stop["requested"]:
                print(f"[supervisor] stop requested; child exited rc={rc} "
                      "— not restarting", flush=True)
                return rc
            kind = "stall" if rc == EXIT_STALL else (
                f"signal {-rc}" if rc < 0 else f"crash rc={rc}")
            if restarts >= max_restarts:
                log_event(results_folder, "supervised_giveup",
                          f"{kind} and the restart budget "
                          f"(train.max_restarts={max_restarts}) is "
                          "exhausted")
                return rc
            restarts += 1
            delay = min(300.0, backoff_s * (2 ** (restarts - 1)))
            log_event(results_folder, "supervised_restart",
                      f"{kind}; restart {restarts}/{max_restarts} "
                      f"after {delay:.1f}s backoff (resume from last "
                      "intact checkpoint)")
            time.sleep(delay)
    finally:
        for sig, handler in old_handlers.items():
            signal.signal(sig, handler)


def train_child_argv(args, overrides: Sequence[str]) -> List[str]:
    """Reconstruct the `nvs3d train` child command from parsed args,
    minus --supervise (the child must not recurse)."""
    argv = [sys.executable, "-m", "novel_view_synthesis_3d_tpu", "train"]
    if getattr(args, "preset", None):
        argv += ["--preset", args.preset]
    if getattr(args, "config", None):
        argv += ["--config", args.config]
    if getattr(args, "no_grain", False):
        argv += ["--no-grain"]
    if getattr(args, "folder", None):
        argv.append(args.folder)
    argv += list(overrides)
    return argv
