"""Resolution ladder: progressive 64→128 training over one checkpoint.

ROADMAP item 5's training schedule. `train.ladder="64:N,128:M"` runs the
run as consecutive RUNGS against ONE checkpoint_dir: rung r trains at its
resolution from the previous rung's final state (the XUNet is fully
convolutional — conv/norm/emb param shapes are resolution-independent,
so every rung shares one param tree; model.attn_resolutions must select
the SAME UNet levels at every rung, which Config.validate and
`attention_levels` below enforce). The contracts:

  - rung boundaries are CANONICAL checkpoint boundaries: each rung ends
    with the trainer's forced final save at its cumulative step count,
    so a kill between rungs resumes into the next rung's fresh loader
    with bit-identical results to an uninterrupted ladder;
  - rung selection on resume derives from the restored step ALONE
    (cumulative step ranges) — no side-channel rung state to corrupt;
  - MID-rung resume is bit-identical too: the rung's loader fast-
    forwards its plan stream by the steps already trained in the rung
    (PipelinedLoader skip_batches), so the resumed run consumes exactly
    the batches the uninterrupted run would have;
  - the promotion gate probes at EVERY rung resolution
    (registry/gate.run_gate_matrix, wired in cli._run_gates).

This module also owns the VERSIONED PARAM-TREE GROWTH shim: enabling
scene-category conditioning (model.num_classes > 0) adds a zero-init
`category_emb` table under ConditioningProcessor_0 (plus its Adam-moment
and EMA shadows). `restore_with_growth` lets checkpoints saved WITHOUT
the table restore into the grown tree — it retries the restore with the
grown leaves stripped from the template, then splices the template's
fresh zero-init values back in, asserting they really are zero (the
numeric-no-op contract of the growth).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Param-tree keys that version-grow the tree (old checkpoints may lack
# them; the fresh template value is a numeric no-op by construction).
GROWN_PARAM_KEYS = ("category_emb",)


@dataclasses.dataclass(frozen=True)
class Rung:
    """One resolution rung: train at `resolution` until global step
    reaches `end_step` (cumulative over the ladder)."""

    resolution: int
    steps: int
    start_step: int
    end_step: int


def parse_ladder(spec: str) -> List[Rung]:
    """train.ladder string → cumulative rung schedule.

    Config.validate() already rejects malformed specs at startup; this
    re-raises on the same conditions for direct callers.
    """
    rungs: List[Rung] = []
    start = 0
    for entry in spec.split(","):
        parts = entry.strip().split(":")
        if len(parts) != 2:
            raise ValueError(
                f"ladder entry {entry.strip()!r} must be "
                "'resolution:steps'")
        res, steps = int(parts[0]), int(parts[1])
        if res < 8 or res & (res - 1) != 0:
            raise ValueError(
                f"ladder resolution {res} must be a power of two >= 8")
        if steps < 1:
            raise ValueError(
                f"ladder rung {entry.strip()!r} must train >= 1 step")
        if rungs and res < rungs[-1].resolution:
            raise ValueError(
                f"ladder resolutions must be non-decreasing "
                f"({rungs[-1].resolution} before {res})")
        rungs.append(Rung(resolution=res, steps=steps, start_step=start,
                          end_step=start + steps))
        start += steps
    if not rungs:
        raise ValueError("empty ladder spec")
    return rungs


def ladder_resolutions(cfg) -> List[int]:
    """Every resolution the run trains (and the gate must probe) at:
    the ladder's rung resolutions, or the flat data.img_sidelength."""
    if cfg.train.ladder:
        seen: List[int] = []
        for r in parse_ladder(cfg.train.ladder):
            if r.resolution not in seen:
                seen.append(r.resolution)
        return seen
    return [cfg.data.img_sidelength]


def attention_levels(model_cfg, resolution: int) -> Tuple[int, ...]:
    """The UNet levels whose feature maps trigger attention at this
    input resolution (level i runs at resolution >> i). The ladder
    requires this tuple to be IDENTICAL across rung resolutions —
    attn_resolutions is keyed on absolute feature-map resolution, so a
    mismatch means structurally incompatible rung param trees."""
    return tuple(lvl for lvl in range(len(model_cfg.ch_mult))
                 if (resolution >> lvl) in model_cfg.attn_resolutions)


def check_ladder_attention(cfg, rungs: List[Rung]) -> None:
    """Raise loudly when the rung resolutions place attention at
    different UNet levels (Config.validate runs the same check; this
    covers direct run_ladder callers with unvalidated configs)."""
    patterns = {r.resolution: attention_levels(cfg.model, r.resolution)
                for r in rungs}
    if len(set(patterns.values())) > 1:
        raise ValueError(
            "train.ladder places attention at different UNet levels "
            f"per rung ({ {r: list(p) for r, p in patterns.items()} }) "
            "— the rung param trees would be structurally incompatible; "
            "choose model.attn_resolutions that select the same levels "
            "at every rung resolution (e.g. [] for the ladder run)")


def rung_of_step(rungs: List[Rung], step: int) -> Rung:
    """The rung a global step trains in (end_step exclusive; a step at
    or past the ladder's total belongs to the final rung)."""
    for r in rungs:
        if step < r.end_step:
            return r
    return rungs[-1]


def rung_config(cfg, rung: Rung):
    """Derive the rung's flat Config: the rung resolution, the ladder's
    cumulative step target, and ladder cleared (a rung is a plain run)."""
    return cfg.override(**{
        "data.img_sidelength": rung.resolution,
        "train.num_steps": rung.end_step,
        "train.ladder": "",
    })


def _release_rung(trainer) -> None:
    """Release a finished rung's IO: the ladder opens one Trainer per
    rung against the SAME checkpoint_dir, so the finished rung's decode
    workers and async Orbax manager must not linger under the next
    rung's (train() already drained the final forced save)."""
    loader = getattr(trainer, "_packed_loader", None)
    if loader is not None:
        loader.stop()
    trainer.ckpt.wait()
    trainer.ckpt.close()


def run_ladder(cfg, *, use_grain: bool = True):
    """Drive the full ladder: one Trainer per remaining rung, sequential,
    resuming from the shared checkpoint_dir. Returns the last Trainer
    driven (None when every rung was already complete) so the CLI can
    propagate its stall exit code."""
    from novel_view_synthesis_3d_tpu.train.checkpoint import (
        CheckpointManager)
    from novel_view_synthesis_3d_tpu.train.trainer import Trainer

    rungs = parse_ladder(cfg.train.ladder)
    check_ladder_attention(cfg, rungs)
    if not cfg.train.resume:
        raise ValueError(
            "train.ladder requires train.resume=true — every rung after "
            "the first RESTORES the previous rung's final checkpoint "
            "(and a mid-rung rerun restores its own); resume=false would "
            "silently retrain each rung from scratch")
    mgr = CheckpointManager(cfg.train.checkpoint_dir)
    latest = mgr.latest_step() or 0
    mgr.close()
    trainer = None
    for rung in rungs:
        if latest >= rung.end_step:
            print(f"ladder: rung {rung.resolution}px "
                  f"[{rung.start_step}, {rung.end_step}) already "
                  f"complete (checkpoint at step {latest}) — skipping",
                  flush=True)
            continue
        # Mid-rung resume: fast-forward the rung's data stream by the
        # steps already trained in it, so the resumed run consumes the
        # exact batches the uninterrupted rung would have.
        skip = max(0, latest - rung.start_step)
        rcfg = rung_config(cfg, rung)
        rcfg.validate()
        print(f"ladder: rung {rung.resolution}px "
              f"[{rung.start_step}, {rung.end_step})"
              + (f", fast-forwarding {skip} batches" if skip else ""),
              flush=True)
        trainer = Trainer(config=rcfg, use_grain=use_grain,
                          skip_batches=skip)
        trainer.train()
        _release_rung(trainer)
        if trainer.stalled or getattr(trainer, "_preempted", False):
            # The rung checkpointed and bailed; the NEXT invocation
            # resumes INSIDE this rung (skip derived from the restored
            # step) — advancing `latest` here would silently skip the
            # untrained remainder.
            print(f"ladder: interrupted inside rung {rung.resolution}px "
                  f"at step {trainer.step}; rerun to resume this rung",
                  flush=True)
            return trainer
        latest = rung.end_step
        print(f"ladder: rung {rung.resolution}px complete at step "
              f"{latest} (canonical checkpoint boundary)", flush=True)
    return trainer


# ---------------------------------------------------------------------------
# Versioned param-tree growth (scene-category conditioning)
# ---------------------------------------------------------------------------
def _strip_grown(tree: Any, removed: Dict[tuple, Any],
                 path: tuple = ()) -> Any:
    """Copy of `tree` with every dict entry named in GROWN_PARAM_KEYS
    removed (recorded in `removed` by path). Recurses through the
    containers a TrainState is made of: dicts (param/moment/EMA trees),
    tuples incl. namedtuples (optax states), lists, and (flax struct)
    dataclasses."""
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            if k in GROWN_PARAM_KEYS:
                removed[path + (k,)] = v
            else:
                out[k] = _strip_grown(v, removed, path + (k,))
        return out
    if isinstance(tree, tuple):
        vals = [_strip_grown(v, removed, path + (i,))
                for i, v in enumerate(tree)]
        return (type(tree)(*vals) if hasattr(tree, "_fields")
                else tuple(vals))
    if isinstance(tree, list):
        return [_strip_grown(v, removed, path + (i,))
                for i, v in enumerate(tree)]
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        kw = {f.name: _strip_grown(getattr(tree, f.name), removed,
                                   path + (f.name,))
              for f in dataclasses.fields(tree)}
        return tree.replace(**kw) if hasattr(tree, "replace") else \
            dataclasses.replace(tree, **kw)
    return tree


def _reinsert(tree: Any, removed: Dict[tuple, Any],
              path: tuple = ()) -> Any:
    """Inverse of _strip_grown: re-add the removed dict entries (with
    their recorded values) into a structurally-stripped tree."""
    if isinstance(tree, dict):
        out = {k: _reinsert(v, removed, path + (k,))
               for k, v in tree.items()}
        for rp, val in removed.items():
            if rp[:-1] == path:
                out[rp[-1]] = val
        return out
    if isinstance(tree, tuple):
        vals = [_reinsert(v, removed, path + (i,))
                for i, v in enumerate(tree)]
        return (type(tree)(*vals) if hasattr(tree, "_fields")
                else tuple(vals))
    if isinstance(tree, list):
        return [_reinsert(v, removed, path + (i,))
                for i, v in enumerate(tree)]
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        kw = {f.name: _reinsert(getattr(tree, f.name), removed,
                                path + (f.name,))
              for f in dataclasses.fields(tree)}
        return tree.replace(**kw) if hasattr(tree, "replace") else \
            dataclasses.replace(tree, **kw)
    return tree


def restore_with_growth(ckpt, template, step: Optional[int] = None
                        ) -> Optional[Any]:
    """CheckpointManager.restore with param-tree-growth compat.

    Try the full template first (same-version checkpoints restore
    unchanged). If that fails AND the template contains grown keys,
    retry with the grown leaves stripped — an old (pre-growth)
    checkpoint restores into the stripped structure — then splice the
    template's fresh values back in, ASSERTING they are all-zero (the
    zero-init contract is what makes the splice a numeric no-op; a
    non-zero template value would mean the growth semantics changed and
    this shim must not silently guess).
    """
    import jax

    try:
        return ckpt.restore(template, step=step)
    except Exception:
        removed: Dict[tuple, Any] = {}
        stripped = _strip_grown(template, removed)
        if not removed:
            raise  # not a growth mismatch — surface the original error
        restored = ckpt.restore(stripped, step=step)
        if restored is None:
            return None
        for path, val in removed.items():
            arr = np.asarray(jax.device_get(val))
            if arr.size and np.any(arr):
                raise RuntimeError(
                    "param-tree growth compat: template value at "
                    f"{'/'.join(map(str, path))} is not zero-init — "
                    "refusing to splice a non-neutral value into a "
                    "restored checkpoint")
        print("checkpoint predates param-tree growth: spliced "
              f"{len(removed)} zero-init leaf/leaves "
              f"({', '.join(sorted({str(p[-1]) for p in removed}))}) "
              "into the restored state", flush=True)
        return _reinsert(restored, removed)
