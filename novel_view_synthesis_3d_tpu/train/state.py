"""Train state + optimizer factory.

Replaces the reference's per-device `flax.training.TrainState` under pmap
(train.py:36-47). One logical state, replicated over the mesh by sharding
annotations; `step` and the base PRNG key live IN the state so per-step keys
are derived on device (`fold_in`) — the reference instead baked a fixed
dropout key and a host-numpy CFG mask into the trace (train.py:64-66).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from novel_view_synthesis_3d_tpu.config import TrainConfig
from novel_view_synthesis_3d_tpu.train.guard import (
    GuardState,
    init_guard_state,
)


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray  # () int32
    params: Any
    opt_state: Any
    rng: jax.Array  # base key; per-step keys are fold_in(rng, step)
    ema_params: Optional[Any] = None
    # Anomaly-guard bookkeeping (train/guard.py). Lives in the state so it
    # (a) threads through the steps_per_dispatch fused scan as part of the
    # carry and (b) survives checkpoint/restore. None when
    # train.anomaly_guard is off.
    guard: Optional[GuardState] = None


def make_lr_schedule(cfg: TrainConfig):
    """LR schedule per config — probeable directly (scalar or step→lr)."""
    if cfg.lr_schedule == "constant":
        return (optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
                if cfg.warmup_steps > 0 else cfg.lr)
    if cfg.lr_schedule == "cosine":
        if cfg.warmup_steps >= cfg.num_steps:
            raise ValueError(
                f"lr_schedule='cosine' needs num_steps ({cfg.num_steps}) > "
                f"warmup_steps ({cfg.warmup_steps})")
        if cfg.warmup_steps > 0:
            return optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=cfg.lr,
                warmup_steps=cfg.warmup_steps,
                decay_steps=cfg.num_steps,
                end_value=cfg.lr * cfg.lr_final_fraction)
        return optax.cosine_decay_schedule(
            init_value=cfg.lr, decay_steps=max(1, cfg.num_steps),
            alpha=cfg.lr_final_fraction)
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")


def make_optimizer(cfg: TrainConfig, return_schedule: bool = False):
    """Optimizer chain per config; with return_schedule=True also returns
    the EXACT lr schedule handed to optax, so callers logging lr can never
    drift from what the optimizer applies.

    'adam' is the reference optimizer (train.py:46, optax.adam(1e-4));
    'adafactor' is the memory-lean alternative for HBM-bound single-chip
    configs: factored second moments + no first moment cut optimizer state
    from 2x param bytes (Adam f32 mu+nu; 5.3G for the 708M-param paper256
    model) to ~sqrt-sized row/col stats, the difference between paper256
    fitting a 16G v5e with margin and scraping the ceiling.
    """
    schedule = make_lr_schedule(cfg)
    parts = []
    if cfg.grad_clip > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip))
    if cfg.optimizer == "adam":
        parts.append(optax.adam(
            schedule, mu_dtype=jnp.dtype(cfg.adam_mu_dtype)))
    elif cfg.optimizer == "adafactor":
        # min_dim_size_to_factor=128: small tensors (biases, norm scales)
        # keep an unfactored (exact) second moment — factoring them saves
        # nothing and costs accuracy. multiply_by_parameter_scale=False +
        # momentum=None keeps the update closest to Adam's geometry so lr
        # presets transfer; momentum would reintroduce the 1x-param-bytes
        # buffer this optimizer exists to avoid.
        parts.append(optax.adafactor(
            schedule, min_dim_size_to_factor=128,
            multiply_by_parameter_scale=False, momentum=None))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    tx = optax.chain(*parts)
    return (tx, schedule) if return_schedule else tx


def create_train_state(cfg: TrainConfig, model, sample_batch: dict,
                       seed: Optional[int] = None,
                       on_cpu: Optional[bool] = None) -> TrainState:
    """Initialize params ONCE (same everywhere — the reference initialized
    each device differently, train.py:122-123) and build the state.

    `on_cpu` (default: automatically True off the CPU backend) runs the init
    forward on the host: flax init dispatches thousands of small eager ops,
    which over a remote-accelerator link takes minutes for large models,
    while the threefry PRNG makes the resulting params bitwise identical on
    every backend. The init pass swaps in a dense-attention model (Pallas
    kernels can't lower on CPU, shard_map can't use remote device meshes) —
    neither feature has parameters, so the tree is unchanged.
    """
    seed = cfg.seed if seed is None else seed
    root = jax.random.PRNGKey(seed)
    k_params, k_dropout, k_train = jax.random.split(root, 3)
    if on_cpu is None:
        on_cpu = jax.default_backend() != "cpu"

    # Params are batch-size independent: init on the smallest batch slice
    # so the traced init forward costs ~1/B of the real step (at paper256
    # scale the full batch-8 256px forward takes tens of minutes on the
    # host). A sequence-parallel model initializing on its real mesh needs
    # the batch divisible by the 'data' axis, so keep that many rows.
    min_b = 1
    model_mesh = getattr(model, "mesh", None)
    if not on_cpu and model_mesh is not None:
        min_b = dict(model_mesh.shape).get("data", 1)
    full_b = sample_batch["z"].shape[0]
    min_b = min(min_b, full_b)
    sample_batch = jax.tree.map(lambda a: a[:min_b], sample_batch)
    B = min_b

    init_model = model
    if on_cpu and hasattr(model, "config"):
        import dataclasses

        init_model = type(model)(dataclasses.replace(
            model.config, use_flash_attention=False,
            sequence_parallel=False))

    def run_init():
        # jit makes the init forward an XLA program instead of thousands of
        # eager dispatches — the dominant cost of large-model host init.
        @jax.jit
        def _init(k_p, k_d, batch):
            return init_model.init(
                {"params": k_p, "dropout": k_d}, batch,
                cond_mask=jnp.ones((B,)), train=True)

        return _init(k_params, k_dropout, sample_batch)

    tx = make_optimizer(cfg)

    def build_state():
        params = run_init()["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            # Optimizer + EMA state are ~3x param bytes — they must follow
            # the same host-side path as params or they'd materialize on
            # accelerator device 0 before any sharded device_put.
            opt_state=tx.init(params),
            rng=k_train,
            # Distinct buffers from params: the donated train step must not
            # see the same buffer twice (f(donate(a), donate(a)) invalid).
            # With ema_host the EMA buffer lives in host RAM instead
            # (Trainer._host_ema) — no device copy at all.
            ema_params=(jax.tree.map(jnp.copy, params)
                        if cfg.ema_decay > 0 and not cfg.ema_host else None),
            guard=init_guard_state() if cfg.anomaly_guard else None,
        )

    if on_cpu:
        with jax.default_device(jax.devices("cpu")[0]):
            return build_state()
    return build_state()
