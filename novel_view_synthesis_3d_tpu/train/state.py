"""Train state + optimizer factory.

Replaces the reference's per-device `flax.training.TrainState` under pmap
(train.py:36-47). One logical state, replicated over the mesh by sharding
annotations; `step` and the base PRNG key live IN the state so per-step keys
are derived on device (`fold_in`) — the reference instead baked a fixed
dropout key and a host-numpy CFG mask into the trace (train.py:64-66).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from novel_view_synthesis_3d_tpu.config import TrainConfig
from novel_view_synthesis_3d_tpu.train.guard import (
    GuardState,
    init_guard_state,
)


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray  # () int32
    params: Any
    opt_state: Any
    rng: jax.Array  # base key; per-step keys are fold_in(rng, step)
    ema_params: Optional[Any] = None
    # Anomaly-guard bookkeeping (train/guard.py). Lives in the state so it
    # (a) threads through the steps_per_dispatch fused scan as part of the
    # carry and (b) survives checkpoint/restore. None when
    # train.anomaly_guard is off.
    guard: Optional[GuardState] = None


def make_lr_schedule(cfg: TrainConfig):
    """LR schedule per config — probeable directly (scalar or step→lr)."""
    if cfg.lr_schedule == "constant":
        return (optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
                if cfg.warmup_steps > 0 else cfg.lr)
    if cfg.lr_schedule == "cosine":
        if cfg.warmup_steps >= cfg.num_steps:
            raise ValueError(
                f"lr_schedule='cosine' needs num_steps ({cfg.num_steps}) > "
                f"warmup_steps ({cfg.warmup_steps})")
        if cfg.warmup_steps > 0:
            return optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=cfg.lr,
                warmup_steps=cfg.warmup_steps,
                decay_steps=cfg.num_steps,
                end_value=cfg.lr * cfg.lr_final_fraction)
        return optax.cosine_decay_schedule(
            init_value=cfg.lr, decay_steps=max(1, cfg.num_steps),
            alpha=cfg.lr_final_fraction)
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")


def make_optimizer(cfg: TrainConfig, return_schedule: bool = False,
                   shard_local: bool = False):
    """Optimizer chain per config; with return_schedule=True also returns
    the EXACT lr schedule handed to optax, so callers logging lr can never
    drift from what the optimizer applies.

    'adam' is the reference optimizer (train.py:46, optax.adam(1e-4));
    'adafactor' is the memory-lean alternative for HBM-bound single-chip
    configs: factored second moments + no first moment cut optimizer state
    from 2x param bytes (Adam f32 mu+nu; 5.3G for the 708M-param paper256
    model) to ~sqrt-sized row/col stats, the difference between paper256
    fitting a 16G v5e with margin and scraping the ceiling.

    `shard_local=True` (the ZeRO update path, parallel/zero.py) builds the
    chain that runs INSIDE shard_map on each replica's 1/N shard: the
    global-norm clip is replaced by optax.identity() — a shard-local norm
    would be wrong, so the caller clips the full gradient before entering
    the sharded region. identity's state is EmptyState(), exactly like
    clip_by_global_norm's, so the opt_state TREEDEF is identical across
    both variants and checkpoints move freely between update_sharding
    settings.
    """
    schedule = make_lr_schedule(cfg)
    parts = []
    if cfg.grad_clip > 0:
        parts.append(optax.identity() if shard_local
                     else optax.clip_by_global_norm(cfg.grad_clip))
    if cfg.optimizer == "adam":
        parts.append(optax.adam(
            schedule, mu_dtype=jnp.dtype(cfg.adam_mu_dtype)))
    elif cfg.optimizer == "adafactor":
        # min_dim_size_to_factor=128: small tensors (biases, norm scales)
        # keep an unfactored (exact) second moment — factoring them saves
        # nothing and costs accuracy. multiply_by_parameter_scale=False +
        # momentum=None keeps the update closest to Adam's geometry so lr
        # presets transfer; momentum would reintroduce the 1x-param-bytes
        # buffer this optimizer exists to avoid.
        parts.append(optax.adafactor(
            schedule, min_dim_size_to_factor=128,
            multiply_by_parameter_scale=False, momentum=None))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    tx = optax.chain(*parts)
    return (tx, schedule) if return_schedule else tx


def create_train_state(cfg: TrainConfig, model, sample_batch: dict,
                       seed: Optional[int] = None,
                       on_cpu: Optional[bool] = None) -> TrainState:
    """Initialize params ONCE (same everywhere — the reference initialized
    each device differently, train.py:122-123) and build the state.

    `on_cpu` (default: automatically True off the CPU backend) runs the init
    forward on the host: flax init dispatches thousands of small eager ops,
    which over a remote-accelerator link takes minutes for large models,
    while the threefry PRNG makes the resulting params bitwise identical on
    every backend. The init pass swaps in a dense-attention model (Pallas
    kernels can't lower on CPU, shard_map can't use remote device meshes) —
    neither feature has parameters, so the tree is unchanged.
    """
    seed = cfg.seed if seed is None else seed
    root = jax.random.PRNGKey(seed)
    k_params, k_dropout, k_train = jax.random.split(root, 3)
    if on_cpu is None:
        on_cpu = jax.default_backend() != "cpu"

    # Params are batch-size independent: init on the smallest batch slice
    # so the traced init forward costs ~1/B of the real step (at paper256
    # scale the full batch-8 256px forward takes tens of minutes on the
    # host). A sequence-parallel model initializing on its real mesh needs
    # the batch divisible by the 'data' axis, so keep that many rows.
    min_b = 1
    model_mesh = getattr(model, "mesh", None)
    if not on_cpu and model_mesh is not None:
        min_b = dict(model_mesh.shape).get("data", 1)
    full_b = sample_batch["z"].shape[0]
    min_b = min(min_b, full_b)
    sample_batch = jax.tree.map(lambda a: a[:min_b], sample_batch)
    B = min_b

    init_model = model
    if on_cpu and hasattr(model, "config"):
        import dataclasses

        init_model = type(model)(dataclasses.replace(
            model.config, use_flash_attention=False,
            sequence_parallel=False))

    def run_init():
        # jit makes the init forward an XLA program instead of thousands of
        # eager dispatches — the dominant cost of large-model host init.
        @jax.jit
        def _init(k_p, k_d, batch):
            return init_model.init(
                {"params": k_p, "dropout": k_d}, batch,
                cond_mask=jnp.ones((B,)), train=True)

        return _init(k_params, k_dropout, sample_batch)

    tx = make_optimizer(cfg)

    def build_state():
        params = run_init()["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            # Optimizer + EMA state are ~3x param bytes — they must follow
            # the same host-side path as params or they'd materialize on
            # accelerator device 0 before any sharded device_put.
            opt_state=tx.init(params),
            rng=k_train,
            # Distinct buffers from params: the donated train step must not
            # see the same buffer twice (f(donate(a), donate(a)) invalid).
            # With ema_host the EMA buffer lives in host RAM instead
            # (Trainer._host_ema) — no device copy at all.
            ema_params=(jax.tree.map(jnp.copy, params)
                        if cfg.ema_decay > 0 and not cfg.ema_host else None),
            guard=init_guard_state() if cfg.anomaly_guard else None,
        )

    if on_cpu:
        with jax.default_device(jax.devices("cpu")[0]):
            return build_state()
    return build_state()


# ---------------------------------------------------------------------------
# ZeRO (train.update_sharding='zero') state layout
# ---------------------------------------------------------------------------
# Between steps the TrainState carries opt_state/ema_params in the packed
# (N, c) row-sharded layout of parallel/zero.py; params stay replicated.
# Checkpoints and the registry/probe always see the canonical UNPACKED
# layout — pack/unpack live here so every boundary (trainer init, save,
# restore, publish) converts the same way.

def _zero_plans(cfg: TrainConfig, params: Any, has_ema: bool, n: int):
    from novel_view_synthesis_3d_tpu.parallel import zero as zero_lib

    tx = make_optimizer(cfg, shard_local=True)
    return zero_lib.state_plans(tx, params, has_ema, n)


def pack_train_state(cfg: TrainConfig, mesh, state: TrainState):
    """Canonical state → (packed state, matching per-leaf sharding tree).

    The sharding tree mirrors the PACKED state leaf-for-leaf (packed
    opt/EMA rows over 'data', everything else replicated) so it can feed
    both jax.device_put and the train step's in/out_shardings."""
    import jax.sharding as js

    from novel_view_synthesis_3d_tpu.parallel import zero as zero_lib

    n = mesh.shape["data"]
    plans = _zero_plans(cfg, state.params, state.ema_params is not None, n)
    packed = state.replace(
        opt_state=zero_lib.pack(state.opt_state, plans["opt_state"]),
        ema_params=(zero_lib.pack(state.ema_params, plans["ema_params"])
                    if state.ema_params is not None else None))
    repl = js.NamedSharding(mesh, js.PartitionSpec())
    shardings = packed.replace(
        step=repl,
        params=jax.tree.map(lambda _: repl, state.params),
        opt_state=zero_lib.packed_shardings(mesh, plans["opt_state"]),
        rng=repl,
        ema_params=(zero_lib.packed_shardings(mesh, plans["ema_params"])
                    if state.ema_params is not None else None),
        guard=(jax.tree.map(lambda _: repl, state.guard)
               if state.guard is not None else None))
    return packed, shardings


def unpack_train_state(cfg: TrainConfig, mesh, packed: TrainState
                       ) -> TrainState:
    """Packed state → canonical layout (leaf shapes re-derived from the
    params avals; works on device or host-numpy leaves alike)."""
    from novel_view_synthesis_3d_tpu.parallel import zero as zero_lib

    n = mesh.shape["data"]
    plans = _zero_plans(cfg, packed.params, packed.ema_params is not None, n)
    return packed.replace(
        opt_state=zero_lib.unpack(packed.opt_state, plans["opt_state"]),
        ema_params=(zero_lib.unpack(packed.ema_params, plans["ema_params"])
                    if packed.ema_params is not None else None))


def unpack_ema(cfg: TrainConfig, mesh, params: Any, ema_packed: Any):
    """Gather a ZeRO-packed EMA tree back to canonical leaves.

    The registry publisher and the sampling probes call this ONCE per
    publish/probe — the shard gather stays off the train-step hot loop.
    Works on device or host-numpy leaves alike (parallel/zero.py unpack
    is pure reshape/slice)."""
    from novel_view_synthesis_3d_tpu.parallel import zero as zero_lib

    n = mesh.shape["data"]
    plans = _zero_plans(cfg, params, True, n)
    return zero_lib.unpack(ema_packed, plans["ema_params"])
