from novel_view_synthesis_3d_tpu.train.checkpoint import CheckpointManager  # noqa: F401
from novel_view_synthesis_3d_tpu.train.state import (  # noqa: F401
    TrainState,
    create_train_state,
    make_optimizer,
)
from novel_view_synthesis_3d_tpu.train.step import make_train_step  # noqa: F401
from novel_view_synthesis_3d_tpu.train.trainer import Trainer  # noqa: F401
