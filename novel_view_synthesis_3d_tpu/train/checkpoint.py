"""Orbax checkpointing with a tested save → restore → resume round-trip.

The reference's checkpointing is save-only and broken in three ways
(SURVEY.md §3.5): it saves pmap-replicated params (leading device axis
baked into the file), restores with a mismatched prefix ('model0' vs
'model<step>'), and has no training resume at all (train.py:159-167,
sampling.py:104-114). Here: single logical (unreplicated) TrainState, async
Orbax saves, restore-latest, and auto-resume in the Trainer.

Fault tolerance (docs/DESIGN.md "Fault tolerance"): a torn write — host
preempted mid-save — must not brick auto-resume. `restore` VERIFIES each
candidate (Orbax restore succeeds AND every float leaf is finite) and walks
back to the newest intact step; `save` retries with backoff before giving
up, and a periodic-save failure degrades to a loud warning instead of
killing a multi-day run (the final/preemption save still raises).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

import os

import jax
import numpy as np
import orbax.checkpoint as ocp

from novel_view_synthesis_3d_tpu.train.state import TrainState


def nonfinite_leaf_count(tree: Any) -> int:
    """Number of float leaves containing any non-finite value.

    Host numpy leaves (host-EMA checkpoints) are checked in place; device
    leaves via one batched fetch of per-leaf all-finite flags (cheap next
    to the restore IO itself)."""
    device_flags = []
    bad = 0
    for leaf in jax.tree.leaves(tree):
        dtype = getattr(leaf, "dtype", None)
        if dtype is None or not np.issubdtype(np.dtype(dtype), np.floating):
            continue
        if isinstance(leaf, np.ndarray):
            bad += int(not np.isfinite(leaf).all())
        else:
            import jax.numpy as jnp

            device_flags.append(jnp.all(jnp.isfinite(leaf)))
    if device_flags:
        bad += sum(1 for ok in jax.device_get(device_flags) if not bool(ok))
    return bad


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_retries: int = 2, save_backoff_s: float = 0.5):
        self.directory = os.path.abspath(directory)
        self.save_retries = save_retries
        self.save_backoff_s = save_backoff_s
        self.save_failures = 0  # cumulative failed save ATTEMPTS
        # Provenance of the last restore() — {'step', 'rejected': [(step,
        # reason), ...]} — so the Trainer can put a fallback line in the
        # run log (silent recovery is indistinguishable from silent data
        # loss).
        self.last_restore: Optional[dict] = None
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: TrainState, force: bool = False) -> bool:
        if step in self._mngr.all_steps():
            if not force:
                return False  # already checkpointed (final step == save_every)
            # Orbax refuses to overwrite an existing step even with
            # force=True (force only bypasses the save-interval policy), so a
            # forced save of a stale step (e.g. left by a previous run with
            # resume=False) must delete it first — after draining any
            # in-flight async save of that same step.
            self._mngr.wait_until_finished()
            self._mngr.delete(step)
        last_exc: Optional[Exception] = None
        for attempt in range(self.save_retries + 1):
            try:
                saved = self._mngr.save(
                    step, args=ocp.args.StandardSave(state), force=force)
                if jax.default_backend() == "cpu":
                    # Donation race (found by the fault-injection suite):
                    # the train step donates state buffers, and on the CPU
                    # backend Orbax's background serialization reads the
                    # SAME host memory zero-copy — a fast next dispatch
                    # overwrites it mid-write and tears the checkpoint.
                    # Draining here makes CPU saves effectively synchronous
                    # (host-memory writes, cheap at CPU-run scales); on
                    # accelerators the device→host copy completes before
                    # save() returns, so async stays async.
                    self._mngr.wait_until_finished()
                return saved
            except Exception as exc:  # filesystem flake, async-save error
                self.save_failures += 1
                last_exc = exc
                try:
                    # A failed async save may hold a half-registered step;
                    # drain before retrying so the retry starts clean.
                    self._mngr.wait_until_finished()
                except Exception:
                    pass
                if attempt < self.save_retries:
                    time.sleep(self.save_backoff_s * (2 ** attempt))
        if force:
            # Final / preemption save: losing it silently loses the run.
            raise RuntimeError(
                f"checkpoint save of step {step} failed after "
                f"{self.save_retries + 1} attempts") from last_exc
        print(f"warning: checkpoint save of step {step} failed after "
              f"{self.save_retries + 1} attempts ({last_exc!r}) — training "
              "continues; the next save interval will retry", flush=True)
        return False

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        return list(self._mngr.all_steps())

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> Optional[TrainState]:
        """Restore into the structure of `template` (e.g. a freshly created
        state); returns None when no checkpoint exists.

        With `step=None` (auto-resume), candidates are tried newest-first
        and each is VERIFIED — an Orbax error (torn write, missing files)
        or any non-finite float leaf rejects the step and falls back to the
        next older one. Every rejection is recorded in `last_restore` and
        printed. If steps exist but none verifies, raise (a silent fresh
        start would quietly discard the run's progress). An explicit `step`
        is still verified but never falls back — the caller asked for that
        exact step."""
        explicit = step is not None
        candidates = ([step] if explicit
                      else sorted(self._mngr.all_steps(), reverse=True))
        if not candidates:
            return None
        rejected: List[Tuple[int, str]] = []
        for s in candidates:
            try:
                state = self._mngr.restore(
                    s, args=ocp.args.StandardRestore(template))
            except Exception as exc:
                reason = f"{type(exc).__name__}: {exc}"
                rejected.append((s, reason))
                print(f"warning: checkpoint step {s} failed to restore "
                      f"({reason.splitlines()[0][:200]})", flush=True)
                if explicit:
                    raise
                continue
            bad = nonfinite_leaf_count(state)
            if bad:
                reason = f"{bad} non-finite leaves"
                rejected.append((s, reason))
                print(f"warning: checkpoint step {s} restored but holds "
                      f"{bad} non-finite leaves — rejected", flush=True)
                if explicit:
                    raise RuntimeError(
                        f"checkpoint step {s} holds {bad} non-finite "
                        "leaves")
                continue
            self.last_restore = {"step": s, "rejected": rejected}
            if rejected:
                print(f"checkpoint fallback: step(s) "
                      f"{[r[0] for r in rejected]} corrupt; restored intact "
                      f"step {s}", flush=True)
            return state
        raise RuntimeError(
            "no intact checkpoint: all steps "
            f"{[r[0] for r in rejected]} under {self.directory!r} failed "
            "verification "
            f"({'; '.join(f'{s}: {r.splitlines()[0][:120]}' for s, r in rejected)})")

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
