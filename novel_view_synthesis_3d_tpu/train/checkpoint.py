"""Orbax checkpointing with a tested save → restore → resume round-trip.

The reference's checkpointing is save-only and broken in three ways
(SURVEY.md §3.5): it saves pmap-replicated params (leading device axis
baked into the file), restores with a mismatched prefix ('model0' vs
'model<step>'), and has no training resume at all (train.py:159-167,
sampling.py:104-114). Here: single logical (unreplicated) TrainState, async
Orbax saves, restore-latest, and auto-resume in the Trainer.
"""

from __future__ import annotations

import os
from typing import Optional

import orbax.checkpoint as ocp

from novel_view_synthesis_3d_tpu.train.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: TrainState, force: bool = False) -> bool:
        if step in self._mngr.all_steps():
            if not force:
                return False  # already checkpointed (final step == save_every)
            # Orbax refuses to overwrite an existing step even with
            # force=True (force only bypasses the save-interval policy), so a
            # forced save of a stale step (e.g. left by a previous run with
            # resume=False) must delete it first — after draining any
            # in-flight async save of that same step.
            self._mngr.wait_until_finished()
            self._mngr.delete(step)
        return self._mngr.save(step, args=ocp.args.StandardSave(state),
                               force=force)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> Optional[TrainState]:
        """Restore into the structure of `template` (e.g. a freshly created
        state); returns None when no checkpoint exists."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return self._mngr.restore(step, args=ocp.args.StandardRestore(template))

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
