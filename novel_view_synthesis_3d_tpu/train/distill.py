"""Progressive distillation: teacher→student step-halving rounds.

Salimans & Ho, "Progressive Distillation for Fast Sampling of Diffusion
Models" (arXiv 2202.00512): a student initialized from the teacher learns
to match TWO deterministic (η=0 DDIM) teacher steps with ONE of its own,
halving the sampling-step count per round — 256 → 128 → … → 4 — so the
serving cost of the 3DiM reverse process drops by the same factor. The
dominant serving cost in this repo is exactly that loop (ROADMAP item 1);
the step-level scheduler (sample/service.py) makes the resulting 4-step
requests first-class traffic.

Discrete construction (the tables here are the repo's respaced DDPM
tables, diffusion/schedules.py):

  - the TEACHER samples on a 2S-step respaced ladder with ᾱ_t at indices
    t = 0 … 2S−1;
  - the STUDENT's S-step ladder is the teacher's odd indices:
    ᾱ^s_k = ᾱ_t[2k+1] (`halved_schedule`), so student step k spans the
    teacher pair (2k+1 → 2k → 2k−1) EXACTLY — same noise levels, same
    logsnr conditioning (timestep_map re-indexes into the original T);
  - the distill target inverts the student's one DDIM step analytically:
    with z'' = two teacher steps from z_t, and (α, σ) = (√ᾱ, √(1−ᾱ)),
        x̃ = (z'' − (σ''/σ_t) z_t) / (α'' − (σ''/σ_t) α_t)
    (the paper's Algorithm 2 target; at k = 0, σ'' = 0 and x̃ = z'');
  - loss = truncated-SNR-weighted x₀-space MSE:
    w(t) = clip(SNR_t, 1, distill.snr_clip).

The registry (PR 5) is the teacher/student store: `run_distill` reads
nothing from disk itself — the CLI (`nvs3d distill`) resolves the teacher
from a registry channel, each round's student is published as a version,
and promotion runs the existing fixed-seed PSNR gate (registry/gate.py).
Conditioning is dropped per-sample with train.cond_drop_prob — teacher
and student see the SAME mask, so the student's unconditional branch is
distilled too and CFG keeps working at serving time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from novel_view_synthesis_3d_tpu.config import Config
from novel_view_synthesis_3d_tpu.diffusion.schedules import (
    DiffusionSchedule,
    _tables_from_betas,
    sampling_schedule,
)


@dataclasses.dataclass(frozen=True)
class RoundResult:
    """One halving round's outcome (the JSON line `nvs3d distill` prints)."""

    round_index: int
    teacher_steps: int
    student_steps: int
    updates: int
    loss_first: float
    loss_last: float
    seconds: float
    version: str = ""  # registry version id when published

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def halved_schedule(teacher: DiffusionSchedule) -> DiffusionSchedule:
    """Student schedule with half the teacher's steps.

    Student step k carries the teacher's ᾱ at index 2k+1, so one student
    DDIM step covers exactly the teacher's (2k+1 → 2k → 2k−1) pair:
    identical noise levels at both endpoints, which is what makes the
    distillation target exact rather than approximate. timestep_map (and
    any exact logsnr table) re-index so the model is conditioned on the
    same original-time logsnr it trained under.
    """
    n = teacher.num_timesteps
    if n < 2 or n % 2 != 0:
        raise ValueError(
            f"halved_schedule needs an even teacher ladder, got {n} steps "
            "(respacing can dedup to an odd length at tiny "
            "diffusion.timesteps — pick start_steps so the respaced "
            "ladder stays even)")
    acp_t = np.asarray(teacher.alphas_cumprod, np.float64)
    acp_s = acp_t[1::2]
    prev = np.concatenate([[1.0], acp_s[:-1]])
    # No 0.9999 ceiling here: a student step composes TWO teacher steps,
    # so its β legitimately sits closer to 1 than any single-step
    # schedule's (clipping would silently raise the noisiest student
    # step's ᾱ and break the level-matching the target math relies on).
    betas = np.clip(1.0 - acp_s / prev, 0.0, 1.0 - 1e-12)
    tables = {k: jnp.asarray(v, dtype=jnp.float32)
              for k, v in _tables_from_betas(betas).items()}
    return DiffusionSchedule(
        **tables,
        logsnr_min=teacher.logsnr_min,
        logsnr_max=teacher.logsnr_max,
        timestep_map=jnp.asarray(np.asarray(teacher.timestep_map)[1::2],
                                 jnp.int32),
        num_original_timesteps=teacher.num_original_timesteps,
        logsnr_table=teacher.logsnr_table,
    )


def distill_target(student: DiffusionSchedule, z_t, t_s, z_pp):
    """Invert the student's single DDIM step: the x̃ that makes one η=0
    student step from (z_t, t_s) land exactly on the teacher's two-step
    result z''. Shapes: z_t/z_pp (B, H, W, 3), t_s (B,) int."""
    def ex(table):
        v = jnp.take(table, t_s, axis=0)
        return v.reshape(v.shape + (1,) * (z_t.ndim - v.ndim))

    alpha_t = ex(student.sqrt_alphas_cumprod)
    sigma_t = ex(student.sqrt_one_minus_alphas_cumprod)
    acp_prev = ex(student.alphas_cumprod_prev)
    alpha_p = jnp.sqrt(acp_prev)
    sigma_p = jnp.sqrt(jnp.maximum(1.0 - acp_prev, 0.0))
    ratio = sigma_p / jnp.maximum(sigma_t, 1e-20)
    denom = alpha_p - ratio * alpha_t
    return (z_pp - ratio * z_t) / jnp.maximum(denom, 1e-20)


def make_distill_step(config: Config, model,
                      teacher_sched: DiffusionSchedule,
                      student_sched: DiffusionSchedule,
                      tx: optax.GradientTransformation) -> Callable:
    """Jitted distillation update bound to one (teacher, student) ladder
    pair: step(params, opt_state, teacher_params, batch, rng) ->
    (params, opt_state, metrics)."""
    dcfg = config.diffusion
    objective = dcfg.objective
    if objective not in ("eps", "x0", "v"):
        raise ValueError(f"unknown objective {objective!r}")
    snr_clip = config.distill.snr_clip
    drop = config.train.cond_drop_prob
    clip_denoised = dcfg.clip_denoised
    S = student_sched.num_timesteps

    def x0_from(schedule, z, t, out):
        if objective == "eps":
            return schedule.predict_start_from_noise(z, t, out)
        if objective == "x0":
            return out
        return schedule.predict_start_from_v(z, t, out)

    def teacher_ddim(teacher_params, cond, mask, z, t):
        batch = dict(cond, z=z, logsnr=teacher_sched.logsnr(t))
        out = model.apply({"params": teacher_params}, batch,
                          cond_mask=mask, train=False)
        x0 = x0_from(teacher_sched, z, t, out)
        if clip_denoised:
            x0 = jnp.clip(x0, -1.0, 1.0)
        return teacher_sched.ddim_step(x0, z, t, 0.0, 0.0)

    def loss_fn(params, teacher_params, batch, rng):
        x0 = batch["target"]
        B = x0.shape[0]
        k_t, k_noise, k_mask, k_drop = jax.random.split(rng, 4)
        t_s = jax.random.randint(k_t, (B,), 0, S)
        noise = jax.random.normal(k_noise, x0.shape, dtype=x0.dtype)
        z_t = student_sched.q_sample(x0, t_s, noise)
        cond = {k: batch[k] for k in ("x", "R1", "t1", "R2", "t2", "K")}
        # Teacher and student share one conditioning mask: the student's
        # unconditional branch is distilled alongside the conditional
        # one, so CFG still works on the few-step model.
        mask = (jax.random.uniform(k_mask, (B,)) >= drop
                ).astype(jnp.float32)
        # Two deterministic teacher steps: 2t+1 → 2t → 2t−1.
        t_hi = 2 * t_s + 1
        z_mid = teacher_ddim(teacher_params, cond, mask, z_t, t_hi)
        z_pp = teacher_ddim(teacher_params, cond, mask, z_mid, 2 * t_s)
        x_target = jax.lax.stop_gradient(
            distill_target(student_sched, z_t, t_s, z_pp))
        # Student's one-step x̂₀ at the SAME noise level.
        sbatch = dict(cond, z=z_t, logsnr=student_sched.logsnr(t_s))
        out = model.apply({"params": params}, sbatch, cond_mask=mask,
                          train=True, rngs={"dropout": k_drop})
        x0_pred = x0_from(student_sched, z_t, t_s, out)
        acp = jnp.take(student_sched.alphas_cumprod, t_s, axis=0)
        snr = acp / jnp.maximum(1.0 - acp, 1e-20)
        weight = jnp.clip(snr, 1.0, snr_clip)
        per_sample = jnp.mean(
            jnp.square(x_target - x0_pred).reshape(B, -1), axis=-1)
        return jnp.mean(weight * per_sample)

    @jax.jit
    def step(params, opt_state, teacher_params, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, teacher_params, batch, rng)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {
            "loss": loss, "grad_norm": optax.global_norm(grads)}

    return step


def synthetic_batches(batch_size: int, sidelength: int,
                      seed: int = 0) -> Iterator[dict]:
    """Endless synthetic SRN-style batches (the no-dataset fallback —
    still a valid teacher→student comparator: both see the same pairs)."""
    from novel_view_synthesis_3d_tpu.data.synthetic import make_example_batch

    i = 0
    while True:
        yield make_example_batch(batch_size=batch_size,
                                 sidelength=sidelength, seed=seed + i)
        i += 1


def run_distill(config: Config, model, teacher_params, *,
                data_iter: Optional[Iterator[dict]] = None,
                store=None, publish_channel: str = "distill",
                base_step: int = 0,
                event_cb: Optional[Callable] = None,
                log: Callable[[str], None] = print) -> List[RoundResult]:
    """Teacher→student halving rounds per config.distill.

    Returns one RoundResult per round; the final round's student is the
    few-step model. With `store` (a registry.RegistryStore) each round's
    student is PUBLISHED as a version on `publish_channel` — promotion
    through the PSNR gate stays an explicit operator step
    (`nvs3d registry promote` / `nvs3d distill --promote-channel`).
    """
    dl = config.distill
    if dl.start_steps > config.diffusion.timesteps:
        raise ValueError(
            f"distill.start_steps={dl.start_steps} exceeds "
            f"diffusion.timesteps={config.diffusion.timesteps}")
    if data_iter is None:
        data_iter = synthetic_batches(dl.batch_size,
                                      config.data.img_sidelength, dl.seed)
    tx = optax.adam(dl.lr)
    rng = jax.random.PRNGKey(dl.seed)
    params = teacher_params
    results: List[RoundResult] = []
    cur = dl.start_steps
    r = 0
    while cur > dl.target_steps:
        t_round = time.perf_counter()
        teacher_sched = sampling_schedule(config.diffusion, cur)
        student_sched = halved_schedule(teacher_sched)
        student_steps = student_sched.num_timesteps
        # Student initialized FROM the teacher (the paper's warm start).
        teacher = params
        student = jax.tree.map(jnp.asarray, teacher)
        opt_state = tx.init(student)
        step = make_distill_step(config, model, teacher_sched,
                                 student_sched, tx)
        loss_first = loss_last = float("nan")
        for i in range(dl.steps_per_round):
            rng, k = jax.random.split(rng)
            batch = next(data_iter)
            device_batch = {k2: jnp.asarray(v) for k2, v in batch.items()
                            if k2 in ("x", "target", "R1", "t1", "R2",
                                      "t2", "K")}
            student, opt_state, metrics = step(
                student, opt_state, teacher, device_batch, k)
            if i == 0:
                loss_first = float(jax.device_get(metrics["loss"]))
        loss_last = float(jax.device_get(metrics["loss"]))
        if not np.isfinite(loss_last):
            raise FloatingPointError(
                f"distill round {r} ({cur}→{student_steps} steps) "
                f"diverged: loss={loss_last}")
        version = ""
        if store is not None:
            host = jax.tree.map(np.asarray, jax.device_get(student))
            m = store.publish_params(
                host, step=base_step, ema=False,
                channel=publish_channel,
                notes=(f"progressive distillation round {r}: "
                       f"{cur}→{student_steps} steps "
                       f"(loss {loss_first:.4g}→{loss_last:.4g})"))
            version = m.version
            if event_cb is not None:
                event_cb(base_step, "distill_publish",
                         f"round {r}: {cur}→{student_steps} steps -> "
                         f"{version} (channel {publish_channel})", version)
        res = RoundResult(
            round_index=r, teacher_steps=cur, student_steps=student_steps,
            updates=dl.steps_per_round, loss_first=loss_first,
            loss_last=loss_last,
            seconds=round(time.perf_counter() - t_round, 3),
            version=version)
        results.append(res)
        log(f"distill round {r}: {cur} -> {student_steps} steps, "
            f"loss {loss_first:.4g} -> {loss_last:.4g} "
            f"({res.seconds:.1f}s)"
            + (f", published {version}" if version else ""))
        params = student
        cur = student_steps
        r += 1
    return results
