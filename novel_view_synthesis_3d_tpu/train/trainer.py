"""Trainer: the end-to-end training driver.

API-compatible with the reference's `Trainer(folder, *, train_batch_size,
train_lr, train_num_steps, save_every, img_sidelength, results_folder)`
(train.py:78-126) but TPU-native throughout: mesh + sharded batches instead
of pmap replication, on-device noising, Orbax checkpoints with auto-resume
(the reference cannot resume — SURVEY.md §5.4), real metrics, periodic
sample dumps, and optional jax.profiler traces.
"""

from __future__ import annotations

import os
import queue
import signal
import threading
import time
from typing import Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.config import Config
from novel_view_synthesis_3d_tpu.data.pipeline import (
    cycle,
    iter_batches,
    make_dataset,
    make_grain_loader,
)
from novel_view_synthesis_3d_tpu.diffusion.schedules import (
    make_schedule,
    sampling_schedule,
)
from novel_view_synthesis_3d_tpu.models.xunet import XUNet
from novel_view_synthesis_3d_tpu.parallel import dist, mesh as mesh_lib
from novel_view_synthesis_3d_tpu.parallel import pipeline as pipeline_lib
from novel_view_synthesis_3d_tpu.sample.ddpm import make_sampler
from novel_view_synthesis_3d_tpu.train.checkpoint import CheckpointManager
from novel_view_synthesis_3d_tpu.train.guard import init_guard_state
from novel_view_synthesis_3d_tpu.train.metrics import MetricsLogger
from novel_view_synthesis_3d_tpu.train.state import (
    create_train_state,
    pack_train_state,
    unpack_ema,
    unpack_train_state,
)
from novel_view_synthesis_3d_tpu.train.step import (
    effective_accum_steps,
    make_train_step,
)
from novel_view_synthesis_3d_tpu.utils import faultinject, watchdog
from novel_view_synthesis_3d_tpu.utils.images import save_image_grid
from novel_view_synthesis_3d_tpu.utils.profiling import (
    StepTimer,
    enable_nan_checks,
)


def _sample_model_batch(batch: dict) -> dict:
    """Shape-template batch for model.init from a clean data batch."""
    target = batch["target"]
    return {
        "x": jnp.asarray(batch["x"]),
        "z": jnp.asarray(target),
        "logsnr": jnp.zeros((target.shape[0],)),
        "R1": jnp.asarray(batch["R1"]),
        "t1": jnp.asarray(batch["t1"]),
        "R2": jnp.asarray(batch["R2"]),
        "t2": jnp.asarray(batch["t2"]),
        "K": jnp.asarray(batch["K"]),
    }


class _DevicePrefetcher:
    """Bounded background uploader: runs `make_batch` (host fetch + async
    device_put) up to `depth` batches ahead of the consumer.

    Replaces the hardcoded depth-1 prefetch slot: with depth > 1 a slow
    fetch (cold page cache, contended loader workers) is absorbed by the
    buffered batches instead of stalling the very next step. `data.prefetch`
    sets the depth — the same knob that sizes the loaders' host-side
    prefetch, so one number describes the whole feed pipeline.

    Terminal conditions ride the queue in-band: StopIteration from the
    data iterator parks the prefetcher in an 'ended' state (get() raises
    StopIteration — only fatal if the trainer actually needs another
    batch, preserving the finite-injected-iterator contract), and any
    other exception re-raises in the consumer. The producer thread is a
    daemon: a fetch wedged in uninterruptible IO can't block interpreter
    exit (the run watchdog catches the stall itself — the consumer blocks
    inside its armed `data_fetch` phase once the buffer drains)."""

    _END = "end"
    _ERROR = "error"
    _BATCH = "batch"

    def __init__(self, make_batch: Callable[[], object], depth: int):
        self._make_batch = make_batch
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._terminal = None  # sticky ("end"|"error", exc) once popped
        self._gen = 0  # bumped by flush(); stale-generation batches drop
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device-prefetch")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            gen = self._gen  # read BEFORE the fetch: a flush() during
            # make_batch leaves this item stale, and get() discards it
            try:
                item = (self._BATCH, self._make_batch(), gen)
            except StopIteration:
                item = (self._END, None, gen)
            except BaseException as exc:  # propagate to the consumer
                item = (self._ERROR, exc, gen)
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.2)
                    break
                except queue.Full:
                    continue
            if item[0] != self._BATCH:
                return

    def get(self):
        """Next device batch; raises StopIteration at stream end, or the
        producer's exception. Blocks while the buffer is empty — callers
        arm the watchdog's data_fetch phase around this."""
        if self._terminal is not None:
            kind, exc = self._terminal
            raise StopIteration if kind == self._END else exc
        while True:
            kind, val, gen = self._q.get()
            if kind == self._BATCH:
                if gen != self._gen:
                    continue  # fetched before a flush(): suspect, drop
                return val
            self._terminal = (kind, val)
            if kind == self._END:
                raise StopIteration
            raise val

    def flush(self) -> None:
        """Drop buffered batches (rollback: the staged data is suspect) —
        including one currently inside make_batch on the producer thread,
        which lands in the queue AFTER this returns but carries the old
        generation and is discarded by get(). Terminal items stay sticky;
        the producer simply refills."""
        self._gen += 1  # before the drain: an in-flight fetch stays stale
        while True:
            try:
                kind, val, _gen = self._q.get_nowait()
            except queue.Empty:
                return
            if kind != self._BATCH:
                self._terminal = (kind, val)
                return

    def stop(self) -> None:
        self._stop.set()
        # Drain so a producer blocked on a full queue can observe _stop.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)


class Trainer:
    def __init__(
        self,
        folder: Optional[str] = None,
        *,
        train_batch_size: int = 2,
        train_lr: float = 1e-4,
        train_num_steps: int = 100_000,
        save_every: int = 1000,
        img_sidelength: int = 64,
        results_folder: str = "./results",
        config: Optional[Config] = None,
        data_iter: Optional[Iterator[dict]] = None,
        use_grain: bool = True,
        skip_batches: int = 0,
    ):
        if config is None:
            config = Config()
        if folder is not None:
            config = config.override(**{
                "data.root_dir": folder,
                "train.batch_size": train_batch_size,
                "train.lr": train_lr,
                "train.num_steps": train_num_steps,
                "train.save_every": save_every,
                "data.img_sidelength": img_sidelength,
                "train.results_folder": results_folder,
            })
        self.config = config.validate()
        tcfg = config.train

        dist.initialize_distributed()
        self.mesh = mesh_lib.make_mesh(config.mesh)
        mesh_lib.validate_global_batch(self.mesh, tcfg.batch_size)

        # --- data ---
        self._native_loader = None
        self._packed_loader = None
        # skip_batches: mid-rung ladder resume (train/ladder.py) — the
        # loader replays that many batches' PLANNING before yielding, so
        # a resumed rung consumes the exact batches the uninterrupted
        # run would have. Only the pipelined packed loader implements it.
        if skip_batches and (data_iter is not None
                             or config.data.backend != "packed"):
            raise ValueError(
                "skip_batches (ladder mid-rung resume) requires "
                "data.backend='packed' with no injected data_iter — the "
                "other backends have no deterministic plan stream to "
                "fast-forward")
        if data_iter is not None:
            self.data_iter = data_iter
            self.dataset = None
        elif config.data.mix:
            # Corpus mixer (data/corpus.py): N packed corpora behind one
            # FlatViewDataset-shaped surface; validate() already pinned
            # backend='packed' for mixes.
            from novel_view_synthesis_3d_tpu.data.corpus import (
                make_mixed_dataset)

            self.dataset = make_mixed_dataset(
                config.data,
                shard_index=jax.process_index(),
                shard_count=jax.process_count())
        else:
            self.dataset = make_dataset(
                config.data,
                # Packed backend: per-host reads at shard granularity —
                # this process opens only its 1/process_count() slice of
                # the shard set (files backend ignores the kwargs; its
                # sharding happens at the index-sampler level).
                shard_index=jax.process_index(),
                shard_count=jax.process_count())
        if self.dataset is not None:
            assert len(self.dataset) > 0
            local_bs = dist.local_batch_size(tcfg.batch_size)
            num_cond = config.model.num_cond_frames
            spi = config.data.samples_per_instance
            if spi > 1 and local_bs % spi != 0:
                # Config.validate checks the GLOBAL batch (it has no process
                # topology); the per-host slice must divide too.
                raise ValueError(
                    f"per-host batch {local_bs} (train.batch_size="
                    f"{tcfg.batch_size} over {jax.process_count()} "
                    f"processes) is not divisible by "
                    f"data.samples_per_instance={spi}")
            # Instance-grouped sampling (samples_per_instance > 1) is
            # implemented by all backends: in-process iterator, Grain
            # (grouped transform + flatten), the native loader (grouped
            # claims in C++), and the packed pipelined loader (grouped
            # plans) — no fallback needed.
            backend = config.data.loader if use_grain else "python"
            if config.data.backend == "packed":
                # Compute-overlapped pipelined loader (decode worker pool
                # feeding the _DevicePrefetcher below); `loader`/use_grain
                # govern the files backend only. A data.mix runs the
                # weighted mixer variant over the MixedDataset built above.
                if config.data.mix:
                    from novel_view_synthesis_3d_tpu.data.corpus import (
                        make_mixed_loader)

                    self._packed_loader = make_mixed_loader(
                        self.dataset, local_bs,
                        seed=config.data.shuffle_seed,
                        shard_index=jax.process_index(),
                        num_cond=num_cond,
                        workers=config.data.num_workers,
                        depth=config.data.prefetch,
                        skip_batches=skip_batches)
                else:
                    from novel_view_synthesis_3d_tpu.data.pipeline import (
                        make_packed_loader)

                    self._packed_loader = make_packed_loader(
                        self.dataset, local_bs,
                        seed=config.data.shuffle_seed,
                        shard_index=jax.process_index(),
                        num_cond=num_cond,
                        workers=config.data.num_workers,
                        depth=config.data.prefetch,
                        skip_batches=skip_batches)
                self.data_iter = iter(self._packed_loader)
            elif backend == "native":
                from novel_view_synthesis_3d_tpu.data import native_io
                if native_io.available():
                    self._native_loader = native_io.make_native_loader(
                        self.dataset, local_bs, num_cond=num_cond,
                        n_threads=config.data.num_workers,
                        prefetch_depth=config.data.prefetch,
                        seed=config.data.shuffle_seed,
                        shard_index=jax.process_index(),
                        shard_count=jax.process_count(),
                        max_record_retries=config.data.max_record_retries)
                    self.data_iter = iter(self._native_loader)
                else:
                    backend = "grain"  # graceful fallback
            if self._packed_loader is not None:
                pass  # data_iter already set above
            elif backend == "grain" and config.data.num_workers > 0:
                loader = make_grain_loader(
                    self.dataset, local_bs,
                    seed=config.data.shuffle_seed,
                    num_workers=config.data.num_workers,
                    num_cond=num_cond)
                self.data_iter = cycle(loader)
            elif self._native_loader is None:
                self.data_iter = iter_batches(
                    self.dataset, local_bs, seed=config.data.shuffle_seed,
                    shard_index=jax.process_index(),
                    shard_count=jax.process_count(),
                    num_cond=num_cond)

        # --- model / schedule / state ---
        self.schedule = make_schedule(config.diffusion)
        # train.remat overrides the checkpoint policy for the TRAINING
        # build only ('' = inherit model.remat): the param tree layout is
        # remat-independent (models/xunet._named_remat), so checkpoints
        # stay portable to samplers built without it.
        model_cfg = config.model
        if config.train.remat != "":
            import dataclasses as _dc
            model_cfg = _dc.replace(model_cfg, remat=config.train.remat)
        self.model = XUNet(
            model_cfg,
            mesh=self.mesh if config.model.sequence_parallel else None)
        first_batch = next(self.data_iter)
        self._held_batch = first_batch
        self._device_batch = None  # staged batch for the NEXT dispatch
        # Background device prefetcher (train()): fetches + uploads up to
        # data.prefetch batches ahead. The lock serializes its data_iter
        # access against main-thread peeks (eval probe, dump_samples).
        self._prefetcher: Optional[_DevicePrefetcher] = None
        self._data_lock = threading.Lock()
        # Fixed probe batch for eval_every: scoring the SAME views every
        # time makes the PSNR/SSIM curve comparable across steps (a fresh
        # random batch per eval would swing several dB on content alone).
        # Only copied when the probe is on — it pins a full batch in host
        # RAM for the Trainer's lifetime. With train.eval_folder set, the
        # probe batch is drawn from that HELD-OUT tree instead of the first
        # training batch, turning eval.csv into a true validation curve.
        self._eval_batch = None
        if tcfg.eval_every:
            if tcfg.eval_folder:
                self._eval_batch = jax.tree.map(
                    np.array, self._held_out_probe_batch(tcfg.eval_folder))
            else:
                self._eval_batch = jax.tree.map(np.array, first_batch)
        self._samplers = {}  # sample_steps -> jitted sampler (_sample_cond)
        self._cond_sens_fn = None  # lazily-built jitted probe (eval_step)
        self.state = create_train_state(
            tcfg, self.model, _sample_model_batch(first_batch))
        # ZeRO update sharding (train.update_sharding='zero'): between
        # steps the state carries opt_state/EMA in the packed row-sharded
        # layout of parallel/zero.py — 1/data_shards of those bytes per
        # device. Every host boundary (checkpoint save/restore, registry
        # publish, probes) converts through pack/unpack below so the rest
        # of the trainer only ever sees the canonical layout.
        self._zero = tcfg.update_sharding == "zero"
        if self._zero:
            self.state, self._state_sharding = pack_train_state(
                tcfg, self.mesh, self.state)
        else:
            self._state_sharding = mesh_lib.state_shardings(
                self.mesh, self.state, tcfg.fsdp, tp=tcfg.tp)
        self.state = jax.device_put(self.state, self._state_sharding)
        self.train_step = make_train_step(
            config, self.model, self.schedule, self.mesh,
            state_sharding=self._state_sharding)

        # --- host-side EMA (train.ema_host) ---
        # The EMA buffer lives in host RAM (f32 numpy) instead of HBM —
        # 4 bytes/param of chip memory back, the paper256-on-16G margin
        # (config.py preset comment). Folded in every ema_host_every steps
        # with the decay^k correction; rides in the checkpoint as the
        # state's ema_params leaves.
        self._host_ema = None
        self._host_ema_step = 0
        self._host_ema_pending = False  # seed from params at first fold
        ema_host_on = tcfg.ema_host and tcfg.ema_decay > 0
        if ema_host_on:
            # Structure-only template (the restore path just needs matching
            # tree structure/shapes). Seeding from the live params is
            # DEFERRED to the first fold: a pull here would be (a) a full
            # param transfer discarded on every resume and (b) on pods an
            # un-barriered replication collective inside __init__, where
            # per-host init-compile stagger can blow the communicator
            # rendezvous window — the first fold instead runs at a point
            # where every host is in lock-step.
            self._host_ema = jax.tree.map(
                lambda p: np.zeros(p.shape, np.float32), self.state.params)
            self._host_ema_pending = True

        # --- telemetry (obs/: spans + registry + sinks + gauges) ---
        # Created BEFORE the MetricsLogger so both share one EventBus —
        # the single write path for metrics.csv/events.csv/telemetry.jsonl.
        # The /metrics endpoint starts here iff obs.metrics_port is set.
        self.telemetry = obs.RunTelemetry.create(
            config.obs, tcfg.results_folder)
        self.tracer = self.telemetry.tracer
        reg = self.telemetry.registry
        self._steps_total = reg.counter(
            "nvs3d_steps_total", "optimizer steps completed this process")
        self._gauge_steps_per_sec = reg.gauge(
            "nvs3d_steps_per_sec", "training steps per second")
        self._gauge_imgs_per_sec = reg.gauge(
            "nvs3d_imgs_per_sec_per_chip",
            "training images per second per chip")
        self._gauge_mfu = reg.gauge(
            "nvs3d_mfu", "model-FLOPs utilization of the train step")
        self._gauge_loss = reg.gauge("nvs3d_loss", "last logged train loss")
        # Static memory/topology gauges: set once at init. The *_bytes
        # gauges report PER-DEVICE bytes (local shard shapes), so a ZeRO
        # run shows opt/EMA at ~1/data_shards of the replicated numbers —
        # the measured half of the ISSUE's memory claim, also asserted in
        # tests/test_zero.py.
        self._gauge_params_bytes = reg.gauge(
            "nvs3d_params_bytes", "per-device bytes of the param tree")
        self._gauge_opt_state_bytes = reg.gauge(
            "nvs3d_opt_state_bytes",
            "per-device bytes of the optimizer state")
        self._gauge_ema_bytes = reg.gauge(
            "nvs3d_ema_bytes", "per-device bytes of the EMA tree")
        self._gauge_pipeline_bubble = reg.gauge(
            "nvs3d_pipeline_bubble_frac",
            "GPipe fill/drain bubble fraction of the pipelined step")
        self._gauge_params_bytes.set(
            float(mesh_lib.tree_device_bytes(self.state.params)))
        self._gauge_opt_state_bytes.set(
            float(mesh_lib.tree_device_bytes(self.state.opt_state)))
        self._gauge_ema_bytes.set(
            float(mesh_lib.tree_device_bytes(self.state.ema_params)))
        stages = config.mesh.stages
        self._gauge_pipeline_bubble.set(
            pipeline_lib.bubble_fraction(
                effective_accum_steps(
                    tcfg.batch_size, mesh_lib.num_data_shards(self.mesh),
                    tcfg.grad_accum_steps), stages)
            if stages > 1 else 0.0)
        # One-time FLOPs estimate for MFU (obs.cost_analysis): filled at
        # the first dispatch via train_step.lower(...).cost_analysis().
        self._flops_per_step: Optional[float] = None
        # Compile ledger (obs/compiles.py): every jit build this process
        # makes lands in compiles.jsonl with a fingerprint, so a recompile
        # can name the argument that changed. The train step's entry is
        # recorded at its first dispatch (where the wall time is known).
        self._compile_ledger = obs.CompileLedger(tcfg.results_folder,
                                                 registry=reg)
        self._train_step_hlo = ""
        # Numerics observatory (train.numerics): host half of the in-jit
        # per-layer-group stats — numerics.jsonl rows, grad-norm gauges,
        # EWMA spike detection. The labels are kept even with the monitor
        # off: the step always emits the stats, so NaN provenance
        # (first_bad_layer on anomaly events/flight dumps) works without
        # opting into the full observatory.
        from novel_view_synthesis_3d_tpu.models.xunet import op_groups

        self._numerics_labels = obs.group_labels(op_groups(config.model))
        self._numerics: Optional[obs.NumericsMonitor] = None
        if tcfg.numerics.enabled:
            self._numerics = obs.NumericsMonitor(
                self._numerics_labels,
                self.telemetry.bus, reg,
                every=tcfg.numerics.every,
                spike_z=tcfg.numerics.spike_z,
                ewma_decay=tcfg.numerics.ewma_decay)
        # Continuous profiler (obs.profile): re-arming jax.profiler
        # windows attributed to the same op-group vocabulary. Host-side
        # only; the loop hook sits next to the one-shot xprof window's.
        self._profiler = obs.make_profiler(
            config.obs.profile, tcfg.results_folder, config.model,
            self.telemetry.bus, reg) if config.obs.enabled else None
        # armed_steps_total snapshot at the last metrics log: a log
        # interval that overlapped a profile window skips the step-rate
        # gauges (the overhead-exclusion contract).
        self._profiler_armed_mark = 0
        # /healthz progress facts: an external probe distinguishes
        # wedged-but-listening from healthy by last_step_age_s.
        self._last_step_t = time.time()
        if self.telemetry.server is not None:
            self.telemetry.server.set_health_provider(self._health_snapshot)

        # --- checkpointing / metrics ---
        self.ckpt = CheckpointManager(tcfg.checkpoint_dir)
        # Fault-tolerance bookkeeping (docs/DESIGN.md "Fault tolerance"):
        # rollback budget consumed + last anomaly total observed (to log
        # each new anomaly exactly once).
        self._rollbacks = 0
        self._anomalies_seen = 0
        if tcfg.resume:
            # restore_with_growth (train/ladder.py): a checkpoint saved
            # before model.num_classes grew the category table restores
            # with the table's zero-init spliced in (asserted neutral);
            # same-version checkpoints take the plain path inside.
            from novel_view_synthesis_3d_tpu.train.ladder import (
                restore_with_growth)

            restored = restore_with_growth(self.ckpt, self._ckpt_state())
            if restored is not None:
                restored = self._adopt_restored_state(restored)
                # Restore provenance line: which step actually resumed, and
                # whether corrupt newer steps were walked past.
                prov = self.ckpt.last_restore or {}
                rejected = prov.get("rejected", [])
                fallback = (f" (fell back past corrupt step(s) "
                            f"{[s for s, _ in rejected]})" if rejected
                            else "")
                print(f"resumed from checkpoint at step "
                      f"{int(self.state.step)}{fallback}")
        self.metrics = MetricsLogger(tcfg.results_folder,
                                     bus=self.telemetry.bus)
        prov = self.ckpt.last_restore or {}
        for bad_step, reason in prov.get("rejected", []):
            self.metrics.log_event(
                int(prov["step"]), "restore_fallback",
                f"step {bad_step} rejected: {reason.splitlines()[0][:160]}")
        self.results_folder = tcfg.results_folder
        os.makedirs(self.results_folder, exist_ok=True)

        # --- registry publisher (registry.publish_every; docs/DESIGN.md
        # "Model lifecycle") ---
        # Every publish_every steps the EMA snapshot is published to the
        # registry's `latest` channel as a content-hashed version. The
        # hand-off is a reference; serialization/hashing/fsync run on the
        # publisher's worker thread, so the step loop never blocks on
        # registry IO. Process 0 only — the snapshot gather below is the
        # collective part every host joins.
        self._publisher = None
        rcfg = config.registry
        if rcfg.publish_every > 0 and jax.process_index() == 0:
            from novel_view_synthesis_3d_tpu.registry import (
                RegistryPublisher, RegistryStore)
            from novel_view_synthesis_3d_tpu.registry.manifest import (
                config_digest)

            bus = self.telemetry.bus
            self._publisher = RegistryPublisher(
                RegistryStore(rcfg.dir),
                ema=rcfg.publish_ema and tcfg.ema_decay > 0,
                config_digest=config_digest(config),
                event_cb=lambda step, kind, detail, version="": bus.event(
                    step, kind, detail, model_version=version,
                    echo="[registry]"))
        # units_per_measure: each measured region covers one dispatch, i.e.
        # steps_per_dispatch training steps — normalize so the end-of-run
        # summary reports true per-step times at any dispatch width.
        self.timer = StepTimer(units_per_measure=tcfg.steps_per_dispatch)
        if tcfg.debug_nans:
            enable_nan_checks()

        # Preemption handling (SURVEY.md §5.3 — the reference has none):
        # TPU VMs receive SIGTERM on maintenance/preemption. Flag it and let
        # the step loop checkpoint + exit cleanly; combined with
        # resume=True the run continues from the last step after reschedule.
        self._preempted = False
        if tcfg.handle_preemption:
            try:
                signal.signal(signal.SIGTERM, self._on_preempt)
            except ValueError:
                pass  # not the main thread (e.g. under some test runners)

        # Hang/stall watchdog (utils/watchdog.py; docs/DESIGN.md "Stall
        # recovery"). The monitor thread starts with train() and feeds on
        # the loop's phase markers; _on_stall below runs ON THE MONITOR
        # THREAD, so it only writes (events.csv row, flag) — escalation is
        # observed by the main loop at the next cross-host agreement
        # point, exactly like preemption.
        self._stalled = False  # set by the watchdog; observed by the loop
        self._fetches = 0  # host-batch fetch ordinal (data-stall drills)
        self._step_host = self.step  # sync-free step estimate (watchdog)
        # Supervised-restart generation (train/supervisor.py): rides into
        # metrics.csv so a curve produced across restarts says so.
        from novel_view_synthesis_3d_tpu.train.supervisor import RESTART_ENV
        self._restarts = int(os.environ.get(RESTART_ENV, "0") or 0)
        if self._restarts:
            self.metrics.log_event(
                self.step, "supervised_resume",
                f"restart generation {self._restarts} resumed at step "
                f"{self.step}")
        self.watchdog = watchdog.from_config(
            tcfg.watchdog, on_stall=self._on_stall,
            diagnosis_dir=tcfg.results_folder,
            # Device memory queries can themselves hang on a wedged
            # backend; the bundle helper bounds them, but skip entirely in
            # multi-process runs where a straggling query could collide
            # with collectives.
            query_device=jax.process_count() == 1)

    def _on_preempt(self, signum, frame) -> None:
        self._preempted = True

    def _on_stall(self, phase: str, diagnosis_path: str) -> None:
        """Watchdog escalation (monitor thread — flags only, no JAX calls).

        Per-phase policy: a stalled checkpoint_save DEGRADES (diagnosis +
        events.csv row; training continues — exiting through a save that
        is itself stuck would be circular, and the save path already has
        retry/degrade semantics); every other phase flags a cross-host-
        agreed checkpoint-and-exit, the same escalation lane preemption
        uses, so one stuck host can't wedge the slice."""
        degrade = phase == "checkpoint_save"
        self.metrics.log_event(
            self.step_host_estimate, "stall",
            f"phase {phase} exceeded its watchdog budget; diagnosis in "
            f"{diagnosis_path}"
            + ("; degrading (save retries continue)" if degrade
               else "; checkpoint-and-exit requested"))
        # Flight-recorder dump next to the watchdog's stall bundle: the
        # last ~512 spans/events/gauges BEFORE the stall (no JAX calls —
        # safe on the monitor thread).
        if self.telemetry.flight is not None:
            self.telemetry.flight.dump(
                "stall", phase=phase, step=self.step_host_estimate,
                degrade=degrade)
        if not degrade:
            self._stalled = True

    @property
    def step_host_estimate(self) -> int:
        """Last step count observed WITHOUT a device sync — safe to read
        from the watchdog thread while the main thread is stuck inside a
        dispatch (self.step would join it in the hang)."""
        return self._step_host

    def _stop_agreed(self) -> int:
        """Cross-host agreement on the exit flags (0 none, 1 preempted,
        2 watchdog stall — max over hosts wins).

        SIGTERM (or a stall) can land at different step boundaries on
        different hosts; if one host broke into the (collective)
        checkpoint save while another entered the next train step's psum,
        the mismatched collectives would hang the slice. Every host
        therefore joins an allgather each step and all of them break
        together iff any host flagged. The per-step allgather is a few µs
        over ICI — negligible next to a train step.
        """
        local = 2 if self._stalled else (1 if self._preempted else 0)
        if jax.process_count() == 1:
            return local
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.asarray(local))
        return int(np.max(flags))

    @property
    def stalled(self) -> bool:
        """True once the watchdog escalated a stall (cli.cmd_train exits
        with watchdog.EXIT_STALL so a supervisor restarts the run)."""
        return self._stalled

    # ------------------------------------------------------------------
    @property
    def step(self) -> int:
        return int(jax.device_get(self.state.step))

    def _next_batch(self) -> dict:
        with self._data_lock:
            if self._held_batch is not None:
                batch, self._held_batch = self._held_batch, None
                return batch
            return next(self.data_iter)

    def _peek_batch(self) -> dict:
        """Look at the next batch without consuming it from the loop."""
        with self._data_lock:
            if self._held_batch is None:
                self._held_batch = next(self.data_iter)
            return self._held_batch

    # ------------------------------------------------------------------
    def _host_params(self):
        """Full host numpy copy of the live params. On multi-process runs
        EVERY host joins a replication collective first (FSDP shards →
        fully replicated), so all hosts see — and host-EMA over — the same
        tree; call at the same step on every host."""
        params = self.state.params
        if jax.process_count() > 1:
            params = mesh_lib.replicate(self.mesh, params)
        return jax.device_get(params)

    def _ckpt_state(self):
        """State handed to Orbax: with host EMA on, the numpy EMA tree
        rides in ema_params (StandardSave/Restore handle mixed
        device/numpy leaves), so the checkpoint format is identical to a
        device-EMA run's."""
        state = self.state
        if self._zero:
            # Gather-on-save: checkpoints always hold the CANONICAL
            # layout, so a run can resume under either update_sharding
            # setting (tests/test_zero.py round-trips both ways). The
            # device_get is the same full-state fetch Orbax would do.
            state = unpack_train_state(
                self.config.train, self.mesh, jax.device_get(state))
        if self._host_ema is None:
            return state
        return state.replace(ema_params=self._host_ema)

    def _adopt_restored_state(self, restored):
        """Install a checkpoint-restored TrainState (resume or rollback):
        peel the host-EMA tree back into host RAM, shard the rest onto the
        mesh, and re-anchor the sparse-EMA step counter.

        The restored leaves are explicitly COPIED before the donating train
        step may consume them: on the CPU backend Orbax/tensorstore can
        hand back arrays aliasing its own restore buffers, and jit
        donation then writes outputs into that shared memory — observed as
        garbage step counters right after a rollback (fault-injection
        suite). jnp.copy is cheap next to the restore IO and guarantees
        the state owns its buffers on every backend."""
        if self._host_ema is not None:
            self._host_ema = jax.tree.map(np.asarray, restored.ema_params)
            self._host_ema_pending = False
            restored = restored.replace(ema_params=None)
        owned = jax.tree.map(
            lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a,
            restored)
        if self._zero:
            # Checkpoints are canonical (gather-on-save above); re-pack
            # into the row-sharded between-steps layout before device_put.
            owned, _ = pack_train_state(self.config.train, self.mesh, owned)
        self.state = jax.device_put(owned, self._state_sharding)
        self._host_ema_step = int(jax.device_get(restored.step))
        return restored

    def _rollback(self, step_now: int) -> None:
        """Anomaly-guard escalation: restore the last intact checkpoint.

        Fired when `max_anomaly_strikes` consecutive steps were anomalous —
        the skipped-update guard alone isn't recovering, so the optimizer
        state (or the data window) is presumed poisoned. The restored state
        gets a RESEEDED rng (same rng + same step would replay the exact
        t/ε/dropout draws that blew up) and a cleared guard; the data
        stream simply continues — the replayed steps see fresh batches.
        Bounded by `max_rollbacks`, then abort: past that point the fault
        is systematic and retrying only burns pod-hours."""
        tcfg = self.config.train
        self._rollbacks += 1
        self.metrics.log_event(
            step_now, "rollback",
            f"{tcfg.max_anomaly_strikes} consecutive anomalies; attempt "
            f"{self._rollbacks}/{tcfg.max_rollbacks}")
        if self._rollbacks > tcfg.max_rollbacks:
            raise RuntimeError(
                f"anomaly guard: {tcfg.max_anomaly_strikes} consecutive "
                f"anomalous steps at step {step_now} and the rollback "
                f"budget (train.max_rollbacks={tcfg.max_rollbacks}) is "
                "exhausted — aborting. Inspect metrics.csv/events.csv; "
                "likely a systematic fault (bad data shard, lr blow-up), "
                "not a transient.")
        self.ckpt.wait()
        with self.tracer.span("checkpoint_restore", step=step_now):
            restored = self.ckpt.restore(self._ckpt_state())
        if restored is None:
            raise RuntimeError(
                f"anomaly guard: rollback requested at step {step_now} but "
                "no checkpoint exists yet (train.save_every="
                f"{tcfg.save_every}) — aborting before the anomaly "
                "propagates")
        restored = restored.replace(
            rng=jax.random.fold_in(restored.rng, 0x5EED + self._rollbacks),
            guard=(init_guard_state() if restored.guard is not None
                   else None))
        self._adopt_restored_state(restored)
        self._anomalies_seen = 0
        self._device_batch = None  # drop the staged (suspect) batch
        if self._prefetcher is not None:
            self._prefetcher.flush()  # ...and the buffered ones behind it
        self.metrics.log_event(
            self.step, "rollback_restored",
            f"resumed at step {self.step} with reseeded rng")

    def _check_guard(self, step_now: int, step_metrics: dict) -> bool:
        """Host-side half of the anomaly guard: log new anomalies, roll
        back when strikes exceed the budget. Returns True if a rollback
        happened (the loop should restart its iteration)."""
        tcfg = self.config.train
        if not tcfg.anomaly_guard or "strikes" not in step_metrics:
            return False
        strikes, anomalies = (int(v) for v in jax.device_get(
            [step_metrics["strikes"], step_metrics["anomalies"]]))
        if anomalies > self._anomalies_seen:
            # NaN provenance (obs/numerics.py): the per-group non-finite
            # counts name the first bad layer group, so the anomaly event
            # (and the flight dump) carry their root cause.
            first_bad = ""
            if "numerics" in step_metrics:
                first_bad = obs.first_bad_group(
                    self._numerics_labels,
                    jax.device_get(step_metrics["numerics"]["nonfinite"]))
            detail = (f"non-finite/spike step skipped (strikes={strikes}, "
                      f"total={anomalies})")
            if first_bad:
                detail += f" first_bad_layer={first_bad}"
            self.metrics.log_event(step_now, "anomaly", detail)
            if (self.telemetry.flight is not None
                    and strikes <= tcfg.steps_per_dispatch):
                # One forensics dump per strike streak (its first
                # anomalous dispatch), not per anomaly — a poisoned-run
                # drill must not carpet the results folder.
                self.telemetry.flight.dump(
                    "anomaly", step=step_now, strikes=strikes,
                    anomalies=anomalies, first_bad_layer=first_bad)
            self._anomalies_seen = anomalies
        if strikes >= tcfg.max_anomaly_strikes:
            self._rollback(step_now)
            return True
        return False

    def _maybe_update_host_ema(self, step_now: int,
                               force: bool = False) -> None:
        """Fold the live params into the host EMA buffer if due.

        Sparse EMA: k elapsed steps fold in with decay^k —
        ema ← d^k·ema + (1−d^k)·params — exact for k=1 and the standard
        approximation for k>1 (one params→host transfer per
        ema_host_every steps instead of per step). `force` (checkpoint
        saves, probes) flushes regardless of the interval."""
        if self._host_ema is None:
            return
        if self._host_ema_pending:
            # First touch of a fresh (non-resumed) run: seed EMA = params.
            # On pods every host reaches here at the same step (the fold
            # sites are symmetric), so the replicate inside _host_params
            # rendezvouses in lock-step.
            self._host_ema = jax.tree.map(
                lambda a: np.asarray(a, np.float32), self._host_params())
            self._host_ema_pending = False
            self._host_ema_step = step_now
            return
        k = step_now - self._host_ema_step
        if k <= 0 or (not force and k < self.config.train.ema_host_every):
            return
        d = self.config.train.ema_decay ** k
        params = self._host_params()
        self._host_ema = jax.tree.map(
            lambda e, p: d * e + (1.0 - d) * np.asarray(p, np.float32),
            self._host_ema, params)
        self._host_ema_step = step_now

    def _make_device_batch(self):
        """One dispatch's worth of data: host fetch + async device upload.

        Runs on the prefetcher thread (train()) up to data.prefetch
        batches ahead of the consumer; the device_put inside shard_batch
        is async, so buffered batches are in flight to HBM while the
        device executes earlier steps. The stall drill keys on the fetch
        ordinal — deterministic regardless of how far ahead the
        prefetcher runs.

        With train.steps_per_dispatch = K > 1, K consecutive batches are
        stacked on a leading step axis and consumed by one fused-scan
        dispatch (train/step.py multi_step) — fresh data every step, K-1
        fewer dispatch round trips."""
        spd = self.config.train.steps_per_dispatch

        def clean(b):
            return {k: v for k, v in b.items() if k != "noise"}

        faultinject.maybe_stall("data", self._fetches)
        fetch = self._fetches
        self._fetches += 1
        # Two spans per staged batch: data_fetch is the HOST half (loader
        # wait + decode), h2d the device upload — on the trace timeline
        # these sit on the prefetcher thread's row, overlapping train_step
        # spans on the main thread when the pipeline is healthy.
        with self.tracer.span("data_fetch", fetch=fetch):
            if spd <= 1:
                host = clean(self._next_batch())
            else:
                hosts = [clean(self._next_batch()) for _ in range(spd)]
                host = jax.tree.map(lambda *xs: np.stack(xs), *hosts)
        with self.tracer.span("h2d", fetch=fetch):
            return mesh_lib.shard_batch(self.mesh, host, stacked=spd > 1)

    def _staged_batch(self):
        """The next device batch, blocking under the armed data_fetch
        phase: when the prefetch buffer is drained by a stalled loader,
        the consumer blocks HERE and the watchdog sees the stall exactly
        as it did when the fetch was inline."""
        with self.watchdog.phase("data_fetch"):
            if self._prefetcher is not None:
                return self._prefetcher.get()
            return self._make_device_batch()

    def train(self) -> None:
        tcfg = self.config.train
        last_metrics = None
        profiling = False
        self.watchdog.start()
        # Device prefetch honoring data.prefetch (was a hardcoded depth-1
        # slot): the background thread keeps up to `depth` staged batches
        # uploading while the device runs, so a fetch hiccup shorter than
        # depth × step-time never stalls a dispatch.
        self._prefetcher = _DevicePrefetcher(
            self._make_device_batch, depth=self.config.data.prefetch)
        try:
            self._train_loop(tcfg, last_metrics, profiling)
        except BaseException as exc:
            # Fatal exit (incl. KeyboardInterrupt/SystemExit): dump the
            # flight ring BEFORE the telemetry teardown below, so the
            # postmortem has the last spans/events leading into the
            # fault even when the process is about to die.
            if self.telemetry.flight is not None:
                self.telemetry.flight.dump(
                    "fatal", error=repr(exc)[:200],
                    step=self.step_host_estimate)
            raise
        finally:
            self._prefetcher.stop()
            self._prefetcher = None
            self.watchdog.stop()
            if self._publisher is not None:
                # Drain, don't drop: the final snapshot is usually the
                # one an operator wants to promote.
                self._publisher.stop(drain=True)
            # A window open at exit (run ended mid-capture) still stops,
            # parses, and lands its row — before the bus closes.
            if self._profiler is not None:
                self._profiler.close()
            # Export trace.json, stop the device monitor, close the bus
            # and endpoint. Idempotent; a crashed run still gets its
            # trace up to the fault.
            self.telemetry.finalize()

    def _train_loop(self, tcfg, last_metrics, profiling) -> None:
        # The first dispatch of the jitted train step runs under the
        # separate (long) compile budget; every later one under the
        # steady-state step budget.
        first_dispatch = True
        while self.step < tcfg.num_steps:
            if tcfg.profile_steps:
                at = self.step
                end = tcfg.profile_from + tcfg.profile_steps
                if profiling and at >= end:
                    jax.profiler.stop_trace()
                    profiling = False
                elif not profiling and tcfg.profile_from <= at < end:
                    # Range check (not equality) so the window still fires
                    # when resuming into or past profile_from.
                    jax.profiler.start_trace(
                        os.path.join(self.results_folder, "profile"))
                    profiling = True
            # Device batches come from the background prefetcher (up to
            # data.prefetch staged uploads in flight); a StopIteration is
            # only fatal when a step actually needs the missing batch.
            if self.telemetry.xprof is not None:
                # Sync-free step estimate: the xprof window tolerates a
                # ±1-dispatch skew; a device_get here would add a sync to
                # EVERY iteration just to arm a rarely-used capture.
                self.telemetry.xprof.on_step(self._step_host)
            if self._profiler is not None:
                # Continuous profiling window (same sync-free estimate).
                self._profiler.on_step(self._step_host)
            if self._device_batch is None:
                try:
                    self._device_batch = self._staged_batch()
                except StopIteration:
                    raise RuntimeError(
                        "data_iter exhausted before train.num_steps="
                        f"{tcfg.num_steps} (at step {self.step}). Injected "
                        "finite iterators must supply ceil(remaining_steps /"
                        f" steps_per_dispatch={tcfg.steps_per_dispatch}) * "
                        "steps_per_dispatch batches; with "
                        "steps_per_dispatch>1 a partial trailing group "
                        "cannot be dispatched.") from None
            if first_dispatch:
                # One-time FLOPs estimate for the MFU gauge, BEFORE the
                # donating dispatch deletes the state's buffers. lower()
                # only traces — no XLA compile, no device time.
                self._maybe_cost_analysis(self._device_batch)
                # Ledger fingerprint is taken BEFORE the donating dispatch
                # too — it reads the arg tree's shapes/dtypes.
                compile_fp = obs.fingerprint_args(
                    self.state, self._device_batch,
                    static=(self.config.model, self.config.diffusion,
                            self.config.train, self.config.mesh))
                compile_t0 = time.perf_counter()
            phase = "compile" if first_dispatch else "train_step"
            was_first = first_dispatch
            with self.timer.measure(), self.watchdog.phase(phase), \
                    self.tracer.span(phase) as sp:
                first_dispatch = False
                self.state, step_metrics = self.train_step(
                    self.state, self._device_batch)
                self._device_batch = None  # consumed (donated) by the step
                # Dispatch is async; the step read below device_gets
                # state.step, which syncs on the whole step — keep it inside
                # the timed region so timings reflect real device time.
                # (The NEXT batch's fetch + upload overlaps this step on
                # the prefetcher thread.)
                step_now = self.step
                self._step_host = step_now
                sp.set(step=step_now)
                # Deterministic hang drill: the injected sleep sits inside
                # the armed train_step phase, exactly where a wedged
                # dispatch would stall.
                faultinject.maybe_stall("step", step_now)
            if was_first:
                # Compile-ledger entry for the train step: the first
                # dispatch's wall time IS compile + first step (the same
                # definition the compile span/watchdog budget uses).
                self._compile_ledger.record(
                    "train_step", compile_fp,
                    wall_s=time.perf_counter() - compile_t0,
                    hlo=self._train_step_hlo,
                    backend=jax.default_backend())
            # /healthz heartbeat: a dispatch completed; last_step_age_s
            # restarts from zero.
            self._last_step_t = time.time()
            # Counter semantics: steps EXECUTED — each dispatch runs
            # steps_per_dispatch optimizer steps; a rolled-back window
            # that re-runs counts again (a Prometheus counter is monotone,
            # the step column in metrics.csv carries the logical step).
            self._steps_total.inc(self.config.train.steps_per_dispatch)

            # Numerics observatory: decimated host publish of the in-jit
            # per-group stats. BEFORE the guard check so an anomalous
            # window's stats (and its non-finite provenance) are on disk
            # even when the guard rolls back and restarts the loop.
            if self._numerics is not None and "numerics" in step_metrics:
                self._numerics.observe(step_now, step_metrics["numerics"])

            if self._check_guard(step_now, step_metrics):
                continue  # rolled back: restart the loop from the restore

            self._maybe_update_host_ema(step_now)

            # First-iteration log: step_now is 1 normally, K under fused
            # multi-step dispatch (both only at a fresh, non-resumed start).
            if (step_now % tcfg.log_every == 0
                    or step_now == tcfg.steps_per_dispatch):
                with self.tracer.span("d2h", step=step_now):
                    host_metrics = jax.device_get(step_metrics)
                util = self._utilization_metrics()
                corpus_cols = self._publish_corpus_stats(step_now,
                                                         host_metrics)
                logged = self.metrics.log(
                    step_now,
                    dict(host_metrics,
                         rollbacks=self._rollbacks,
                         restarts=self._restarts, **util),
                    tcfg.batch_size, extra=corpus_cols)
                # Overhead-exclusion contract (obs.profile): a log
                # interval that overlapped a profile window carries the
                # window's arm/parse host time in its wall clock, so its
                # step-rate samples are excluded from the rate gauges
                # (metrics.csv keeps every row — the gauges feed alerts).
                armed = (self._profiler.armed_steps_total
                         if self._profiler is not None else 0)
                self._update_gauges(
                    logged, util,
                    exclude_rates=armed != self._profiler_armed_mark)
                self._profiler_armed_mark = armed
                print(f"{step_now}: loss={logged['loss']:.5f} "
                      f"imgs/s/chip={logged['imgs_per_sec_per_chip']:.2f}")
                last_metrics = logged

            if tcfg.save_every and step_now % tcfg.save_every == 0:
                # Pass the (possibly FSDP-sharded) device state directly:
                # Orbax gathers per-shard across hosts; device_get would
                # crash on non-fully-addressable arrays in multi-host runs.
                self._maybe_update_host_ema(step_now, force=True)
                with self.watchdog.phase("checkpoint_save"), \
                        self.tracer.span("checkpoint_save", step=step_now):
                    faultinject.maybe_stall("save", step_now)
                    self.ckpt.save(step_now, self._ckpt_state())

            rcfg = self.config.registry
            if rcfg.publish_every and step_now % rcfg.publish_every == 0:
                # Collective on pods (every host joins the snapshot
                # gather); only process 0 holds a publisher. The slow
                # half (serialize + hash + fsync + rename) runs on the
                # publisher's worker thread.
                with self.tracer.span("registry_publish", step=step_now):
                    snap = self._registry_snapshot(step_now)
                    if self._publisher is not None and snap is not None:
                        self._publisher.publish_async(step_now, snap)

            sample_due = (tcfg.sample_every
                          and step_now % tcfg.sample_every == 0)
            eval_due = tcfg.eval_every and step_now % tcfg.eval_every == 0
            if sample_due or eval_due:
                self._maybe_update_host_ema(step_now, force=True)
                # Called on EVERY host: non-reporting hosts join the param
                # replication collective and get None back. Gathered ONCE
                # even when both probes fire (on a pod each gather is a
                # full cross-host all-gather of the param tree).
                with self.watchdog.phase("eval"), \
                        self.tracer.span("eval", step=step_now):
                    probe_params = self._probe_host_params()
                    try:
                        if sample_due:
                            self.dump_samples(step_now, params=probe_params)
                        if eval_due:
                            logged = self.eval_step(step_now,
                                                    params=probe_params)
                            if logged is not None:
                                print(f"{step_now}: "
                                      f"eval psnr={logged['psnr']:.2f} "
                                      f"ssim={logged['ssim']:.4f}")
                    finally:
                        # Free the pinned probe copy promptly — at paper256
                        # it is the difference between the next step fitting
                        # HBM and an OOM (VERDICT r4 item 8).
                        self._release_probe_params(probe_params)

            # Fault-injection SIGTERM drill (env-gated, inert otherwise):
            # fires here so the flag is observed by the agreement check
            # below within the same iteration.
            faultinject.maybe_sigterm(step_now)

            stop = self._stop_agreed()
            if stop:
                print(("preemption signal received" if stop == 1 else
                       "watchdog stall escalation") + f" at step {step_now}"
                      ": checkpointing and exiting")
                break

        if profiling:
            jax.profiler.stop_trace()
        # Release the dead prefetched batch's HBM before post-training use
        # of this Trainer (sampling/eval on large configs wants the room).
        self._device_batch = None
        self._maybe_update_host_ema(self.step, force=True)
        with self.watchdog.phase("checkpoint_save"), \
                self.tracer.span("checkpoint_save", step=self.step):
            self.ckpt.save(self.step, self._ckpt_state(), force=True)
            self.ckpt.wait()
        print("training completed" if not self._stalled else
              f"training STALLED at step {self.step}; state checkpointed "
              "for a supervised restart")
        if last_metrics is not None:
            print(f"final: {last_metrics}")
        timing = self.timer.summary()
        if timing:
            print(f"step timing: {timing}")

    # -- telemetry helpers (obs/) --------------------------------------
    def _publish_corpus_stats(self, step_now: int,
                              host_metrics: dict) -> Optional[dict]:
        """Per-corpus attribution at log time (data/corpus.py mixes).

        Consumes the step's (C,) corpus_loss_sum/corpus_count aux (popped
        so the scalar logger never sees array values) and joins it with
        the MixedDataset's quarantine/decode stats and the MixedLoader's
        draw counts: one telemetry.jsonl row per corpus via the bus, a
        per-corpus loss gauge, and the `loss_<corpus>` extra columns for
        metrics.csv. Returns None on unmixed runs."""
        sums = host_metrics.pop("corpus_loss_sum", None)
        counts = host_metrics.pop("corpus_count", None)
        stats_fn = getattr(self.dataset, "corpus_stats", None)
        if sums is None or stats_fn is None:
            return None
        draws = getattr(self._packed_loader, "corpus_draws", None)
        cols: dict = {}
        reg = self.telemetry.registry
        for i, row in enumerate(stats_fn()):
            name = row["corpus"]
            n = float(counts[i])
            mean_loss = float(sums[i]) / n if n else float("nan")
            cols[f"loss_{name}"] = mean_loss
            if not np.isnan(mean_loss):
                reg.gauge(
                    f"nvs3d_corpus_{name}_loss",
                    f"last logged train loss attributed to corpus "
                    f"{name!r}").set(mean_loss)
            self.telemetry.bus.jsonl_row(dict(
                row, kind="corpus_stats", step=step_now,
                loss=mean_loss, samples=n,
                draws=(int(draws[i]) if draws is not None else None)))
        return cols

    def _health_snapshot(self) -> dict:
        """/healthz body (obs/server.py health provider): progress facts
        an external probe can alarm on — a wedged trainer keeps /metrics
        up while last_step_age_s grows without bound."""
        return {
            "status": "ok",
            "role": "train",
            "step": int(getattr(self, "_step_host", 0)),
            "last_step_age_s": round(time.time() - self._last_step_t, 3),
        }

    def _maybe_cost_analysis(self, device_batch) -> None:
        """One-time FLOPs estimate of the train step for the MFU gauge
        (obs.cost_analysis): jit(...).lower(...).cost_analysis() on the
        unoptimized HLO — a trace, not an XLA compile, so it neither
        touches the jit cache nor adds steady-state dispatches."""
        if not self.config.obs.cost_analysis \
                or self._flops_per_step is not None:
            return
        try:
            with self.tracer.span("cost_analysis"):
                lowered = self.train_step.lower(self.state, device_batch)
                # Piggyback the compile ledger's HLO module hash on the
                # lowering we already paid for.
                self._train_step_hlo = obs.hlo_hash(lowered)
                ca = lowered.cost_analysis()
            flops = (float(ca.get("flops", 0.0))
                     if isinstance(ca, dict) else 0.0)
        except Exception as e:  # bonus context, never fatal
            print(f"note: obs cost analysis unavailable ({e})")
            flops = 0.0
        # 0.0 = tried and unavailable (don't retry every dispatch). The
        # fused multi-step program's FLOPs cover steps_per_dispatch steps.
        self._flops_per_step = flops / max(
            1, self.config.train.steps_per_dispatch)
        if self._flops_per_step:
            self.telemetry.registry.gauge(
                "nvs3d_flops_per_step",
                "XLA cost-model FLOPs per optimizer step").set(
                    self._flops_per_step)

    def _utilization_metrics(self) -> dict:
        """device_mem_gb / mfu for the metrics.csv row (NaN = unknown)."""
        out = {}
        devmon = self.telemetry.devmon
        if devmon is not None and devmon.peak_bytes:
            out["device_mem_gb"] = devmon.peak_bytes / 1e9
        step_s = self.timer.last_s
        if self._flops_per_step and step_s:
            from novel_view_synthesis_3d_tpu.obs import devmon as obs_devmon

            m = obs_devmon.mfu(self._flops_per_step, 1.0 / step_s)
            if m is not None:
                out["mfu"] = m
        return out

    def _update_gauges(self, logged: dict, util: dict,
                       exclude_rates: bool = False) -> None:
        # exclude_rates: this log interval overlapped a continuous-
        # profiler window, so its wall clock includes arm/parse host
        # time — rate gauges (and the rate-derived MFU) keep their last
        # clean sample rather than alerting on profiler overhead.
        if not exclude_rates:
            self._gauge_steps_per_sec.set(logged["steps_per_sec"])
            self._gauge_imgs_per_sec.set(logged["imgs_per_sec_per_chip"])
            if "mfu" in util:
                self._gauge_mfu.set(util["mfu"])
        self._gauge_loss.set(logged["loss"])

    def _registry_snapshot(self, step_now: int):
        """Host numpy copy of the publishable tree: the EMA when the run
        trains one (and registry.publish_ema), else live params.

        Collective on pods — EVERY host must call at the same step (the
        replicate below rides ICI/DCN); non-reporting hosts get None.
        Returns a tree the publisher worker may hold past this step: the
        host-EMA fold REPLACES its tree (never mutates in place), and
        device_get materializes fresh host arrays, so the snapshot can't
        be overwritten under the async publish."""
        use_ema = (self.config.registry.publish_ema
                   and self.config.train.ema_decay > 0)
        if use_ema and self._host_ema is not None:
            self._maybe_update_host_ema(step_now, force=True)
            if jax.process_index() != 0:
                return None
            return self._host_ema
        device_ema = use_ema and self.state.ema_params is not None
        tree = (self.state.ema_params if device_ema else self.state.params)
        if jax.process_count() > 1:
            tree = mesh_lib.replicate(self.mesh, tree)
            jax.block_until_ready(tree)
            if jax.process_index() != 0:
                return None
        host = jax.tree.map(np.asarray, jax.device_get(tree))
        if device_ema and self._zero:
            # The device EMA rides in the packed 1/N row-sharded layout;
            # gather it back to canonical leaves exactly once per publish,
            # off the step loop (tests/test_zero.py asserts the published
            # tree hashes identical to a replicated run's).
            host = unpack_ema(self.config.train, self.mesh,
                              self.state.params, host)
        return host

    def _probe_host_params(self):
        """Sampling params for the in-loop probes, pod-safe.

        Single-process: returns the live (possibly device-sharded) params.
        Multi-process (pods): the naive probe would feed per-host batches
        into a collective program and device_get non-addressable outputs —
        a mid-training crash or hang. Instead EVERY host joins one
        replication collective here (FSDP shards → fully-replicated,
        riding ICI/DCN — so the train loop must call the probe on every
        host at the same step), then process 0 alone fetches the now
        host-addressable copy and samples on its own devices with zero
        collectives inside the sampler; other hosts get None and return
        early — no multi-writer eval.csv, no mismatched collectives."""
        self._maybe_update_host_ema(self.step, force=True)
        pd = self.config.train.probe_dtype or None
        if self._host_ema is not None:
            # Host EMA is already fully replicated host-side (every host
            # folds the same values) — no collective needed; process 0
            # pins it on a local device for the probe samplers. probe_dtype
            # (paper256: bf16) halves the pin — the f32 copy is ~2.6G the
            # 16G chip doesn't have mid-training (VERDICT r4 item 8).
            if jax.process_index() != 0:
                return None
            tree = self._host_ema
            if pd:
                tree = jax.tree.map(lambda a: np.asarray(a, pd), tree)
            return jax.device_put(tree, jax.local_devices()[0])
        params = (self.state.ema_params if self.state.ema_params is not None
                  else self.state.params)
        if self._zero and self.state.ema_params is not None:
            # Packed EMA → canonical, one gather per probe (the sampler
            # can't consume (N, c) rows); then pin on one local device
            # like the pod path below.
            packed = self.state.ema_params
            if jax.process_count() > 1:
                packed = mesh_lib.replicate(self.mesh, packed)
                jax.block_until_ready(packed)
                if jax.process_index() != 0:
                    return None
            host = unpack_ema(self.config.train, self.mesh,
                              self.state.params, jax.device_get(packed))
            if pd:
                host = jax.tree.map(lambda a: np.asarray(a, pd), host)
            return jax.device_put(host, jax.local_devices()[0])
        if jax.process_count() == 1:
            if pd and pd != self.config.model.param_dtype:
                return jax.tree.map(lambda a: jnp.asarray(a, pd), params)
            return params
        replicated = mesh_lib.replicate(self.mesh, params)
        jax.block_until_ready(replicated)
        if jax.process_index() != 0:
            return None
        # Pin the gathered copy on ONE local device: the probe samplers are
        # single-device programs, and handing them host numpy would re-pay
        # the host→device transfer per sampler call (2× when sample and
        # eval probes coincide).
        host = jax.device_get(replicated)
        if pd:
            host = jax.tree.map(lambda a: np.asarray(a, pd), host)
        return jax.device_put(host, jax.local_devices()[0])

    def _release_probe_params(self, probe_params) -> None:
        """Free the probe's pinned device copy (paper256 HBM margin).

        No-op when the probe handed out the live state trees themselves
        (single-process, probe_dtype unset) — only a distinct pinned copy
        is deleted. Guarded PER LEAF, not just per tree (ADVICE r5):
        jnp.asarray(a, dtype) is a no-copy alias when a leaf already has
        the target dtype, so a future mixed-dtype param tree could hand
        out a tree that fails the tree-level 'is' check while some of its
        leaves ARE the live training buffers — deleting those would kill
        the run."""
        if probe_params is None:
            return
        if (probe_params is self.state.params
                or probe_params is self.state.ema_params):
            return
        live = set()
        for tree in (self.state.params, self.state.ema_params):
            if tree is not None:
                live.update(id(leaf) for leaf in jax.tree.leaves(tree))
        for leaf in jax.tree.leaves(probe_params):
            if id(leaf) not in live and hasattr(leaf, "delete"):
                leaf.delete()

    def _held_out_probe_batch(self, folder: str):
        """Fixed probe batch from a held-out SRN tree (train.eval_folder).

        Drawn once, deterministically (seed 0), sized to the smaller of the
        train batch and what the tree holds — small val splits must not
        trip the loader's records>=batch contract."""
        import dataclasses

        ds = make_dataset(dataclasses.replace(
            self.config.data, root_dir=folder))
        if len(ds) == 0:
            raise ValueError(f"train.eval_folder={folder!r} has no records")
        bs = min(dist.local_batch_size(self.config.train.batch_size),
                 len(ds))
        spi = self.config.data.samples_per_instance
        if spi > 1:
            bs = (bs // spi) * spi  # iter_batches needs bs % spi == 0
            if bs == 0:
                raise ValueError(
                    f"train.eval_folder={folder!r} holds {len(ds)} records "
                    f"— fewer than data.samples_per_instance={spi}")
        return next(iter_batches(
            ds, bs, seed=0,
            num_cond=self.config.model.num_cond_frames))

    # ------------------------------------------------------------------
    _UNSET = object()  # "gather the probe params yourself" sentinel

    def eval_step(self, step: int, num: int = 4,
                  params=_UNSET) -> Optional[dict]:
        """In-loop quality probe on a FIXED batch of views.

        Samples the probe batch's target poses and scores PSNR/SSIM against
        the ground-truth targets — same views every call, so the eval.csv
        curve is comparable across steps. The batch comes from
        `train.eval_folder` (held-out views — a true validation curve) when
        set, else from the first TRAINING batch (reconstruction-progress
        signal only; the `eval` CLI does held-out). Uses EMA params
        when available, a respaced `eval_sample_steps` ladder, and logs to
        eval.csv — the reference has no quality signal at all during
        training (SURVEY.md §5.5)."""
        from novel_view_synthesis_3d_tpu.eval.metrics import psnr, ssim

        if params is Trainer._UNSET:
            params = self._probe_host_params()  # collective: all hosts call
        if params is None:
            return None  # non-reporting host of a multi-process run
        if self._eval_batch is None:  # direct eval_step call, eval_every=0
            tcfg = self.config.train
            self._eval_batch = jax.tree.map(
                np.array,
                self._held_out_probe_batch(tcfg.eval_folder)
                if tcfg.eval_folder else self._peek_batch())
        batch = self._eval_batch
        num = min(num, batch["target"].shape[0])
        imgs = self._sample_cond(
            {k: jnp.asarray(batch[k][:num])
             for k in ("x", "R1", "t1", "R2", "t2", "K")},
            seed=step, sample_steps=self.config.train.eval_sample_steps,
            params=params)
        truth = np.asarray(batch["target"][:num])
        logged = {
            "psnr": float(np.mean(psnr(imgs, truth))),
            "ssim": float(np.mean(ssim(imgs, truth))),
        }
        # Standing conditioning-sensitivity probe (VERDICT r3 item 3): the
        # r2/r3 inert-attention failure class trains an unconditional
        # pose-memorizer whose seen-pose PSNR looks healthy — this logs
        # 0.00000 in eval.csv the first time that happens instead of
        # requiring a manual postmortem. One cheap forward pair; absent
        # (not 0.0) while the probe is degenerate (e.g. zero-init output).
        from novel_view_synthesis_3d_tpu.eval.evaluate import (
            cond_sensitivity,
            make_cond_sensitivity_fn,
        )

        if self._cond_sens_fn is None:
            self._cond_sens_fn = make_cond_sensitivity_fn(self._probe_model())
        sens = cond_sensitivity(
            None, params,
            {k: jnp.asarray(batch[k][:num])
             for k in ("x", "R1", "t1", "R2", "t2", "K", "target")},
            key=jax.random.PRNGKey(step), fn=self._cond_sens_fn)
        # NaN (not a missing key) when the probe declines: the eval.csv
        # schema must be stable across a run — a step-0 eval (zero-init
        # output → probe degenerate) would otherwise log a different
        # column set than later evals and trigger the header rotation
        # mid-run, truncating the curve.
        logged["cond_sens"] = float("nan") if sens is None else sens
        self.metrics.log_eval(step, logged)
        return logged

    def _probe_model(self) -> XUNet:
        """The model the in-loop probes run: dense (non-sequence-parallel)
        attention — identical math and identical params, but free of the
        batch/'data'-axis divisibility constraint the ring path imposes (a
        4-view probe need not divide the mesh)."""
        if self.config.model.sequence_parallel:
            import dataclasses
            return XUNet(dataclasses.replace(
                self.config.model, sequence_parallel=False))
        return self.model

    def _sample_cond(self, cond: dict, seed: int, *, params,
                     sample_steps: Optional[int] = None) -> np.ndarray:
        """Sample novel views for a conditioning dict with current params.

        Samplers are cached per sample_steps — a fresh make_sampler closure
        would recompile its scan on every call.

        `params` comes from `_probe_host_params` (host-local on pods, so
        the sampler never emits a cross-host collective)."""
        key = (self.config.diffusion.sample_timesteps
               if sample_steps is None else sample_steps)
        sampler = self._samplers.get(key)
        if sampler is None:
            dcfg = self.config.diffusion
            sampler = make_sampler(self._probe_model(),
                                   sampling_schedule(dcfg, sample_steps),
                                   dcfg)
            self._samplers[key] = sampler
        imgs = sampler(params, jax.random.PRNGKey(seed), cond)
        return np.asarray(jax.device_get(imgs))

    def dump_samples(self, step: int, num: int = 4,
                     sample_steps: Optional[int] = None,
                     params=_UNSET) -> Optional[str]:
        """Sample novel views for the first records and write a PNG grid.

        Call on every host (the param gather inside is collective); only
        process 0 writes and returns a path."""
        if params is Trainer._UNSET:
            params = self._probe_host_params()
        if params is None:
            return None
        batch = self._peek_batch()
        cond = {k: jnp.asarray(batch[k][:num])
                for k in ("x", "R1", "t1", "R2", "t2", "K")}
        imgs = self._sample_cond(cond, seed=step, sample_steps=sample_steps,
                                 params=params)
        path = os.path.join(self.results_folder, f"samples_{step:07d}.png")
        save_image_grid(imgs, path)
        return path
