"""Metrics: stdout + CSV + optional TensorBoard, with throughput counters.

The reference's observability is a per-step print (train.py:157) and a dead
tensorboard pin (SURVEY.md §5.5). These are the BASELINE metrics
(imgs/sec/chip) so they are first-class here.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Optional

import jax


class MetricsLogger:
    # anomalies/rollbacks/restarts: cumulative fault-tolerance counters
    # (guard skips, checkpoint rollbacks, supervised restarts —
    # train/guard.py + trainer + train/supervisor.py) — in the main CSV,
    # not a side channel, so a recovered-from fault is visible in the same
    # place the loss curve is (no silent recovery).
    HEADER = ["step", "loss", "grad_norm", "lr", "steps_per_sec",
              "imgs_per_sec_per_chip", "anomalies", "rollbacks", "restarts"]

    def __init__(self, results_folder: str, use_tensorboard: bool = False):
        os.makedirs(results_folder, exist_ok=True)
        self.csv_path = os.path.join(results_folder, "metrics.csv")
        # Resumed run with a DIFFERENT schema (older build): rotate the old
        # file aside rather than appending misaligned rows under its header.
        if os.path.exists(self.csv_path) and os.path.getsize(self.csv_path):
            with open(self.csv_path) as fh:
                old_header = fh.readline().strip().split(",")
            if old_header != self.HEADER:
                os.replace(self.csv_path, self.csv_path + ".old")
        self._csv_file = open(self.csv_path, "a", newline="")
        self._csv = csv.writer(self._csv_file)
        if self._csv_file.tell() == 0:
            self._csv.writerow(self.HEADER)
        self._tb = None
        if use_tensorboard:
            try:
                import tensorflow as tf

                self._tb = tf.summary.create_file_writer(
                    os.path.join(results_folder, "tb"))
            except Exception:
                self._tb = None
        self._last_time: Optional[float] = None
        self._last_step: Optional[int] = None

    def log(self, step: int, metrics: dict, batch_size: int) -> dict:
        now = time.perf_counter()
        steps_per_sec = 0.0
        if self._last_time is not None and step > self._last_step:
            steps_per_sec = (step - self._last_step) / (now - self._last_time)
        self._last_time = now
        self._last_step = step
        imgs_per_sec_per_chip = (
            steps_per_sec * batch_size / max(1, jax.device_count()))

        loss = float(metrics.get("loss", float("nan")))
        gnorm = float(metrics.get("grad_norm", float("nan")))
        lr = float(metrics.get("lr", float("nan")))
        anomalies = int(metrics.get("anomalies", 0))
        rollbacks = int(metrics.get("rollbacks", 0))
        restarts = int(metrics.get("restarts", 0))
        self._csv.writerow([step, loss, gnorm, f"{lr:.3e}",
                            f"{steps_per_sec:.3f}",
                            f"{imgs_per_sec_per_chip:.3f}",
                            anomalies, rollbacks, restarts])
        self._csv_file.flush()
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                tf.summary.scalar("loss", loss, step=step)
                tf.summary.scalar("grad_norm", gnorm, step=step)
                tf.summary.scalar("lr", lr, step=step)
                tf.summary.scalar("imgs_per_sec_per_chip",
                                  imgs_per_sec_per_chip, step=step)
        return {
            "loss": loss,
            "grad_norm": gnorm,
            "steps_per_sec": steps_per_sec,
            "imgs_per_sec_per_chip": imgs_per_sec_per_chip,
        }

    def log_eval(self, step: int, metrics: dict) -> None:
        """Append eval-quality metrics (PSNR/SSIM/…) to eval.csv + TB."""
        path = os.path.join(os.path.dirname(self.csv_path), "eval.csv")
        header = ["step"] + sorted(metrics)
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        if not new:
            # Resumed run logging a different metric set (e.g. an older
            # build without cond_sens): rotate rather than misalign rows.
            with open(path) as fh:
                if fh.readline().strip().split(",") != header:
                    os.replace(path, path + ".old")
                    new = True
        with open(path, "a", newline="") as fh:
            w = csv.writer(fh)
            if new:
                w.writerow(header)
            w.writerow([step] + [f"{float(metrics[k]):.5f}"
                                 for k in sorted(metrics)])
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                for k in sorted(metrics):
                    tf.summary.scalar(f"eval/{k}", float(metrics[k]),
                                      step=step)

    def log_event(self, step: int, kind: str, detail: str = "") -> None:
        """Append a fault-tolerance event (anomaly, rollback, restore
        fallback, save failure) to events.csv and echo it to the run log.
        Rare by construction — opened per call, no handle to leak."""
        path = os.path.join(os.path.dirname(self.csv_path), "events.csv")
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        with open(path, "a", newline="") as fh:
            w = csv.writer(fh)
            if new:
                w.writerow(["step", "event", "detail"])
            w.writerow([step, kind, detail])
        print(f"[fault] step {step}: {kind}"
              + (f" ({detail})" if detail else ""), flush=True)

    def close(self) -> None:
        self._csv_file.close()
