"""Metrics: stdout + CSV + optional TensorBoard, with throughput counters.

The reference's observability is a per-step print (train.py:157) and a dead
tensorboard pin (SURVEY.md §5.5). These are the BASELINE metrics
(imgs/sec/chip) so they are first-class here.

All file writes route through the obs.EventBus (obs/bus.py) — the single
write path for the run's CSV/JSONL telemetry, so this module carries the
schema and the derived-metric math, not file handling.
"""

from __future__ import annotations

import csv
import math
import os
import time
from typing import Optional

import jax

from novel_view_synthesis_3d_tpu.obs.bus import EventBus


class MetricsLogger:
    # anomalies/rollbacks/restarts: cumulative fault-tolerance counters
    # (guard skips, checkpoint rollbacks, supervised restarts —
    # train/guard.py + trainer + train/supervisor.py) — in the main CSV,
    # not a side channel, so a recovered-from fault is visible in the same
    # place the loss curve is (no silent recovery).
    # device_mem_gb/mfu: utilization gauges (obs/devmon.py) — peak device
    # memory high-water and model-FLOPs-utilization, so "is HBM creeping"
    # and "how fed is the MXU" sit next to the loss curve too. NaN when
    # the backend reports no stats / the chip's peak is unknown.
    HEADER = ["step", "loss", "grad_norm", "lr", "steps_per_sec",
              "imgs_per_sec_per_chip", "anomalies", "rollbacks", "restarts",
              "device_mem_gb", "mfu"]

    def __init__(self, results_folder: str, use_tensorboard: bool = False,
                 bus: Optional[EventBus] = None):
        os.makedirs(results_folder, exist_ok=True)
        self.results_folder = results_folder
        # Standalone use (tests, tools) builds its own bus; the Trainer
        # hands in the run's shared one so every sink has one policy.
        self.bus = bus if bus is not None else EventBus(results_folder,
                                                        jsonl=False)
        self._owns_bus = bus is None
        self._tb = None
        if use_tensorboard:
            try:
                import tensorflow as tf

                self._tb = tf.summary.create_file_writer(
                    os.path.join(results_folder, "tb"))
            except Exception:
                self._tb = None
        self._last_time: Optional[float] = None
        self._last_step: Optional[int] = None

    def log(self, step: int, metrics: dict, batch_size: int,
            extra: Optional[dict] = None) -> dict:
        now = time.perf_counter()
        steps_per_sec = 0.0
        if self._last_time is not None and step > self._last_step:
            steps_per_sec = (step - self._last_step) / (now - self._last_time)
        self._last_time = now
        self._last_step = step
        imgs_per_sec_per_chip = (
            steps_per_sec * batch_size / max(1, jax.device_count()))

        loss = float(metrics.get("loss", float("nan")))
        gnorm = float(metrics.get("grad_norm", float("nan")))
        lr = float(metrics.get("lr", float("nan")))
        anomalies = int(metrics.get("anomalies", 0))
        rollbacks = int(metrics.get("rollbacks", 0))
        restarts = int(metrics.get("restarts", 0))
        device_mem_gb = float(metrics.get("device_mem_gb", float("nan")))
        mfu = float(metrics.get("mfu", float("nan")))
        header = list(self.HEADER)
        row = [
            step, loss, gnorm, f"{lr:.3e}",
            f"{steps_per_sec:.3f}",
            f"{imgs_per_sec_per_chip:.3f}",
            anomalies, rollbacks, restarts,
            "" if math.isnan(device_mem_gb) else f"{device_mem_gb:.3f}",
            "" if math.isnan(mfu) else f"{mfu:.4f}"]
        if extra:
            # Run-specific trailing columns (per-corpus loss attribution,
            # data/corpus.py): sorted so the schema is deterministic; the
            # bus's header-rotation handles a resume with a different
            # corpus set.
            for k in sorted(extra):
                header.append(k)
                v = float(extra[k])
                row.append("" if math.isnan(v) else f"{v:.6f}")
        self.bus.metrics_row(header, row)
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                tf.summary.scalar("loss", loss, step=step)
                tf.summary.scalar("grad_norm", gnorm, step=step)
                tf.summary.scalar("lr", lr, step=step)
                tf.summary.scalar("imgs_per_sec_per_chip",
                                  imgs_per_sec_per_chip, step=step)
        return {
            "loss": loss,
            "grad_norm": gnorm,
            "steps_per_sec": steps_per_sec,
            "imgs_per_sec_per_chip": imgs_per_sec_per_chip,
        }

    def log_eval(self, step: int, metrics: dict) -> None:
        """Append eval-quality metrics (PSNR/SSIM/…) to eval.csv + TB."""
        path = os.path.join(self.results_folder, "eval.csv")
        header = ["step"] + sorted(metrics)
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        if not new:
            # Resumed run logging a different metric set (e.g. an older
            # build without cond_sens): rotate rather than misalign rows.
            with open(path) as fh:
                if fh.readline().strip().split(",") != header:
                    os.replace(path, path + ".old")
                    new = True
        with open(path, "a", newline="") as fh:
            w = csv.writer(fh)
            if new:
                w.writerow(header)
            w.writerow([step] + [f"{float(metrics[k]):.5f}"
                                 for k in sorted(metrics)])
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                for k in sorted(metrics):
                    tf.summary.scalar(f"eval/{k}", float(metrics[k]),
                                      step=step)

    def log_event(self, step: int, kind: str, detail: str = "") -> None:
        """Append a fault-tolerance event (anomaly, rollback, restore
        fallback, save failure) to the events log and echo it to the run
        log. Rare by construction."""
        self.bus.event(step, kind, detail, echo="[fault]")

    def close(self) -> None:
        if self._owns_bus:
            self.bus.close()
