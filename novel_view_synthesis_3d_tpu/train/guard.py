"""Step anomaly guard: detect bad steps inside the jitted train step.

First rung of the fault-tolerance ladder (guard → rollback → checkpoint
fallback, docs/DESIGN.md "Fault tolerance"): a single non-finite loss or
gradient must not poison the parameters — once NaN enters Adam's moments
every later step is NaN and the run is dead (the reference has no handling
at all, SURVEY.md §5.3). The guard:

  - flags a step whose loss or global grad norm is non-finite, or (with
    `train.loss_spike_factor` > 0) whose loss exceeds factor × a running
    EMA of accepted losses;
  - skips the optimizer/EMA update for flagged steps via `jax.lax.cond`
    (params bit-identical through the step), which composes with the
    `steps_per_dispatch` fused scan because all guard state lives in the
    TrainState carry;
  - counts consecutive strikes; the Trainer rolls back to the last good
    checkpoint when they exceed `train.max_anomaly_strikes` (bounded by
    `train.max_rollbacks`, then abort).

Everything here is scalar bookkeeping — zero cost next to the step.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

# EMA decay for the accepted-loss baseline the spike detector compares
# against. 0.9 ≈ a ~10-step window: long enough to smooth batch noise,
# short enough to track a fast-falling early loss curve.
LOSS_EMA_DECAY = 0.9


@flax.struct.dataclass
class GuardState:
    """Anomaly-guard bookkeeping; rides in the TrainState (scan carry +
    checkpoint), all scalars."""

    strikes: jnp.ndarray    # () int32 — consecutive anomalous steps
    anomalies: jnp.ndarray  # () int32 — cumulative anomalous steps
    loss_ema: jnp.ndarray   # () float32 — EMA of ACCEPTED losses
    good_steps: jnp.ndarray  # () int32 — accepted steps (EMA warmup gate)


def init_guard_state() -> GuardState:
    return GuardState(
        strikes=jnp.zeros((), jnp.int32),
        anomalies=jnp.zeros((), jnp.int32),
        loss_ema=jnp.zeros((), jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
    )


def detect_anomaly(loss: jnp.ndarray, grad_norm: jnp.ndarray,
                   guard: GuardState, spike_factor: float) -> jnp.ndarray:
    """Traced () bool: is this step anomalous?

    Non-finite loss/grad always flags. The spike test (`spike_factor` > 0,
    off by default — it changes clean-run behavior only when it fires)
    additionally flags loss > factor × EMA, gated on at least one accepted
    step so the unseeded EMA can never flag step 0.
    """
    bad = jnp.logical_not(
        jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(grad_norm)))
    if spike_factor > 0:
        spike = jnp.logical_and(
            guard.good_steps > 0,
            loss > jnp.float32(spike_factor) * guard.loss_ema)
        bad = jnp.logical_or(bad, spike)
    return bad


def update_guard(guard: GuardState, loss: jnp.ndarray,
                 anomalous: jnp.ndarray) -> GuardState:
    """Advance the guard: strikes reset on any accepted step; the loss EMA
    folds in accepted losses only (an anomalous loss must not drag the
    baseline it is judged against)."""
    anomalous_i = anomalous.astype(jnp.int32)
    seeded = guard.good_steps > 0
    folded = jnp.where(
        seeded,
        LOSS_EMA_DECAY * guard.loss_ema
        + (1.0 - LOSS_EMA_DECAY) * loss.astype(jnp.float32),
        loss.astype(jnp.float32))
    return GuardState(
        strikes=jnp.where(anomalous, guard.strikes + 1, 0).astype(jnp.int32),
        anomalies=guard.anomalies + anomalous_i,
        loss_ema=jnp.where(anomalous, guard.loss_ema, folded),
        good_steps=guard.good_steps + (1 - anomalous_i),
    )
