"""The jitted data-parallel train step.

TPU-native redesign of the reference hot path (train.py:49-76 + the CPU-side
noising at data_loader.py:92-110):

  - forward noising (t, ε, z_t, logsnr) happens ON DEVICE inside the jit —
    the data pipeline ships clean image pairs only. This both removes the
    reference's float64 `z` / list-typed collate bug (SURVEY.md §3.4) and
    keeps host→device traffic to 2 images per sample;
  - fresh per-step PRNG keys via fold_in(state.rng, state.step) — dropout,
    CFG mask, t and ε all differ every step (reference baked them at trace
    time, SURVEY.md §3.1);
  - batch arrives SHARDED over the mesh 'data' axis; the mean loss makes XLA
    emit the gradient all-reduce over ICI (the psum the reference never had);
  - state is donated (in-place buffer reuse in HBM).

Batch contract (clean, from data/pipeline.py):
  x (B,[Fc],H,W,3) cond view(s) · target (B,H,W,3) clean target view ·
  R1,t1 cond pose(s) · R2,t2 target pose · K intrinsics.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax

from novel_view_synthesis_3d_tpu.config import Config
from novel_view_synthesis_3d_tpu.diffusion.schedules import DiffusionSchedule
from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib
from novel_view_synthesis_3d_tpu.parallel import zero as zero_lib
from novel_view_synthesis_3d_tpu.parallel.pipeline import MODEL_KEYS
from novel_view_synthesis_3d_tpu.train import guard as guard_lib
from novel_view_synthesis_3d_tpu.train.state import TrainState, make_optimizer
from novel_view_synthesis_3d_tpu.utils import faultinject


def effective_accum_steps(batch_size: int, data_shards: int,
                          requested: int) -> int:
    """Largest usable accumulation ≤ `requested` for this batch and mesh.

    Accumulation only helps while each micro-batch can stay sharded over
    the 'data' axis (micro % data_shards == 0) — otherwise GSPMD replicates
    the batch inside the scan and memory goes UP. Per-chip memory already
    scales as 1/data_shards, so the accumulation a config requests for one
    chip is naturally satisfied by the sharding on many. Hence: the largest
    divisor of the per-shard batch that is ≤ `requested`.
    """
    if batch_size % max(1, data_shards) != 0:
        raise ValueError(
            f"global batch {batch_size} not divisible by data-axis size "
            f"{data_shards}")
    per_shard = batch_size // max(1, data_shards)
    requested = max(1, requested)
    for accum in range(min(requested, per_shard), 0, -1):
        if per_shard % accum == 0:
            return accum
    return 1


def compute_loss(eps_pred: jnp.ndarray, noise: jnp.ndarray, kind: str,
                 weight: jnp.ndarray | None = None) -> jnp.ndarray:
    if kind == "mse":
        if weight is None:
            return jnp.mean(jnp.square(eps_pred - noise))
        # Per-sample MSE over pixel dims, then weighted batch mean.
        per_sample = jnp.mean(
            jnp.square(eps_pred - noise).reshape(eps_pred.shape[0], -1),
            axis=-1)
        return jnp.mean(weight * per_sample)
    if kind == "frobenius":
        if weight is not None:
            raise ValueError("loss weighting requires kind='mse' — the "
                             "whole-tensor norm has no per-sample terms")
        # Reference parity (train.py:67): L2 norm of the whole flattened
        # residual tensor (jnp.mean over a scalar is the identity).
        return jnp.linalg.norm((eps_pred - noise).reshape(-1))
    raise ValueError(f"unknown loss {kind!r}")


def min_snr_weight(snr: jnp.ndarray, gamma: float,
                   objective: str) -> jnp.ndarray:
    """Min-SNR-γ per-sample loss weight (Hang et al. 2023, arXiv 2303.09556).

    The paper weights the x₀-space loss by min(SNR, γ); expressed in each
    prediction space that becomes min(SNR,γ)/SNR for ε-prediction and
    min(SNR,γ)/(SNR+1) for v-prediction.
    """
    clipped = jnp.minimum(snr, gamma)
    if objective == "eps":
        return clipped / snr
    if objective == "x0":
        return clipped
    if objective == "v":
        return clipped / (snr + 1.0)
    raise ValueError(f"unknown objective {objective!r}")


def make_train_step(config: Config, model, schedule: DiffusionSchedule,
                    mesh, state_sharding=None
                    ) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Build the jitted train step bound to a mesh.

    Returns step(state, batch) -> (state, metrics); `batch` must already be
    device-put with `parallel.mesh.shard_batch`. `state_sharding` (default
    fully replicated) carries the FSDP layout when train.fsdp is on: with
    params/opt-state sharded over 'data', XLA emits the all-gather before
    use and reduce-scatters the gradient — ZeRO-3 from annotations alone.
    """
    tcfg = config.train
    objective = config.diffusion.objective
    if objective not in ("eps", "x0", "v"):
        raise ValueError(f"unknown objective {objective!r}")
    data_shards = mesh_lib.num_data_shards(mesh)
    accum = effective_accum_steps(tcfg.batch_size, data_shards,
                                  tcfg.grad_accum_steps)
    # (grad_accum_steps > 1 with loss='frobenius' is rejected by
    # Config.validate() at startup — the whole-tensor norm has no
    # per-micro-batch decomposition.)
    if tcfg.loss_weighting not in ("none", "min_snr"):
        raise ValueError(
            f"unknown loss_weighting {tcfg.loss_weighting!r}")
    if tcfg.loss_weighting != "none" and tcfg.loss != "mse":
        raise ValueError("loss_weighting requires loss='mse'")
    # Composable update sharding (train.update_sharding): 'zero' runs the
    # Adam+EMA update on 1/data_shards shards (parallel/zero.py). Its inner
    # chain swaps the global-norm clip for identity (a shard-local norm
    # would be wrong); the clip then runs here on the FULL gradient before
    # the sharded region — same math, same order as the replicated chain.
    zero = tcfg.update_sharding == "zero"
    stages = config.mesh.stages
    tx, lr_schedule = make_optimizer(tcfg, return_schedule=True,
                                     shard_local=zero)
    full_clip = (optax.clip_by_global_norm(tcfg.grad_clip)
                 if zero and tcfg.grad_clip > 0 else None)
    # Corpus mixer (data/corpus.py): per-corpus loss attribution. The mix
    # spec fixes the number of corpora at TRACE time (static C), so the
    # segment_sum below compiles to a fixed-shape (C,) reduction — no
    # dynamic shapes, no recompiles as corpus proportions drift per batch.
    if config.data.mix:
        from novel_view_synthesis_3d_tpu.data.corpus import parse_mix_spec
        corpus_count = len(parse_mix_spec(config.data.mix))
    else:
        corpus_count = 0
    if corpus_count and tcfg.loss != "mse":
        raise ValueError(
            "data.mix per-corpus loss attribution requires train.loss="
            "'mse' — the whole-tensor frobenius norm has no per-sample "
            "terms to attribute to a corpus")
    if stages > 1 and (corpus_count or config.model.num_classes > 0):
        raise ValueError(
            "data.mix / model.num_classes are not supported with "
            "mesh.stages > 1 — the pipeline-staged step streams only "
            "MODEL_KEYS through its stage shard_map; run the corpus "
            "mixer on the sequential (stages=1) step")
    if stages > 1:
        from novel_view_synthesis_3d_tpu.parallel import (
            pipeline as pipeline_lib)
    # Fault injection (utils/faultinject.py): read at TRACE time — a clean
    # build compiles no injection ops at all.
    fi_nan_steps = faultinject.nan_loss_steps()
    fi_nan_group = faultinject.nan_grad_group()
    # Numerics observatory (obs/numerics.py): per-layer-group read-only
    # reductions grouped by the pipeline op list, UNCONDITIONALLY traced
    # into the step (see finish_step). train.numerics.enabled only gates
    # the host-side consumer, which is what makes flipping it bitwise
    # identical with zero recompiles: earlier Python-gated variants
    # changed XLA's fusion around the optimizer update (~1-ulp param
    # drift on CPU even behind an optimization_barrier).
    from novel_view_synthesis_3d_tpu.models.xunet import op_groups
    from novel_view_synthesis_3d_tpu.obs import numerics as numerics_lib
    layer_groups = op_groups(config.model)

    def derive_fields(batch, k_t, k_noise, k_mask, B, rows):
        """Diffusion training fields for `rows` of a B-row batch.

        Randoms (t, noise, cond_mask) are drawn FULL-batch from the given
        keys and then sliced to `rows` — so the per-row values are the
        same no matter which shard computes them, which is what lets the
        pipeline path rerun this inside its shard_map (parallel/pipeline.py
        explains why it must). `rows=None` keeps the whole batch.
        """
        target = batch["target"]
        t = jax.random.randint(k_t, (B,), 0, schedule.num_timesteps)
        noise = jax.random.normal(
            k_noise, (B,) + target.shape[1:], dtype=target.dtype)
        cond_mask = (
            jax.random.uniform(k_mask, (B,)) >= tcfg.cond_drop_prob
        ).astype(jnp.float32)
        if rows is not None:
            n = target.shape[0]
            t = jax.lax.dynamic_slice_in_dim(t, rows, n)
            noise = jax.lax.dynamic_slice_in_dim(noise, rows, n)
            cond_mask = jax.lax.dynamic_slice_in_dim(cond_mask, rows, n)
        z = schedule.q_sample(target, t, noise)
        logsnr = schedule.logsnr(t)

        model_batch = {
            "x": batch["x"],
            "z": z,
            "logsnr": logsnr,
            "R1": batch["R1"],
            "t1": batch["t1"],
            "R2": batch["R2"],
            "t2": batch["t2"],
            "K": batch["K"],
        }

        # Regression target per diffusion.objective: ε (reference behavior),
        # clean x₀, or v = √ᾱε − √(1−ᾱ)x₀ (Salimans & Ho 2022).
        if objective == "eps":
            regression_target = noise
        elif objective == "x0":
            regression_target = target
        else:  # 'v'
            regression_target = schedule.v_from_eps_x0(t, noise, target)

        full = dict(model_batch, cond_mask=cond_mask,
                    regression_target=regression_target)
        if tcfg.loss_weighting == "min_snr":
            acp = jnp.take(schedule.alphas_cumprod, t, axis=0)
            snr = acp / (1.0 - acp)
            full["loss_weight"] = min_snr_weight(
                snr, tcfg.min_snr_gamma, objective)
        # Mixed-corpus batches (data/corpus.py): category feeds the
        # conditioning table (only when the model grew one), corpus_id
        # feeds loss attribution (never the model).
        if config.model.num_classes > 0 and "category" in batch:
            full["category"] = batch["category"]
        if corpus_count and "corpus_id" in batch:
            full["corpus_id"] = batch["corpus_id"]
        return full

    def train_step(state: TrainState, batch: dict) -> Tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        k_t, k_noise, k_mask, k_dropout = jax.random.split(step_rng, 4)

        target = batch["target"]
        B = target.shape[0]

        if stages > 1:
            # Pipeline-staged forward/backward (parallel/pipeline.py):
            # same per-row t/noise/cond_mask and dropout keys as the
            # accumulation path below, but the micro-batches stream
            # through S model stages in a GPipe fill/drain schedule
            # instead of a sequential scan — equivalent loss/grads up to
            # f32 reduction order (tests/test_pipeline.py). The field
            # derivation reruns inside the shard_map, per data shard;
            # see parallel/pipeline.py for why it cannot stay out here.
            def derive_local(local_batch, rng, data_index):
                k_t_, k_noise_, k_mask_, k_drop_ = jax.random.split(rng, 4)
                rows = data_index * local_batch["target"].shape[0]
                full = derive_fields(local_batch, k_t_, k_noise_, k_mask_,
                                     B, rows)
                micro = jax.tree.map(
                    lambda a: a.reshape((accum, a.shape[0] // accum)
                                        + a.shape[1:]), full)
                return micro, jax.random.split(k_drop_, accum)

            def micro_loss_of(pred, mb):
                return compute_loss(pred, mb["regression_target"],
                                    tcfg.loss, weight=mb.get("loss_weight"))

            loss, grads = pipeline_lib.value_and_grad_pipelined(
                model, mesh, stages, state.params, batch, step_rng,
                accum, derive_local, micro_loss_of)
            return finish_step(state, loss, grads)

        full = derive_fields(batch, k_t, k_noise, k_mask, B, None)

        def model_keys_of(mb):
            # corpus_id/regression_target/... never reach the model;
            # category does, iff the batch carries it (the model grew a
            # conditioning table — derive_fields gates on num_classes).
            return (MODEL_KEYS + ("category",) if "category" in mb
                    else MODEL_KEYS)

        def micro_loss(params, mb):
            pred = model.apply(
                {"params": params},
                {k: mb[k] for k in model_keys_of(mb)},
                cond_mask=mb["cond_mask"], train=True,
                rngs={"dropout": mb["dropout_key"]})
            return compute_loss(pred, mb["regression_target"], tcfg.loss,
                                weight=mb.get("loss_weight"))

        def micro_loss_attributed(params, mb):
            """micro_loss + per-corpus (loss_sum, count) aux — the same
            per-sample terms the scalar mean reduces, bucketed by
            corpus_id with a static-C segment_sum."""
            pred = model.apply(
                {"params": params},
                {k: mb[k] for k in model_keys_of(mb)},
                cond_mask=mb["cond_mask"], train=True,
                rngs={"dropout": mb["dropout_key"]})
            per_sample = jnp.mean(
                jnp.square(pred - mb["regression_target"]).reshape(
                    pred.shape[0], -1), axis=-1)
            w = mb.get("loss_weight")
            if w is not None:
                per_sample = w * per_sample
            sums = jax.ops.segment_sum(
                per_sample, mb["corpus_id"], num_segments=corpus_count)
            counts = jax.ops.segment_sum(
                jnp.ones_like(per_sample), mb["corpus_id"],
                num_segments=corpus_count)
            return jnp.mean(per_sample), (sums, counts)

        attributed = corpus_count > 0 and "corpus_id" in full
        corpus_aux = None
        if accum == 1:
            if attributed:
                (loss, corpus_aux), grads = jax.value_and_grad(
                    micro_loss_attributed, has_aux=True)(
                        state.params, dict(full, dropout_key=k_dropout))
            else:
                loss, grads = jax.value_and_grad(micro_loss)(
                    state.params, dict(full, dropout_key=k_dropout))
        else:
            # lax.scan over micro-batches: activations live one slice at a
            # time; gradients accumulate in a params-shaped f32 tree. Equal
            # slice sizes make mean-of-means == full-batch mean.
            micro = jax.tree.map(
                lambda a: a.reshape((accum, a.shape[0] // accum)
                                    + a.shape[1:]), full)
            micro["dropout_key"] = jax.random.split(k_dropout, accum)

            if attributed:
                def body(carry, mb):
                    loss_sum, grad_sum, (s_sum, c_sum) = carry
                    (l, (s, c)), g = jax.value_and_grad(
                        micro_loss_attributed, has_aux=True)(
                            state.params, mb)
                    return (loss_sum + l,
                            jax.tree.map(
                                lambda a, x: a + x.astype(jnp.float32),
                                grad_sum, g),
                            (s_sum + s, c_sum + c)), None
            else:
                def body(carry, mb):
                    loss_sum, grad_sum, aux = carry
                    l, g = jax.value_and_grad(micro_loss)(state.params, mb)
                    return (loss_sum + l,
                            jax.tree.map(
                                lambda s, x: s + x.astype(jnp.float32),
                                grad_sum, g),
                            aux), None

            # Accumulate in f32 regardless of param_dtype — bf16 sums would
            # swallow small per-micro-batch contributions — then cast back.
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            zero_aux = (jnp.zeros((corpus_count,), jnp.float32),
                        jnp.zeros((corpus_count,), jnp.float32))
            (loss, grads, corpus_aux), _ = jax.lax.scan(
                body, (0.0, zero_grads, zero_aux), micro)
            if not attributed:
                corpus_aux = None
            loss = loss / accum
            grads = jax.tree.map(
                lambda g, p: (g / accum).astype(p.dtype),
                grads, state.params)
        return finish_step(state, loss, grads, corpus_aux)

    def finish_step(state: TrainState, loss, grads, corpus_aux=None):
        """Everything after the forward/backward: fault injection, clip,
        (possibly ZeRO-sharded) update, anomaly guard, metrics. Shared by
        the sequential and pipeline-staged paths."""
        if fi_nan_steps:
            # Injected fault: poison loss AND gradients at the armed steps,
            # exactly what a numerically-blown forward/backward produces.
            # NVS3D_FI_NAN_GRAD_GROUP narrows the grad poisoning to one
            # layer group — the NaN-provenance drill.
            bad_step = jnp.isin(state.step,
                                jnp.asarray(fi_nan_steps, jnp.int32))
            loss = jnp.where(bad_step, jnp.float32(jnp.nan), loss)
            if fi_nan_group:
                poison_keys = {name for label, names in layer_groups
                               if label == fi_nan_group for name in names}
                if not poison_keys:
                    raise ValueError(
                        f"NVS3D_FI_NAN_GRAD_GROUP={fi_nan_group!r} matches "
                        "no layer group; labels: "
                        f"{[label for label, _ in layer_groups]}")

                def poison(path, g):
                    top = getattr(path[0], "key", None)
                    if top in poison_keys:
                        return jnp.where(bad_step,
                                         jnp.asarray(jnp.nan, g.dtype), g)
                    return g

                grads = jax.tree_util.tree_map_with_path(poison, grads)
            else:
                grads = jax.tree.map(
                    lambda g: jnp.where(bad_step,
                                        jnp.asarray(jnp.nan, g.dtype),
                                        g), grads)

        grad_norm = optax.global_norm(grads)

        def apply_update(_):
            if zero:
                # ZeRO path: clip on the full gradient (exactly what the
                # replicated chain's first link does), then the sharded
                # Adam+EMA update — state.opt_state/ema_params are in the
                # packed (N, c) layout (parallel/zero.py).
                g = grads
                if full_clip is not None:
                    g, _ = full_clip.update(g, full_clip.init(None))
                return zero_lib.sharded_update(
                    mesh, tx, g, state.params, state.opt_state,
                    state.ema_params, tcfg.ema_decay)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            ema_params = state.ema_params
            if ema_params is not None:
                d = tcfg.ema_decay
                ema_params = jax.tree.map(
                    lambda e, p: e * d + p.astype(e.dtype) * (1.0 - d),
                    ema_params, params)
            return params, opt_state, ema_params

        new_guard = None
        if state.guard is not None:
            # Anomaly guard (train/guard.py): an anomalous step keeps
            # params/opt-state/EMA bit-identical (lax.cond skips the whole
            # update) and advances only the strike counters; step still
            # increments so the fold_in-derived keys move on.
            anomalous = guard_lib.detect_anomaly(
                loss, grad_norm, state.guard, tcfg.loss_spike_factor)
            params, opt_state, ema_params = jax.lax.cond(
                anomalous,
                lambda _: (state.params, state.opt_state, state.ema_params),
                apply_update, None)
            new_guard = guard_lib.update_guard(state.guard, loss, anomalous)
        else:
            params, opt_state, ema_params = apply_update(None)

        new_state = TrainState(
            step=state.step + 1,
            params=params,
            opt_state=opt_state,
            rng=state.rng,
            ema_params=ema_params,
            guard=new_guard,
        )
        lr = lr_schedule(state.step) if callable(lr_schedule) else lr_schedule
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": jnp.asarray(lr, jnp.float32),
        }
        if new_guard is not None:
            metrics["anomalies"] = new_guard.anomalies.astype(jnp.float32)
            metrics["strikes"] = new_guard.strikes.astype(jnp.float32)
        if corpus_aux is not None:
            # (C,) per-corpus loss sums and sample counts; the trainer's
            # host side divides at log time (mean of sums / mean of counts
            # across a fused window reduces to the same ratio).
            metrics["corpus_loss_sum"] = corpus_aux[0]
            metrics["corpus_count"] = corpus_aux[1]
        # Per-layer-group numerics (obs/numerics.py): read-only reductions
        # over pre-update params, the gradient, and the post-update params
        # (guard-skipped steps read update_ratio 0). ALWAYS part of the
        # program — train.numerics.enabled gates only the host-side
        # consumer (NumericsMonitor), so flipping it is bitwise identical
        # and recompile-free by construction: there is exactly one step
        # program either way. The (G,) outputs cost two elementwise passes
        # over params+grads, noise next to the fwd/bwd and Adam's own
        # tree passes.
        metrics["numerics"] = numerics_lib.group_stats(
            numerics_lib.group_assignment(
                layer_groups, list(state.params.keys())),
            len(layer_groups),
            grads=grads, params=state.params, new_params=params)
        return new_state, metrics

    repl = mesh_lib.replicated(mesh)
    if state_sharding is None:
        state_sharding = repl
    if tcfg.steps_per_dispatch <= 1:
        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(state_sharding, mesh_lib.batch_sharding(mesh)),
            out_shardings=(state_sharding, repl),
        )

    # Fused multi-step dispatch (train.steps_per_dispatch = K > 1): scan
    # the SAME step body over a (K, B, ...) stack of fresh batches — one
    # XLA program per K steps. Semantics are identical to K single
    # dispatches (state.step advances inside the scan, so fold_in-derived
    # noise/dropout/CFG keys match the sequential run exactly); what
    # disappears is K-1 host dispatch round trips, the dominant cost for
    # small models and remote-device runtimes. loss/grad_norm come back as
    # the window mean (per-step values inside the window are unobservable
    # to the logger anyway); lr is the LAST step's value — a schedule
    # position, where a window mean would misreport the logged step.
    def multi_step(state: TrainState, batches: dict):
        state, ms = jax.lax.scan(train_step, state, batches)
        out = jax.tree.map(lambda a: jnp.mean(a, axis=0), ms)
        out["lr"] = ms["lr"][-1]
        # Guard counters are cumulative/positional, not window averages:
        # the logger (and the rollback check) want the value AFTER the
        # window's last step.
        for k in ("anomalies", "strikes"):
            if k in ms:
                out[k] = ms[k][-1]
        # Numerics stats are positional like lr (last step's values),
        # EXCEPT nonfinite which takes the window max — an anomaly inside
        # a fused window must keep its provenance observable.
        if "numerics" in ms:
            out["numerics"] = {
                k: (jnp.max(v, axis=0) if k == "nonfinite" else v[-1])
                for k, v in ms["numerics"].items()}
        return state, out

    return jax.jit(
        multi_step,
        donate_argnums=(0,),
        in_shardings=(state_sharding, mesh_lib.stacked_batch_sharding(mesh)),
        out_shardings=(state_sharding, repl),
    )
