"""Positional encodings (DDPM sinusoidal + NeRF frequency encoding).

Behavior-matches /root/reference/model/xunet.py:23-44 (clean-room jnp
implementation). Dimension contract (SURVEY.md §2.2): with min_deg=0,
max_deg=15 a 3-vector encodes to 3 + 3·2·15 = 93 dims; with max_deg=8 to
3 + 3·2·8 = 51 dims; concatenated ray (origin, direction) encoding = 144.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def posenc_ddpm(timesteps: jnp.ndarray, emb_ch: int, max_time: float = 1000.0,
                dtype=jnp.float32) -> jnp.ndarray:
    """DDPM sinusoidal embedding of (continuous) timesteps → (..., emb_ch).

    Timesteps are normalized by `max_time` then scaled by the DDPM magic 1000;
    frequencies are the transformer 10000-base geometric ladder.
    """
    timesteps = timesteps * (1000.0 / max_time)
    half_dim = emb_ch // 2
    emb = np.log(10000.0) / (half_dim - 1)
    emb = jnp.exp(jnp.arange(half_dim, dtype=dtype) * -emb)
    emb = emb.reshape((1,) * timesteps.ndim + (half_dim,))
    emb = timesteps.astype(dtype)[..., None] * emb
    return jnp.concatenate([jnp.sin(emb), jnp.cos(emb)], axis=-1)


def posenc_nerf(x: jnp.ndarray, min_deg: int = 0, max_deg: int = 15) -> jnp.ndarray:
    """NeRF frequency encoding, concatenating x with sin/cos of scaled x.

    Output dim = D + D·2·(max_deg − min_deg) for input dim D. The cos half is
    computed as sin(x + π/2), matching the reference's formulation exactly.
    """
    if min_deg == max_deg:
        return x
    scales = jnp.asarray([2.0 ** i for i in range(min_deg, max_deg)], dtype=x.dtype)
    xb = jnp.reshape(x[..., None, :] * scales[:, None], x.shape[:-1] + (-1,))
    emb = jnp.sin(jnp.concatenate([xb, xb + np.pi / 2.0], axis=-1))
    return jnp.concatenate([x, emb], axis=-1)
