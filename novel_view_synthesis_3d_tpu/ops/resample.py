"""Spatial up/down-sampling for frame-stacked feature maps (B, F, H, W, C).

Behavior-matches /root/reference/model/xunet.py:14-21: 2× nearest-neighbor
upsampling via broadcast (no gather — XLA lowers this to a cheap reshape
pattern on TPU) and 2×2 average-pool downsampling.
"""

from __future__ import annotations

import jax.numpy as jnp


def nearest_neighbor_upsample(h: jnp.ndarray, k: int = 2) -> jnp.ndarray:
    """(B, F, H, W, C) → (B, F, kH, kW, C) by nearest neighbor."""
    B, F, H, W, C = h.shape
    h = h.reshape(B, F, H, 1, W, 1, C)
    h = jnp.broadcast_to(h, (B, F, H, k, W, k, C))
    return h.reshape(B, F, H * k, W * k, C)


def avgpool_downsample(h: jnp.ndarray, k: int = 2) -> jnp.ndarray:
    """(B, F, H, W, C) → (B, F, H/k, W/k, C) by k×k mean pooling.

    Implemented as a reshape + mean (not a conv): maps to a pure VPU
    reduction on TPU with no MXU round-trip.
    """
    B, F, H, W, C = h.shape
    h = h.reshape(B, F, H // k, k, W // k, k, C)
    return h.mean(axis=(3, 5))
