"""Shared Pallas-kernel plumbing for ops/.

Every kernel in this package carries the same off-TPU contract: the
IDENTICAL kernel code path runs through the Pallas interpreter on CPU
(so tier-1 exercises the real kernel, not a shadow implementation), the
config flag that enables it resolves 'auto' → TPU-only, and slab-sized
kernels bound their VMEM residency and fall back to XLA above it. Those
three pieces were duplicated between ops/flash_attention.py and
ops/fused_groupnorm.py; this module is their one home, and new kernels
(ops/fused_step.py) use it from day one.
"""

from __future__ import annotations

import jax

# Conservative per-program VMEM budget for a kernel's resident input
# slab(s). v5e has ~16 MB VMEM/core and a kernel typically also holds an
# f32 working copy (2-4x the slab), f32 intermediates, and the output —
# a 3 MiB input slab bounds the worst case at ~12 MiB. Strict `<` in
# fits_vmem so power-of-two slab sizes (every UNet level is one) can't
# sit on a zero-headroom boundary.
SLAB_LIMIT_BYTES = 3 * 1024 * 1024


def use_interpret() -> bool:
    """True off-TPU: run the kernel through the Pallas interpreter.

    This is how tier-1 (JAX_PLATFORMS=cpu) executes the exact same
    kernel code path the TPU compiles — correctness is proven on the
    bits that ship, not on an XLA stand-in."""
    return jax.default_backend() != "tpu"


def resolve_flag(flag, field: str) -> bool:
    """Resolve an 'auto' | bool kernel-enable config value.

    'auto' → the Pallas kernel on TPU backends (where it is compiled
    and fast), the XLA path elsewhere (interpreted Pallas on CPU is
    correct but slow). Booleans pass through; anything else is an
    error — CLI overrides arrive as raw strings, and silently coercing
    a typo like 'False' to truthy would force interpret-mode Pallas on
    CPU. `field` names the config knob in the error message."""
    if flag == "auto":
        return not use_interpret()
    if isinstance(flag, bool):
        return flag
    raise ValueError(
        f"{field} must be True, False, or 'auto'; got {flag!r}")


def fits_vmem(nbytes: int, limit: int = SLAB_LIMIT_BYTES) -> bool:
    """True if a per-program input slab of `nbytes` fits the budget."""
    return nbytes < limit
