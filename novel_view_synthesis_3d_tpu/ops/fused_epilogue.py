"""Fused GroupNorm → FiLM-modulate → SiLU block epilogue.

The ResnetBlock tail (models/layers.py) is three bandwidth-bound
elementwise stages with an HBM round-trip between each: GroupNorm reads
and writes the (B·F, H·W, C) activation, the FiLM modulation reads it
back along with the SAME-SHAPE per-pixel scale/shift tensors (3DiM's
FiLM conditioning is spatial — scale/shift are full (H, W, C) maps, not
per-channel scalars), and the swish reads the result again. This kernel
runs the whole tail as ONE pass per (B·F) grid row:

    y = silu((1 + s) · (x̂·γ + β) + t)

with the row's x/s/t slabs resident in VMEM, f32 statistics, and the
same cast-before-activation ordering as the XLA path (nn.GroupNorm
casts to the module dtype, then the modulate/activate chain runs in
that dtype) so the two paths stay numerically interchangeable.

The FiLM Dense projection that PRODUCES s/t stays in XLA — it is a
matmul the MXU already handles; the win here is the elementwise tail's
byte budget. Backward is an explicit XLA VJP (same split as
ops/fused_groupnorm.py: sampling is forward-only and gets the full
benefit; training correctness is preserved without a Pallas backward).
Off-TPU the kernel runs through the Pallas interpreter, so tier-1
exercises the identical kernel path (ops/_pallas.use_interpret).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from novel_view_synthesis_3d_tpu.ops import _pallas


def resolve_fused_epilogue(flag) -> bool:
    """Resolve a use_fused_epilogue config value ('auto' | bool);
    see ops/_pallas.resolve_flag for the shared semantics."""
    return _pallas.resolve_flag(flag, "use_fused_epilogue")


def fits_vmem(hw: int, c: int, dtype) -> bool:
    """True if one grid row's resident slabs fit the kernel budget.

    Three same-shape input slabs stay resident per program (the
    activation row plus the FiLM scale and shift rows), so the shared
    single-slab budget is applied to 3× the row size."""
    return _pallas.fits_vmem(3 * hw * c * jnp.dtype(dtype).itemsize)


def _epilogue_kernel(x_ref, g_ref, b_ref, s_ref, t_ref, y_ref, mean_ref,
                     rstd_ref, *, groups: int, eps: float):
    x = x_ref[0].astype(jnp.float32)            # (HW, C)
    hw, c = x.shape
    cg = c // groups
    xg = x.reshape(hw, groups, cg)
    mean = jnp.mean(xg, axis=(0, 2))            # (G,)
    # Two-pass variance over the VMEM-resident slab (ops/fused_groupnorm
    # rationale: no E[x²]−E[x]² cancellation, no extra HBM traffic).
    var = jnp.mean(jnp.square(xg - mean[None, :, None]), axis=(0, 2))
    rstd = jax.lax.rsqrt(var + eps)
    xhat = ((xg - mean[None, :, None]) * rstd[None, :, None]).reshape(hw, c)
    gn = xhat * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    # Cast BEFORE modulate+activate to mirror the XLA ordering:
    # nn.GroupNorm casts its output to the module dtype, then FiLM's
    # h·(1+s)+t and the swish run in that dtype.
    gn = gn.astype(y_ref.dtype)
    z = gn * (jnp.ones((), y_ref.dtype) + s_ref[0]) + t_ref[0]
    y_ref[0] = z * jax.nn.sigmoid(z)
    mean_ref[0] = mean
    rstd_ref[0] = rstd


def _forward(x, gscale, gbias, fscale, fshift, groups: int, eps: float,
             out_dtype):
    n, hw, c = x.shape
    kernel = functools.partial(_epilogue_kernel, groups=groups, eps=eps)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((c,), lambda i: (0,)),
            pl.BlockSpec((c,), lambda i: (0,)),
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, groups), lambda i: (i, 0)),
            pl.BlockSpec((1, groups), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, hw, c), out_dtype or x.dtype),
            jax.ShapeDtypeStruct((n, groups), jnp.float32),
            jax.ShapeDtypeStruct((n, groups), jnp.float32),
        ],
        interpret=_pallas.use_interpret(),
    )(x, gscale, gbias, fscale, fshift)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def fused_film_epilogue(x, gscale, gbias, fscale, fshift,
                        groups: int = 32, eps: float = 1e-6,
                        out_dtype=None):
    """silu((1+fscale)·GroupNorm(x)+fshift) over (N, H·W, C) rows in one
    HBM pass. gscale/gbias are the (C,) GroupNorm parameters;
    fscale/fshift are the per-pixel (N, H·W, C) FiLM tensors (already
    projected by the FiLM Dense, which stays in XLA)."""
    y, _, _ = _forward(x, gscale, gbias, fscale, fshift, groups, eps,
                       out_dtype)
    return y


def _fwd(x, gscale, gbias, fscale, fshift, groups, eps, out_dtype):
    y, mean, rstd = _forward(x, gscale, gbias, fscale, fshift, groups,
                             eps, out_dtype)
    return y, (x, gscale, gbias, fscale, fshift, mean, rstd)


def _bwd(groups, eps, out_dtype, res, g):
    x, gscale, gbias, fscale, fshift, mean, rstd = res
    n, hw, c = x.shape
    cg = c // groups
    xf = x.astype(jnp.float32).reshape(n, hw, groups, cg)
    xhat = ((xf - mean[:, None, :, None]) * rstd[:, None, :, None]
            ).reshape(n, hw, c)
    gamma = gscale.astype(jnp.float32)
    gn = xhat * gamma + gbias.astype(jnp.float32)
    s = fscale.astype(jnp.float32)
    z = gn * (1.0 + s) + fshift.astype(jnp.float32)
    g = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(z)
    dz = g * (sig * (1.0 + z * (1.0 - sig)))
    dfshift = dz
    dfscale = dz * gn
    dgn = dz * (1.0 + s)
    dgamma = jnp.sum(dgn * xhat, axis=(0, 1))
    dbeta = jnp.sum(dgn, axis=(0, 1))
    dxhat = (dgn * gamma).reshape(n, hw, groups, cg)
    m1 = jnp.mean(dxhat, axis=(1, 3), keepdims=True)
    xhat_g = xhat.reshape(n, hw, groups, cg)
    m2 = jnp.mean(dxhat * xhat_g, axis=(1, 3), keepdims=True)
    dx = (dxhat - m1 - xhat_g * m2) * rstd[:, None, :, None]
    return (dx.reshape(n, hw, c).astype(x.dtype),
            dgamma.astype(gscale.dtype), dbeta.astype(gbias.dtype),
            dfscale.astype(fscale.dtype), dfshift.astype(fshift.dtype))


fused_film_epilogue.defvjp(_fwd, _bwd)
