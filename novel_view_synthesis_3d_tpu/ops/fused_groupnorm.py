"""Fused GroupNorm(+swish) Pallas kernel for the HBM-bound UNet blocks.

Motivation (measured, r2): the base128 train step runs at ~83% of HBM
bandwidth and ~40% MXU — bytes, not FLOPs, bound. XLA lowers GroupNorm as
a reduce (read x) + a normalize map (read x again, write y): ≈ 2 reads +
1 write of the full activation per GN, twice per ResnetBlock
(/root/reference/model/xunet.py:63-92 has the same GN→swish and GN→FiLM
chains). This kernel keeps one sample-row's (H·W, C) slab resident in VMEM
and does stats + normalize + activation in a single pass: 1 read + 1 write
— removing ~a third of GN traffic from the step's byte budget.

Design:
  - grid = (N,) with N = B·F rows (per-frame statistics, the framework
    default; the reference-compat shared-stats path stays on XLA);
  - whole (H·W, C) slab per program; `fits_vmem` guards the slab size and
    callers fall back to XLA above it (paper256's 256²·256 top level);
  - statistics in float32 regardless of input dtype (bf16-safe);
  - forward = Pallas, backward = explicit jnp GN/swish VJP (the training
    step's backward was never the bandwidth win; sampling/eval are
    forward-only and get the full benefit).

Channel grouping matches flax.linen.GroupNorm: C is split into
(groups, C//groups) consecutive-channel blocks; eps defaults to flax's
1e-6 so the two paths are numerically interchangeable.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from novel_view_synthesis_3d_tpu.ops import _pallas

# Shared per-program VMEM slab budget (ops/_pallas.py): a 3 MiB input
# slab bounds the kernel's worst case at ~12 MiB on a ~16 MB/core part.
# base128's top level (128·128·128 bf16 = 4 MiB) falls back to XLA; its
# 64²·256 and lower levels (≤2 MiB) fuse.
_SLAB_LIMIT_BYTES = _pallas.SLAB_LIMIT_BYTES


def _use_interpret() -> bool:
    return _pallas.use_interpret()


def resolve_fused_gn(flag) -> bool:
    """Resolve a use_fused_groupnorm config value ('auto' | bool);
    see ops/_pallas.resolve_flag for the shared semantics."""
    return _pallas.resolve_flag(flag, "use_fused_groupnorm")


def fits_vmem(hw: int, c: int, dtype) -> bool:
    """True if one (H·W, C) slab fits the kernel's VMEM budget."""
    return _pallas.fits_vmem(hw * c * jnp.dtype(dtype).itemsize)


def _gn_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref,
               *, groups: int, eps: float, act: Optional[str]):
    x = x_ref[0].astype(jnp.float32)            # (HW, C)
    hw, c = x.shape
    cg = c // groups
    xg = x.reshape(hw, groups, cg)
    mean = jnp.mean(xg, axis=(0, 2))            # (G,)
    # Two-pass variance over the VMEM-resident slab: E[(x-μ)²] is free of
    # the E[x²]-E[x]² cancellation and costs no extra HBM traffic here.
    var = jnp.mean(jnp.square(xg - mean[None, :, None]), axis=(0, 2))
    rstd = jax.lax.rsqrt(var + eps)
    xhat = ((xg - mean[None, :, None]) * rstd[None, :, None]).reshape(hw, c)
    y = xhat * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    # Cast BEFORE the activation to mirror the XLA path's ordering
    # (nn.GroupNorm casts its output to the module dtype, then swish runs
    # in that dtype) — keeps the two paths interchangeable at bf16 too.
    y = y.astype(y_ref.dtype)
    if act == "swish":
        y = y * jax.nn.sigmoid(y)
    y_ref[0] = y
    mean_ref[0] = mean
    rstd_ref[0] = rstd


def _forward(x, scale, bias, groups: int, eps: float, act: Optional[str],
             out_dtype):
    n, hw, c = x.shape
    kernel = functools.partial(_gn_kernel, groups=groups, eps=eps, act=act)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((c,), lambda i: (0,)),
            pl.BlockSpec((c,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, groups), lambda i: (i, 0)),
            pl.BlockSpec((1, groups), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, hw, c), out_dtype or x.dtype),
            jax.ShapeDtypeStruct((n, groups), jnp.float32),
            jax.ShapeDtypeStruct((n, groups), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x, scale, bias)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_group_norm(x, scale, bias, groups: int = 32, eps: float = 1e-6,
                     act: Optional[str] = None, out_dtype=None):
    """GroupNorm(+optional swish) over (N, H·W, C) rows in one HBM pass.

    scale/bias are (C,) — flax GroupNorm's parameter shapes. Returns the
    normalized (activated) tensor in `out_dtype` (default x.dtype); the
    cast happens BEFORE the activation, mirroring the XLA path's
    nn.GroupNorm(dtype=out_dtype)-then-swish ordering so the two paths
    stay interchangeable even when x.dtype differs from the module dtype.
    Differentiable via an explicit XLA backward (see module docstring).
    """
    y, _, _ = _forward(x, scale, bias, groups, eps, act, out_dtype)
    return y


def _fwd(x, scale, bias, groups, eps, act, out_dtype):
    y, mean, rstd = _forward(x, scale, bias, groups, eps, act, out_dtype)
    return y, (x, scale, bias, mean, rstd)


def _bwd(groups, eps, act, out_dtype, res, g):
    x, scale, bias, mean, rstd = res
    n, hw, c = x.shape
    cg = c // groups
    xf = x.astype(jnp.float32).reshape(n, hw, groups, cg)
    xhat = ((xf - mean[:, None, :, None]) * rstd[:, None, :, None]
            ).reshape(n, hw, c)
    gamma = scale.astype(jnp.float32)
    z = xhat * gamma + bias.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if act == "swish":
        sig = jax.nn.sigmoid(z)
        dz = g * (sig * (1.0 + z * (1.0 - sig)))
    else:
        dz = g
    dgamma = jnp.sum(dz * xhat, axis=(0, 1))
    dbeta = jnp.sum(dz, axis=(0, 1))
    dxhat = (dz * gamma).reshape(n, hw, groups, cg)
    m1 = jnp.mean(dxhat, axis=(1, 3), keepdims=True)
    xhat_g = xhat.reshape(n, hw, groups, cg)
    m2 = jnp.mean(dxhat * xhat_g, axis=(1, 3), keepdims=True)
    dx = (dxhat - m1 - xhat_g * m2) * rstd[:, None, :, None]
    return (dx.reshape(n, hw, c).astype(x.dtype),
            dgamma.astype(scale.dtype), dbeta.astype(bias.dtype))


fused_group_norm.defvjp(_fwd, _bwd)
