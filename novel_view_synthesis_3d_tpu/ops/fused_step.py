"""Fused denoise-step update as a Pallas TPU kernel.

The serving hot path runs the per-step reverse-process update 4–256
times per request (PAPER.md §3; 4–8 after progressive distillation).
After the UNet forward, XLA lowers that update as ~a dozen separate
elementwise HLOs — CFG guidance combine, x̂₀ reconstruction, clipping,
the ancestral/DDIM update line, the noise add — each reading and
writing the full (B, H, W, 3) latent in HBM. On a memory-bandwidth-
bound part that is ~12 HBM round trips for arithmetic the VPU finishes
in a fraction of one (the Gemma-on-TPU serving comparison in PAPERS.md:
per-step fusion is where TPU serving wins its bandwidth budget back).

This kernel runs the whole chain in ONE pass: each grid program holds
one batch row's latent, the two CFG network outputs, and the step noise
resident in VMEM, consumes the row's schedule coefficients from the
stepper's packed (B, len(STEP_COEF_KEYS)) matrix (sample/stepper.py —
the same device-argument contract that keeps t/steps/w out of the
program identity), and writes z_{t−1} once:

  ε̂  = (1+w)·ε̂_cond − w·ε̂_uncond                      (CFG combine)
  x̂₀ = objective⁻¹(z, ε̂)  [optionally cfg-rescaled]    (reconstruction)
  x̂₀ = clip(x̂₀, ±1)                                    (clipping)
  z' = ddpm | ddim update(x̂₀, z) + 1{t>0}·σ·ε'          (update + noise)

Layout: images are flattened to (B, M, 128) lane-aligned slabs (the
update is elementwise, so the image structure is irrelevant inside the
kernel; M pads to the f32 sublane tile on hardware) and the per-row
scalars ride in a lane-padded (B, 128) row-parameter matrix. All
arithmetic is float32 in the exact operation ORDER of the unfused jnp
path (sample/ddpm.py), so off-TPU interpret mode — the same contract as
ops/flash_attention.py: tier-1 runs the identical kernel code path —
is BIT-identical to the unfused sampler at cfg_rescale=0 and within
float tolerance at cfg_rescale>0 (the masked row-std reduction sums in
a different order than jnp.std).

`sampler='dpm++'` is not expressible as a single fused step (2M needs
cross-step x̂₀ history); callers degrade it the same way the stepper
does (first-order = η=0 DDIM) or keep the unfused scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from novel_view_synthesis_3d_tpu.ops import _pallas

try:  # pltpu only imports on TPU-capable jaxlibs; interpret needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128

# Row-parameter columns: STEP_COEF_KEYS order (sample/ddpm.py), then the
# per-row guidance weight. Indices are compile-time constants baked into
# the kernel; the VALUES are device arguments — one (B, 128) transfer
# carries every scalar the step reads, so the compiled program depends
# on the batch shape only (the stepper's program-cache contract).
_COEF_COLS = {
    "logsnr": 0, "sqrt_recip_acp": 1, "sqrt_recipm1_acp": 2,
    "sqrt_acp": 3, "sqrt_1macp": 4, "pm_coef1": 5, "pm_coef2": 6,
    "post_log_var": 7, "acp": 8, "acp_prev": 9, "nonzero": 10,
}
_W_COL = len(_COEF_COLS)


def resolve_fused_step(flag) -> bool:
    """Resolve a diffusion.fused_step config value ('auto' | bool);
    see ops/_pallas.resolve_flag for the shared semantics."""
    return _pallas.resolve_flag(flag, "diffusion.fused_step")


def fits_vmem(row_elems: int) -> bool:
    """True if one row's f32 working slab fits the shared VMEM budget.

    The kernel holds FIVE row slabs (z, ε̂_cond, ε̂_uncond, noise, out)
    plus f32 intermediates; the shared 3 MiB single-slab budget
    (ops/_pallas.py) already prices the working set at ~4× the slab, so
    the guard is on one f32 slab — 256² images (768 KiB) fuse, 512²+
    fall back to the unfused jnp chain."""
    return _pallas.fits_vmem(row_elems * 4)


def unfused_reference_step(z, eps_cond, eps_uncond, noise, coefs, w, *,
                           sampler: str, objective: str, eta: float = 0.0,
                           cfg_rescale: float = 0.0,
                           clip_denoised: bool = True) -> jnp.ndarray:
    """The unfused jnp twin of the kernel: same inputs, same math, same
    operation order, left to XLA to lower as separate HLOs.

    This IS the production unfused path (sample/ddpm.py calls it when
    diffusion.fused_step is off) and the parity reference the tier-1
    tests compare the kernel against bit-for-bit — one implementation,
    so the A/B benchmarks an HLO-fusion difference, never a math one.
    """
    if sampler not in ("ddpm", "ddim"):
        raise ValueError(f"sampler must be 'ddpm' or 'ddim'; "
                         f"got {sampler!r}")
    B = z.shape[0]

    def col(name):
        c = coefs[:, _COEF_COLS[name]].astype(jnp.float32)
        return c.reshape((B,) + (1,) * (z.ndim - 1))

    w_b = jnp.broadcast_to(w, (B,)).astype(jnp.float32).reshape(
        (B,) + (1,) * (z.ndim - 1))
    guided = (1.0 + w_b) * eps_cond - w_b * eps_uncond

    def to_x0(out):
        if objective == "eps":
            return col("sqrt_recip_acp") * z - col("sqrt_recipm1_acp") * out
        if objective == "x0":
            return out
        if objective == "v":
            return col("sqrt_acp") * z - col("sqrt_1macp") * out
        raise ValueError(f"unknown objective {objective!r}")

    x0 = to_x0(guided)
    if cfg_rescale > 0.0:
        x0_c = to_x0(eps_cond)
        axes = tuple(range(1, x0.ndim))
        std_c = jnp.std(x0_c, axis=axes, keepdims=True)
        std_g = jnp.std(x0, axis=axes, keepdims=True)
        rescaled = x0 * (std_c / jnp.maximum(std_g, 1e-8))
        x0 = cfg_rescale * rescaled + (1.0 - cfg_rescale) * x0
    if clip_denoised:
        x0 = jnp.clip(x0, -1.0, 1.0)
    nonzero = col("nonzero")
    if sampler == "ddpm":
        mean = col("pm_coef1") * x0 + col("pm_coef2") * z
        return mean + nonzero * jnp.exp(
            0.5 * col("post_log_var")) * noise
    acp = col("acp")
    acp_prev = col("acp_prev")
    eps_hat = (col("sqrt_recip_acp") * z - x0) / col("sqrt_recipm1_acp")
    sigma = (eta * jnp.sqrt((1.0 - acp_prev) / (1.0 - acp))
             * jnp.sqrt(jnp.maximum(1.0 - acp / acp_prev, 0.0)))
    dir_zt = jnp.sqrt(
        jnp.maximum(1.0 - acp_prev - sigma ** 2, 0.0)) * eps_hat
    return jnp.sqrt(acp_prev) * x0 + dir_zt + nonzero * sigma * noise


def _step_kernel(z_ref, ec_ref, eu_ref, nz_ref, rp_ref, o_ref, *,
                 sampler: str, objective: str, eta: float, phi: float,
                 clip_denoised: bool, n_valid: int):
    """One batch row's fused update, entirely in VMEM.

    z/ec/eu/nz/o refs are (1, M, 128) slabs; rp_ref is the (1, 128)
    row-parameter vector (_COEF_COLS + w). `n_valid` is the true
    (unpadded) element count — static; only the cfg-rescale row-std
    reduction needs it (all other math is elementwise, and padded
    lanes are sliced off by the wrapper)."""
    rp = rp_ref[0]

    def c(name):
        return rp[_COEF_COLS[name]]

    z = z_ref[0].astype(jnp.float32)
    ec = ec_ref[0].astype(jnp.float32)
    eu = eu_ref[0].astype(jnp.float32)
    w = rp[_W_COL]
    # CFG combine — same expression as sample/ddpm._cfg_eps.
    guided = (1.0 + w) * ec - w * eu

    def to_x0(out):
        if objective == "eps":
            return c("sqrt_recip_acp") * z - c("sqrt_recipm1_acp") * out
        if objective == "x0":
            return out
        return c("sqrt_acp") * z - c("sqrt_1macp") * out  # 'v'

    x0 = to_x0(guided)
    if phi > 0.0:
        # cfg-rescale (Lin et al. 2023): match x̂₀'s row std to the
        # conditional prediction's. Masked two-pass moments over the
        # VMEM-resident slab; padded lanes contribute nothing.
        x0_c = to_x0(ec)
        m_idx = jax.lax.broadcasted_iota(jnp.int32, z.shape, 0)
        l_idx = jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
        mask = (m_idx * _LANES + l_idx) < n_valid
        inv_n = 1.0 / float(n_valid)

        def row_std(a):
            mean = jnp.sum(jnp.where(mask, a, 0.0)) * inv_n
            var = jnp.sum(jnp.where(mask, jnp.square(a - mean), 0.0)) * inv_n
            return jnp.sqrt(var)

        rescaled = x0 * (row_std(x0_c) / jnp.maximum(row_std(x0), 1e-8))
        x0 = phi * rescaled + (1.0 - phi) * x0
    if clip_denoised:
        x0 = jnp.clip(x0, -1.0, 1.0)

    nonzero = c("nonzero")
    noise = nz_ref[0].astype(jnp.float32)
    if sampler == "ddpm":
        mean = c("pm_coef1") * x0 + c("pm_coef2") * z
        z_next = mean + nonzero * jnp.exp(0.5 * c("post_log_var")) * noise
    else:  # ddim (and the dpm++ first-order fallback at eta=0)
        acp = c("acp")
        acp_prev = c("acp_prev")
        eps_hat = (c("sqrt_recip_acp") * z - x0) / c("sqrt_recipm1_acp")
        sigma = (eta * jnp.sqrt((1.0 - acp_prev) / (1.0 - acp))
                 * jnp.sqrt(jnp.maximum(1.0 - acp / acp_prev, 0.0)))
        dir_zt = jnp.sqrt(
            jnp.maximum(1.0 - acp_prev - sigma ** 2, 0.0)) * eps_hat
        z_next = (jnp.sqrt(acp_prev) * x0 + dir_zt
                  + nonzero * sigma * noise)
    o_ref[0] = z_next.astype(o_ref.dtype)


def fused_denoise_step(z: jnp.ndarray, eps_cond: jnp.ndarray,
                       eps_uncond: jnp.ndarray, noise: jnp.ndarray,
                       coefs: jnp.ndarray, w: jnp.ndarray, *,
                       sampler: str, objective: str, eta: float = 0.0,
                       cfg_rescale: float = 0.0,
                       clip_denoised: bool = True) -> jnp.ndarray:
    """z_{t−1} from one fused Pallas call over the whole ring batch.

    z / eps_cond / eps_uncond / noise: (B, H, W, C) (any (B, ...) image
    layout — the update is elementwise). `coefs` is the (B, K) per-row
    schedule-coefficient matrix in sample/ddpm.STEP_COEF_KEYS order
    (host-gathered by the stepper's ScheduleBank, or built on device
    from the schedule tables by the request sampler); `w` the (B,)
    per-row guidance weight. Returns z_{t−1} in z.dtype.
    """
    if sampler not in ("ddpm", "ddim"):
        raise ValueError(
            f"fused_denoise_step: sampler must be 'ddpm' or 'ddim' "
            f"(dpm++ 2M needs cross-step history); got {sampler!r}")
    if objective not in ("eps", "x0", "v"):
        raise ValueError(f"unknown objective {objective!r}")
    B = z.shape[0]
    L = int(np.prod(z.shape[1:]))
    interpret = _pallas.use_interpret()
    M = -(-L // _LANES)
    if not interpret:
        M = ((M + 7) // 8) * 8  # f32 sublane tile on hardware
    pad = M * _LANES - L

    def slab(a):
        a = a.reshape(B, L)
        if pad:
            a = jnp.pad(a, ((0, 0), (0, pad)))
        return a.reshape(B, M, _LANES)

    K = coefs.shape[-1]
    rp = jnp.zeros((B, _LANES), jnp.float32)
    rp = rp.at[:, :K].set(coefs.astype(jnp.float32))
    rp = rp.at[:, _W_COL].set(
        jnp.broadcast_to(w, (B,)).astype(jnp.float32))

    kernel = functools.partial(
        _step_kernel, sampler=sampler, objective=objective,
        eta=float(eta), phi=float(cfg_rescale),
        clip_denoised=bool(clip_denoised), n_valid=L)
    mem = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    out = pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, M, _LANES), lambda i: (i, 0, 0), **mem),
            pl.BlockSpec((1, M, _LANES), lambda i: (i, 0, 0), **mem),
            pl.BlockSpec((1, M, _LANES), lambda i: (i, 0, 0), **mem),
            pl.BlockSpec((1, M, _LANES), lambda i: (i, 0, 0), **mem),
            pl.BlockSpec((1, _LANES), lambda i: (i, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, M, _LANES), lambda i: (i, 0, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((B, M, _LANES), z.dtype),
        interpret=interpret,
    )(slab(z), slab(eps_cond), slab(eps_uncond), slab(noise), rp)
    return out.reshape(B, M * _LANES)[:, :L].reshape(z.shape)
