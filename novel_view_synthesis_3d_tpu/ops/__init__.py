from novel_view_synthesis_3d_tpu.ops.posenc import (  # noqa: F401
    posenc_ddpm,
    posenc_nerf,
)
from novel_view_synthesis_3d_tpu.ops.resample import (  # noqa: F401
    avgpool_downsample,
    nearest_neighbor_upsample,
)
