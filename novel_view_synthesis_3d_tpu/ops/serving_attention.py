"""Forward-only fused attention for the serving path.

ops/flash_attention.py exists for TRAINING: it carries logsumexp
residuals, a custom VJP, and two blocked backward kernels. None of that
is needed at serving time — the sampler never differentiates — so this
module is the inference twin: softmax(q·kᵀ/√D)·v as one Pallas pass per
(batch·head, query-block) grid row with NO residual outputs and no VJP
machinery (jax.custom_jvp/vjp bookkeeping costs trace time on every
step program build, and the lse output costs an HBM write per block).

Serving shapes are small — attention runs at the coarse UNet levels
({8,16,32} ⇒ L ≤ 1024 tokens; cross-frame attention at k+1 frames a few
thousand) — so one query block against the full key/value sequence fits
VMEM at every ladder config. Shapes whose resident slabs would exceed
the shared budget (ops/_pallas.SLAB_LIMIT_BYTES) fall back to the XLA
`nn.dot_product_attention` path PER SHAPE, and every decision is
recorded in a module-level coverage registry keyed by the logical
(B, Lq, Lk, heads, head_dim, dtype) shape — tools/summarize_bench.py
renders it so a serving config knows exactly which of its shapes ran
the kernel. The registry is populated at trace time (one entry per
compiled shape, like models/layers.log_once), not per step.

Off-TPU the kernel runs through the Pallas interpreter
(ops/_pallas.use_interpret) so tier-1 exercises the identical kernel
path; 'auto' resolves to TPU-only, the shared resolve_flag semantics.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from novel_view_synthesis_3d_tpu.ops import _pallas

try:  # pltpu only imports on TPU-capable jaxlibs; interpret needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30
_LANES = 128

# Coverage registry: logical shape → "kernel" | "fallback:vmem".
# Written at trace time (one entry per compiled shape), read by
# tools/summarize_bench.py and the service health snapshot.
ShapeKey = Tuple[int, int, int, int, int, str]
_coverage: Dict[ShapeKey, str] = {}
_coverage_lock = threading.Lock()


def attention_coverage() -> Dict[ShapeKey, str]:
    """Snapshot of per-shape kernel/fallback decisions made so far."""
    with _coverage_lock:
        return dict(_coverage)


def reset_attention_coverage() -> None:
    with _coverage_lock:
        _coverage.clear()


def _record(key: ShapeKey, decision: str) -> None:
    with _coverage_lock:
        _coverage[key] = decision


def resolve_serving_attention(flag) -> bool:
    """Resolve a use_serving_attention config value ('auto' | bool);
    see ops/_pallas.resolve_flag for the shared semantics."""
    return _pallas.resolve_flag(flag, "use_serving_attention")


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _serving_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float,
                    kv_len: int):
    """One query block vs. the full kv sequence, entirely in VMEM.

    q_ref (1, Bq, D) · k_ref/v_ref (1, Lk_pad, D) · o_ref (1, Bq, D).
    `kv_len` is the true (unpadded) kv length — static, so the padded-
    column mask compiles away when there is no padding. Identical math
    to flash_attention's forward, minus the lse output."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if kv_len < k.shape[0]:  # mask padded kv columns (static condition)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _slab_bytes(bq: int, lk_p: int, d_p: int, itemsize: int) -> int:
    """Per-program VMEM residency: the q block, both kv slabs, and the
    f32 (Bq, Lk) score/probability working set."""
    return (bq + 2 * lk_p) * d_p * itemsize + bq * lk_p * 4


def serving_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                      scale: Optional[float] = None,
                      block_q: int = 256) -> jnp.ndarray:
    """Fused forward-only softmax(q·kᵀ/√D)·v. q (B, Lq, H, D), k/v
    (B, Lk, H, D) — drop-in for `flax.linen.dot_product_attention`.

    Falls back to the XLA path per shape when the resident slabs exceed
    the shared VMEM budget; either way the decision lands in the
    coverage registry (attention_coverage)."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    key: ShapeKey = (B, Lq, Lk, H, D, jnp.dtype(q.dtype).name)
    scale = float(D ** -0.5) if scale is None else float(scale)
    interpret = _pallas.use_interpret()

    block_q = ((block_q + 15) // 16) * 16
    bq = min(block_q, max(16, ((Lq + 15) // 16) * 16))
    Lk_p = Lk + ((-Lk) % _LANES)
    D_p = D if interpret else D + ((-D) % _LANES)
    if not _pallas.fits_vmem(
            _slab_bytes(bq, Lk_p, D_p, jnp.dtype(q.dtype).itemsize)):
        _record(key, "fallback:vmem")
        return nn.dot_product_attention(q, k, v)
    _record(key, "kernel")

    # (B, L, H, D) → (B·H, L, D): heads become independent grid rows.
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    qt = _pad_to(qt, 1, bq)
    kt = _pad_to(kt, 1, _LANES)
    vt = _pad_to(vt, 1, _LANES)
    if not interpret:  # lane alignment for the MXU
        qt = _pad_to(qt, 2, _LANES)
        kt = _pad_to(kt, 2, _LANES)
        vt = _pad_to(vt, 2, _LANES)
    N, Lq_p, Dp = qt.shape
    Lk_pad = kt.shape[1]
    mem = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    out = pl.pallas_call(
        functools.partial(_serving_kernel, scale=scale, kv_len=Lk),
        grid=(N, Lq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, Dp), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, Lk_pad, Dp), lambda n, i: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lk_pad, Dp), lambda n, i: (n, 0, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, bq, Dp), lambda n, i: (n, i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((N, Lq_p, Dp), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out[:, :Lq, :D].reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
