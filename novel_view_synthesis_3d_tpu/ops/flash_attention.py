"""Fused multi-head attention as a Pallas TPU kernel.

The reference computes attention with `flax.nn.dot_product_attention`
(/root/reference/model/xunet.py:101), which materializes the (L, L) score
matrix in HBM between ops. This kernel keeps the whole
score→softmax→weighted-sum chain in VMEM, streaming one query block at a
time against the full key/value sequence (which for one (batch, head) pair
fits comfortably in VMEM at every config in the ladder — L ≤ 65k would not,
but attention only runs at coarse resolutions {8,16,32} ⇒ L ≤ 1024 tokens,
and cross-frame attention at k+1 frames tops out at a few thousand).

Layout notes (pallas_guide.md "Tiling Constraints"):
  - lanes (last dim) padded to a multiple of 128; sublanes to the dtype
    minimum. Padding is applied in the wrapper, masked inside the kernel
    with a statically-known length, and sliced off afterwards.
  - matmuls request `preferred_element_type=float32` so the MXU accumulates
    in f32 even for bf16 inputs; softmax runs in f32.

The backward pass is a custom VJP using the standard flash-attention
residuals (out, logsumexp): probabilities are recomputed from q·k and lse —
no (L, L) tensor is saved between forward and backward. For head_dim ≥
_PALLAS_BWD_MIN_HEAD_DIM the backward runs as two blocked Pallas kernels
(_dq_kernel over query blocks, _dkv_kernel over kv blocks — scores never
leave VMEM); below that, lane padding (D → 128) wastes more MXU than VMEM
residency saves, and an XLA einsum backward (_flash_bwd_xla) is used
instead (measured on v5e at D=16: ~20% faster train step).

Falls back to interpreter mode off-TPU so the same code path is unit-tested
on the CPU mesh (tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from novel_view_synthesis_3d_tpu.ops import _pallas

try:  # pltpu only imports on TPU-capable jaxlibs; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                 kv_len: int):
    """One query block vs. the full key/value sequence, entirely in VMEM.

    q_ref (1, Bq, D) · k_ref/v_ref (1, Lk_pad, D) · o_ref (1, Bq, D) ·
    lse_ref (1, Bq, 128) — lse broadcast across the lane dim to satisfy the
    TPU (sublane, lane) tiling constraint on output blocks.
    `kv_len` is the true (unpadded) kv length — static.
    """
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if kv_len < k.shape[0]:  # mask padded kv columns (static condition)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse = m + jnp.log(l)  # (Bq, 1)
    lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], lse_ref.shape[-1]))


def _flash_fwd_padded(q, k, v, *, scale: float, kv_len: int, block_q: int,
                      interpret: bool):
    """q (N, Lq_pad, Dp) · k,v (N, Lk_pad, Dp) → (out, lse)."""
    N, Lq, D = q.shape
    Lk = k.shape[1]
    grid = (N, Lq // block_q)
    kernel = functools.partial(_attn_kernel, scale=scale, kv_len=kv_len)
    mem = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, block_q, 128), lambda n, i: (n, i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((N, Lq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


def _use_interpret() -> bool:
    return _pallas.use_interpret()


def resolve_flash(flag) -> bool:
    """Resolve a use_flash_attention config value ('auto' | bool);
    see ops/_pallas.resolve_flag for the shared semantics."""
    return _pallas.resolve_flag(flag, "use_flash_attention")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, scale: float, block_q: int):
    out, _ = _flash_fwd_core(q, k, v, scale, block_q)
    return out


def _flash_fwd_core(q, k, v, scale: float, block_q: int):
    """(B, L, H, D) inputs → padded kernel call → unpadded (out, lse)."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    interpret = _use_interpret()
    # (B, L, H, D) → (B·H, L, D): heads become independent grid rows.
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    # Query block: a multiple of 16 sublanes (covers the f32 and bf16 tile
    # minima) no larger than the padded query length. User-supplied block_q
    # is rounded up so any value Mosaic-compiles on hardware.
    block_q = ((block_q + 15) // 16) * 16
    bq = min(block_q, max(16, ((Lq + 15) // 16) * 16))
    qt = _pad_to(qt, 1, bq)
    kt = _pad_to(kt, 1, 128)
    vt = _pad_to(vt, 1, 128)
    if not interpret:  # lane alignment for the MXU
        qt = _pad_to(qt, 2, 128)
        kt = _pad_to(kt, 2, 128)
        vt = _pad_to(vt, 2, 128)
    out, lse = _flash_fwd_padded(qt, kt, vt, scale=scale, kv_len=Lk,
                                 block_q=bq, interpret=interpret)
    out = out[:, :Lq, :D].reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
    lse = lse[:, :Lq].reshape(B, H, Lq)
    return out, lse


def _flash_vjp_fwd(q, k, v, scale: float, block_q: int):
    out, lse = _flash_fwd_core(q, k, v, scale, block_q)
    return out, (q, k, v, out, lse)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref, *,
               scale: float, kv_len: int):
    """dq for one query block: recompute p from lse, ds = p·(dp−δ)·scale,
    dq = ds·K. q/do (1,Bq,D) · k/v (1,Lk,D) · lse/dlt (1,Bq,128)."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if kv_len < k.shape[0]:
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, :1])                       # (Bq, Lk)
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - dlt_ref[0][:, :1]) * scale
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dk_ref,
                dv_ref, *, scale: float):
    """dk/dv for one kv block against the full query sequence.
    k/v (1,Bk,D) · q/do (1,Lq,D) · lse/dlt (1,Lq,128). Padded q rows carry
    lse=+inf ⇒ p=0 ⇒ they contribute nothing."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (Lq, Bk)
    p = jnp.exp(s - lse_ref[0][:, :1])
    do = do_ref[0]
    dv_ref[0] = jax.lax.dot_general(
        p.astype(do.dtype), do, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v_ref[0], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (Lq, Bk)
    ds = p * (dp - dlt_ref[0][:, :1]) * scale
    dk_ref[0] = jax.lax.dot_general(
        ds.astype(q.dtype), q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale: float, block_q: int):
    """Blocked Pallas backward: one pass for dq (grid over q blocks), one
    for dk/dv (grid over kv blocks); no (Lq, Lk) tensor ever leaves VMEM."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    interpret = _use_interpret()
    g32 = g.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # (B, Lq, H)

    def to_nld(x, L):
        return x.transpose(0, 2, 1, 3).reshape(B * H, L, x.shape[-1])

    qt, kt, vt = to_nld(q, Lq), to_nld(k, Lk), to_nld(v, Lk)
    dot = to_nld(g, Lq)
    lse_n = lse.reshape(B * H, Lq)
    dlt_n = delta.transpose(0, 2, 1).reshape(B * H, Lq)

    block_q = ((block_q + 15) // 16) * 16
    bq = min(block_q, max(16, ((Lq + 15) // 16) * 16))
    bk = min(block_q, max(16, ((Lk + 15) // 16) * 16))
    qt = _pad_to(qt, 1, bq)
    dot = _pad_to(dot, 1, bq)
    # kv must pad to a common multiple of the block size AND the 128-lane
    # tile so the (Lk_p // bk) grid covers every row exactly — padding to
    # max(bk, 128) alone leaves a partial trailing block unwritten when bk
    # doesn't divide 128.
    kv_mult = bk * 128 // math.gcd(bk, 128)
    kt = _pad_to(kt, 1, kv_mult)
    vt = _pad_to(vt, 1, kv_mult)
    # Padded q rows: lse=+inf makes their probabilities exactly 0.
    Lq_p, Lk_p = qt.shape[1], kt.shape[1]
    lse_p = jnp.pad(lse_n, ((0, 0), (0, Lq_p - Lq)),
                    constant_values=jnp.inf)
    dlt_p = jnp.pad(dlt_n, ((0, 0), (0, Lq_p - Lq)))
    # Lane-broadcast lse/delta to (N, L, 128) to satisfy output/input tiling.
    lse_b = jnp.broadcast_to(lse_p[..., None], lse_p.shape + (128,))
    dlt_b = jnp.broadcast_to(dlt_p[..., None], dlt_p.shape + (128,))
    if not interpret:
        qt = _pad_to(qt, 2, 128)
        kt = _pad_to(kt, 2, 128)
        vt = _pad_to(vt, 2, 128)
        dot = _pad_to(dot, 2, 128)
    N, _, Dp = qt.shape
    mem = {} if _VMEM is None or interpret else {"memory_space": _VMEM}

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, kv_len=Lk),
        grid=(N, Lq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, Dp), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, Lk_p, Dp), lambda n, i: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lk_p, Dp), lambda n, i: (n, 0, 0), **mem),
            pl.BlockSpec((1, bq, Dp), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, bq, 128), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, bq, 128), lambda n, i: (n, i, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, bq, Dp), lambda n, i: (n, i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((N, Lq_p, Dp), q.dtype),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_b, dlt_b)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale),
        grid=(N, Lk_p // bk),
        in_specs=[
            pl.BlockSpec((1, Lq_p, Dp), lambda n, j: (n, 0, 0), **mem),
            pl.BlockSpec((1, bk, Dp), lambda n, j: (n, j, 0), **mem),
            pl.BlockSpec((1, bk, Dp), lambda n, j: (n, j, 0), **mem),
            pl.BlockSpec((1, Lq_p, Dp), lambda n, j: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lq_p, 128), lambda n, j: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lq_p, 128), lambda n, j: (n, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, Dp), lambda n, j: (n, j, 0), **mem),
            pl.BlockSpec((1, bk, Dp), lambda n, j: (n, j, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Lk_p, Dp), k.dtype),
            jax.ShapeDtypeStruct((N, Lk_p, Dp), v.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse_b, dlt_b)

    def from_nld(x, L):
        return x[:, :L, :D].reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return from_nld(dq, Lq), from_nld(dk, Lk), from_nld(dv, Lk)


def _flash_bwd_xla(q, k, v, out, lse, g, scale: float):
    """Einsum backward with p recomputed from lse. Materializes (Lq, Lk) in
    HBM, but for small head_dim XLA's unpadded contractions beat the Pallas
    kernels' 128-lane padding (measured on v5e at D=16: ~20% faster step)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = jnp.exp(s - lse[..., None])                      # (B,H,Lq,Lk)
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, g32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", g32, v.astype(jnp.float32))
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # (B,Lq,H)
    ds = p * (dp - delta.transpose(0, 2, 1)[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq, dk, dv


# Below this head_dim the Pallas backward's lane padding (D → 128) wastes
# more MXU than the fused VMEM residency saves.
_PALLAS_BWD_MIN_HEAD_DIM = 64


def _flash_vjp_bwd(scale: float, block_q: int, res, g):
    q, k, v, out, lse = res
    if q.shape[-1] >= _PALLAS_BWD_MIN_HEAD_DIM or _use_interpret():
        dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, g, scale, block_q)
    else:
        dq, dk, dv = _flash_bwd_xla(q, k, v, out, lse, g, scale)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    scale: Optional[float] = None,
                    block_q: int = 256) -> jnp.ndarray:
    """Fused softmax(q·kᵀ/√D)·v. q (B, Lq, H, D), k/v (B, Lk, H, D).

    Drop-in for `flax.linen.dot_product_attention` (same layout/scaling).
    """
    D = q.shape[-1]
    scale = float(D ** -0.5) if scale is None else float(scale)
    return _flash_attention(q, k, v, scale, int(block_q))
