"""Fused multi-head attention as a Pallas TPU kernel.

The reference computes attention with `flax.nn.dot_product_attention`
(/root/reference/model/xunet.py:101), which materializes the (L, L) score
matrix in HBM between ops. This kernel keeps the whole
score→softmax→weighted-sum chain in VMEM, streaming one query block at a
time against the full key/value sequence (which for one (batch, head) pair
fits comfortably in VMEM at every config in the ladder — L ≤ 65k would not,
but attention only runs at coarse resolutions {8,16,32} ⇒ L ≤ 1024 tokens,
and cross-frame attention at k+1 frames tops out at a few thousand).

Layout notes (pallas_guide.md "Tiling Constraints"):
  - lanes (last dim) padded to a multiple of 128; sublanes to the dtype
    minimum. Padding is applied in the wrapper, masked inside the kernel
    with a statically-known length, and sliced off afterwards.
  - matmuls request `preferred_element_type=float32` so the MXU accumulates
    in f32 even for bf16 inputs; softmax runs in f32.

The backward pass is a custom VJP using the standard flash-attention
residuals (out, logsumexp): probabilities are recomputed from q·k and lse —
no (L, L) tensor is saved between forward and backward. The backward
contraction itself is left to XLA (einsums fuse well on the MXU and the
sequence lengths here keep the rematerialized scores in the same size class
as the activations).

Falls back to interpreter mode off-TPU so the same code path is unit-tested
on the CPU mesh (tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable jaxlibs; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                 kv_len: int):
    """One query block vs. the full key/value sequence, entirely in VMEM.

    q_ref (1, Bq, D) · k_ref/v_ref (1, Lk_pad, D) · o_ref (1, Bq, D) ·
    lse_ref (1, Bq, 128) — lse broadcast across the lane dim to satisfy the
    TPU (sublane, lane) tiling constraint on output blocks.
    `kv_len` is the true (unpadded) kv length — static.
    """
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if kv_len < k.shape[0]:  # mask padded kv columns (static condition)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse = m + jnp.log(l)  # (Bq, 1)
    lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], lse_ref.shape[-1]))


def _flash_fwd_padded(q, k, v, *, scale: float, kv_len: int, block_q: int,
                      interpret: bool):
    """q (N, Lq_pad, Dp) · k,v (N, Lk_pad, Dp) → (out, lse)."""
    N, Lq, D = q.shape
    Lk = k.shape[1]
    grid = (N, Lq // block_q)
    kernel = functools.partial(_attn_kernel, scale=scale, kv_len=kv_len)
    mem = {} if _VMEM is None or interpret else {"memory_space": _VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0), **mem),
            pl.BlockSpec((1, Lk, D), lambda n, i: (n, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda n, i: (n, i, 0), **mem),
            pl.BlockSpec((1, block_q, 128), lambda n, i: (n, i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((N, Lq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def resolve_flash(flag) -> bool:
    """Resolve a use_flash_attention config value.

    'auto' → the Pallas kernel on TPU backends (where it's compiled and
    faster), the XLA attention path elsewhere (where the kernel would run in
    the interpreter). Booleans pass through; anything else is an error —
    CLI overrides arrive as raw strings, and silently coercing a typo like
    'False' to truthy would force interpret-mode Pallas on CPU.
    """
    if flag == "auto":
        return not _use_interpret()
    if isinstance(flag, bool):
        return flag
    raise ValueError(
        f"use_flash_attention must be True, False, or 'auto'; got {flag!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, scale: float, block_q: int):
    out, _ = _flash_fwd_core(q, k, v, scale, block_q)
    return out


def _flash_fwd_core(q, k, v, scale: float, block_q: int):
    """(B, L, H, D) inputs → padded kernel call → unpadded (out, lse)."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    interpret = _use_interpret()
    # (B, L, H, D) → (B·H, L, D): heads become independent grid rows.
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    # Query block: a multiple of 16 sublanes (covers the f32 and bf16 tile
    # minima) no larger than the padded query length. User-supplied block_q
    # is rounded up so any value Mosaic-compiles on hardware.
    block_q = ((block_q + 15) // 16) * 16
    bq = min(block_q, max(16, ((Lq + 15) // 16) * 16))
    qt = _pad_to(qt, 1, bq)
    kt = _pad_to(kt, 1, 128)
    vt = _pad_to(vt, 1, 128)
    if not interpret:  # lane alignment for the MXU
        qt = _pad_to(qt, 2, 128)
        kt = _pad_to(kt, 2, 128)
        vt = _pad_to(vt, 2, 128)
    out, lse = _flash_fwd_padded(qt, kt, vt, scale=scale, kv_len=Lk,
                                 block_q=bq, interpret=interpret)
    out = out[:, :Lq, :D].reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
    lse = lse[:, :Lq].reshape(B, H, Lq)
    return out, lse


def _flash_vjp_fwd(q, k, v, scale: float, block_q: int):
    out, lse = _flash_fwd_core(q, k, v, scale, block_q)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale: float, block_q: int, res, g):
    q, k, v, out, lse = res
    # Recompute probabilities from the saved logsumexp (no (L,L) residual).
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = jnp.exp(s - lse[..., None])                      # (B,H,Lq,Lk)
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, g32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", g32, v.astype(jnp.float32))
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # (B,Lq,H)
    ds = p * (dp - delta.transpose(0, 2, 1)[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    scale: Optional[float] = None,
                    block_q: int = 256) -> jnp.ndarray:
    """Fused softmax(q·kᵀ/√D)·v. q (B, Lq, H, D), k/v (B, Lk, H, D).

    Drop-in for `flax.linen.dot_product_attention` (same layout/scaling).
    """
    D = q.shape[-1]
    scale = float(D ** -0.5) if scale is None else float(scale)
    return _flash_attention(q, k, v, scale, int(block_q))
