"""X-UNet: pose-conditional two(+)-frame diffusion UNet (3DiM).

Clean-room TPU-first reimplementation of the architecture at
/root/reference/model/xunet.py:142-280, generalized so that:

  - every hyperparameter is a real config field (the reference freezes
    `ch_mult`/`attn_resolutions` as class attributes — SURVEY.md §2.2 quirk);
  - the frame axis F = num_cond_frames + 1 is free (reference hardcodes 2);
    conditioning frames come first, the noised target frame is LAST, and the
    model returns the target frame's noise prediction (for F=2 this matches
    the reference's `[:, 1]` selection at xunet.py:280);
  - camera rays come from models/rays.py (pure jnp) instead of visu3d;
  - compute dtype / remat are configurable for TPU memory/throughput.

Batch contract (canonical keys, reference train.py:23-34):
  x      (B, H, W, 3) or (B, Fc, H, W, 3)   clean conditioning view(s), [-1,1]
  z      (B, H, W, 3)                        noised target view
  logsnr (B,)
  R1, t1 (B, 3, 3) / (B, 3) or (B, Fc, ...)  cond camera cam→world pose(s)
  R2, t2 (B, 3, 3) / (B, 3)                  target camera pose
  K      (B, 3, 3)                           shared pinhole intrinsics
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import ModelConfig
from novel_view_synthesis_3d_tpu.models.layers import (
    FrameConv,
    GroupNorm,
    ResnetBlock,
    XUNetBlock,
    nonlinearity,
)
from novel_view_synthesis_3d_tpu.models.rays import camera_rays
from novel_view_synthesis_3d_tpu.ops.flash_attention import resolve_flash
from novel_view_synthesis_3d_tpu.ops.fused_epilogue import (
    resolve_fused_epilogue)
from novel_view_synthesis_3d_tpu.ops.fused_groupnorm import resolve_fused_gn
from novel_view_synthesis_3d_tpu.ops.serving_attention import (
    resolve_serving_attention)
from novel_view_synthesis_3d_tpu.ops.posenc import posenc_ddpm, posenc_nerf


def _as_frames(arr: jnp.ndarray, frame_rank: int) -> jnp.ndarray:
    """Insert a singleton frame axis after batch if not already present."""
    if arr.ndim == frame_rank:
        return arr[:, None]
    return arr


def _named_remat(policy=None):
    """nn.remat(XUNetBlock) renamed back to 'XUNetBlock'.

    Flax derives parameter paths from the class name, and the lifted
    transform returns a class called 'CheckpointXUNetBlock' — which would
    silently fork the param tree ('CheckpointXUNetBlock_0' vs
    'XUNetBlock_0') and make checkpoints non-portable between remat
    settings (train at 256px with remat, sample without). Renaming the
    wrapped class keeps one layout for every mode. (A checkpoint written by
    a pre-rename build with remat on can be migrated by renaming its
    'CheckpointXUNetBlock_N' keys to 'XUNetBlock_N'.)
    """
    cls = nn.remat(XUNetBlock, policy=policy)
    cls.__name__ = "XUNetBlock"
    cls.__qualname__ = "XUNetBlock"
    return cls


def _remat_block(remat):
    """Resolve config.model.remat → the (possibly rematerialized) block class.

    False = no remat. True / 'full' = recompute everything in the backward
    pass (smallest memory, most recompute FLOPs). 'dots' = save matmul/conv
    outputs, recompute only the elementwise chains between them
    (jax.checkpoint_policies.dots_saveable) — the bandwidth-flops middle
    ground for an HBM-bound model: GroupNorm/swish/FiLM intermediates are
    never written to HBM, while no conv runs twice.
    """
    if remat in (False, "none"):
        return XUNetBlock
    if remat in (True, "full"):
        return _named_remat()
    if remat == "dots":
        return _named_remat(jax.checkpoint_policies.dots_saveable)
    raise ValueError(
        f"model.remat must be False, True, 'none', 'full', or 'dots'; "
        f"got {remat!r}")




class ConditioningProcessor(nn.Module):
    """logsnr + camera-pose conditioning → per-level FiLM embeddings.

    Reference: model/xunet.py:142-203. Produces `logsnr_emb` (B, emb_ch) and
    one (B, F, H/2ˡ, W/2ˡ, emb_ch) pose embedding per UNet resolution level.
    """

    emb_ch: int
    num_resolutions: int
    use_pos_emb: bool = False
    use_ref_pose_emb: bool = False
    # Scene-category conditioning (model.num_classes): > 0 adds a
    # ZERO-INIT (num_classes, emb_ch) embedding table looked up by the
    # batch's int32 `category` ids and added into logsnr_emb, behind the
    # same CFG cond-drop mask as the pose embedding. Zero init makes the
    # table a numeric no-op at creation, which is what lets checkpoints
    # trained at num_classes=0 load into a num_classes>0 model by
    # splicing the fresh zero table (train/ladder.py).
    num_classes: int = 0
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, batch: dict, cond_mask: jnp.ndarray):
        z = batch["z"]
        B, H, W, _ = z.shape
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        # --- logsnr embedding (reference xunet.py:152-157) ---
        # clip ±20, squash to (0,1) via 2·atan(e^{−λ/2})/π, DDPM sinusoid
        # (max_time=1 ⇒ internal ×1000), then Dense → Dense∘swish.
        logsnr = jnp.clip(batch["logsnr"], -20.0, 20.0)
        logsnr = 2.0 * jnp.arctan(jnp.exp(-logsnr / 2.0)) / np.pi
        logsnr_emb = posenc_ddpm(logsnr, emb_ch=self.emb_ch, max_time=1.0,
                                 dtype=self.dtype)
        logsnr_emb = nn.Dense(self.emb_ch, **kw)(logsnr_emb)
        logsnr_emb = nn.Dense(self.emb_ch, **kw)(nonlinearity(logsnr_emb))

        # --- scene-category embedding (data/corpus.py mixed batches) ---
        # Rides the logsnr channel so it reaches every FiLM site without
        # touching the pose-embedding shapes, and sits BEFORE the
        # precomputed-pose early return so the serving/sampling fast
        # paths stay category-aware. A batch without a `category` field
        # conditions on nothing (zero vector) — old single-corpus batches
        # are numerically unchanged even with the table present.
        if self.num_classes > 0:
            table = self.param("category_emb", nn.initializers.zeros,
                               (self.num_classes, self.emb_ch),
                               self.param_dtype)
            if "category" in batch:
                cat_emb = jnp.take(table.astype(self.dtype),
                                   batch["category"], axis=0)
                if cond_mask is not None:
                    # CFG cond-drop: the category drops with the pose
                    # conditioning (one mask, one uncond branch) so
                    # guidance and distillation survive unchanged.
                    assert cond_mask.shape == (B,), cond_mask.shape
                    cat_emb = jnp.where(cond_mask[:, None], cat_emb,
                                        jnp.zeros_like(cat_emb))
                logsnr_emb = logsnr_emb + cat_emb

        # Precomputed pose path (sampling): the pose embeddings depend only
        # on the cameras, not on (z_t, logsnr) — a sampler can compute them
        # ONCE and hoist them out of its reverse-process scan instead of
        # re-running rays→posenc→convs every denoising step. The caller
        # must have applied the CFG cond_mask at precompute time (the mask
        # zeroes the pose embedding, xunet.py:174-179 in the reference).
        # init() never takes this path, so the param tree is unchanged.
        if "pose_embs" in batch:
            return logsnr_emb, list(batch["pose_embs"])

        # --- pose embeddings (reference xunet.py:158-173) ---
        # Stack cond + target cameras on the frame axis, generate world rays,
        # NeRF-posenc origins (deg 15 → 93) and directions (deg 8 → 51),
        # concat → (B, F, H, W, 144).
        R1 = _as_frames(batch["R1"], 3)   # (B, Fc, 3, 3)
        t1 = _as_frames(batch["t1"], 2)   # (B, Fc, 3)
        R = jnp.concatenate([R1, batch["R2"][:, None]], axis=1)
        t = jnp.concatenate([t1, batch["t2"][:, None]], axis=1)
        F = R.shape[1]
        K = jnp.broadcast_to(batch["K"][:, None], (B, F, 3, 3))
        pos, dirs = camera_rays(R, t, K, resolution=(H, W))
        pose_emb = jnp.concatenate(
            [
                posenc_nerf(pos, min_deg=0, max_deg=15),
                posenc_nerf(dirs, min_deg=0, max_deg=8),
            ],
            axis=-1,
        ).astype(self.dtype)
        D = pose_emb.shape[-1]

        # Classifier-free guidance: zero the whole pose embedding per sample
        # where cond_mask == 0 (reference xunet.py:174-179).
        assert cond_mask.shape == (B,), cond_mask.shape
        mask = cond_mask[:, None, None, None, None]
        pose_emb = jnp.where(mask, pose_emb, jnp.zeros_like(pose_emb))

        if self.use_pos_emb:
            pos_emb = self.param(
                "pos_emb", nn.initializers.normal(stddev=1.0 / np.sqrt(D)),
                (H, W, D), self.param_dtype)
            pose_emb += pos_emb[None, None].astype(self.dtype)

        if self.use_ref_pose_emb:
            # Binary frame-identity embedding: 'first' on frame 0, 'other' on
            # the rest (reference xunet.py:186-194, generalized to F frames).
            first = self.param(
                "ref_pose_emb_first", nn.initializers.normal(stddev=1.0 / np.sqrt(D)),
                (D,), self.param_dtype)
            other = self.param(
                "ref_pose_emb_other", nn.initializers.normal(stddev=1.0 / np.sqrt(D)),
                (D,), self.param_dtype)
            frame_emb = jnp.stack([first] + [other] * (F - 1), axis=0)
            pose_emb += frame_emb[None, :, None, None, :].astype(self.dtype)

        # Per-resolution strided downsampling of the full-res embedding
        # (reference xunet.py:197-202): one conv per level, stride 2ˡ.
        pose_embs = []
        for i_level in range(self.num_resolutions):
            pose_embs.append(
                FrameConv(self.emb_ch, kernel=3, stride=2 ** i_level, **kw)(pose_emb)
            )
        return logsnr_emb, pose_embs


def precompute_pose_embs(model: "XUNet", params, cond: dict,
                         cond_mask: jnp.ndarray):
    """Per-level pose embeddings for a fixed conditioning layout.

    They are loop-invariant across diffusion steps (cameras don't change
    while denoising), so samplers compute them once here and pass them via
    `batch["pose_embs"]` instead of re-running rays → NeRF posenc →
    per-level downsampling convs inside every scan step. `cond_mask` is
    baked in (CFG zeroing happens at this stage). `cond` needs x/R1/t1/
    R2/t2/K; z/logsnr are synthesized for shape purposes only.
    """
    cfg = model.config
    x = cond["x"]
    spatial = x.shape[-3:-1]
    B = x.shape[0]
    proc = ConditioningProcessor(
        emb_ch=cfg.emb_ch,
        num_resolutions=len(cfg.ch_mult),
        use_pos_emb=cfg.use_pos_emb,
        use_ref_pose_emb=cfg.use_ref_pose_emb,
        num_classes=cfg.num_classes,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )
    batch = dict(cond,
                 z=jnp.zeros((B,) + spatial + (x.shape[-1],), x.dtype),
                 logsnr=jnp.zeros((B,)))
    _, pose_embs = proc.apply({"params": params["ConditioningProcessor_0"]},
                              batch, cond_mask)
    return tuple(pose_embs)


def precompute_cond_feats(model: "XUNet", params, cond: dict) -> jnp.ndarray:
    """Stem features of the conditioning frame(s), (B, Fc, H, W, ch).

    The stem FrameConv convolves each frame independently, so the cond
    frames' features never change while the target frame denoises — the
    serving cond cache (sample/service.py) computes them once here and
    passes them via `batch["cond_feats"]`, leaving only the noised
    target frame's conv inside the step program. Unlike the pose
    embeddings these are NOT CFG-masked (the reference feeds the clean
    cond image to both guidance halves — only the pose embedding is
    zeroed), so one tensor serves both halves of a guidance pair.
    """
    cfg = model.config
    x = cond["x"]
    if x.ndim == 4:  # (B,H,W,3) → (B,1,H,W,3)
        x = x[:, None]
    conv = FrameConv(cfg.ch, dtype=jnp.dtype(cfg.dtype),
                     param_dtype=jnp.dtype(cfg.param_dtype))
    return conv.apply({"params": params["FrameConv_0"]},
                      x.astype(jnp.dtype(cfg.dtype)))


def pipeline_op_specs(cfg: ModelConfig):
    """Static, ordered op list for the XUNet — the pipeline partition unit.

    Each entry is (kind, info) where `kind` selects a body in
    XUNet.__call__ and `info` carries the static metadata INCLUDING the
    explicit flax module name. Names replicate the per-class auto-counter
    flax would have assigned in the monolithic call order, so:
      - the param tree is IDENTICAL to pre-refactor checkpoints, and
      - a stage-sliced execution (ops=(a, b)) creates modules under the
        SAME paths as the full run — which also makes flax's per-path
        dropout-rng folding identical under pipeline execution.
    `param_names` lists the top-level param-tree keys the op owns, so the
    pipeline planner can slice per-stage param subtrees exactly.
    """
    counters: dict = {}

    def nm(cls: str) -> str:
        i = counters.get(cls, 0)
        counters[cls] = i + 1
        return f"{cls}_{i}"

    num_resolutions = len(cfg.ch_mult)
    specs = []
    cond, stem = nm("ConditioningProcessor"), nm("FrameConv")
    specs.append(("prelude", dict(cond=cond, stem=stem,
                                  param_names=(cond, stem))))
    for i_level in range(num_resolutions):
        for _ in range(cfg.num_res_blocks):
            name = nm("XUNetBlock")
            specs.append(("down_block", dict(
                level=i_level, features=cfg.ch * cfg.ch_mult[i_level],
                name=name, param_names=(name,))))
        if i_level != num_resolutions - 1:
            name = nm("ResnetBlock")
            specs.append(("down_trans", dict(level=i_level, name=name,
                                             param_names=(name,))))
    name = nm("XUNetBlock")
    specs.append(("middle", dict(features=cfg.ch * cfg.ch_mult[-1],
                                 name=name, param_names=(name,))))
    for i_level in reversed(range(num_resolutions)):
        for _ in range(cfg.num_res_blocks + 1):
            name = nm("XUNetBlock")
            specs.append(("up_block", dict(
                level=i_level, features=cfg.ch * cfg.ch_mult[i_level],
                name=name, param_names=(name,))))
        if i_level != 0:
            name = nm("ResnetBlock")
            specs.append(("up_trans", dict(level=i_level, name=name,
                                           param_names=(name,))))
    gn, out = nm("GroupNorm"), nm("FrameConv")
    specs.append(("final", dict(gn=gn, out=out, param_names=(gn, out))))
    return specs


def op_groups(cfg: ModelConfig):
    """Ordered (label, param_names) layer groups for the numerics
    observatory (obs/numerics.py) — one group per pipeline op.

    Labels are the op's explicit flax module name (stable across builds
    by construction of pipeline_op_specs), except the multi-module
    prelude/final ops which keep their kind as the label. Together the
    groups partition the top-level param-tree keys exactly.
    """
    groups = []
    for kind, info in pipeline_op_specs(cfg):
        label = kind if kind in ("prelude", "final") else info["name"]
        groups.append((label, tuple(info["param_names"])))
    return groups


class XUNet(nn.Module):
    """The X-UNet (reference model/xunet.py:205-280), config-driven.

    `mesh` activates sequence-parallel ring attention when
    config.sequence_parallel is set (tokens sharded over the mesh 'seq'
    axis; parallel/ring_attention.py).

    The body is an ordered list of ops (pipeline_op_specs): the default
    call runs all of them — numerically and param-tree identical to the
    monolithic forward — while `ops=(a, b)` runs the half-open slice
    [a, b) for pipeline-stage execution (parallel/pipeline.py): a slice
    starting at 0 consumes `batch`/`cond_mask` and later slices consume
    `carry` (the (h, skip-stack, logsnr_emb, pose_embs) state); a slice
    ending before the last op returns the carry instead of the output.
    `batch` is still required for ops>0 slices — only its SHAPES are used
    (e.g. the output-channel count), never its values.
    """

    config: ModelConfig = ModelConfig()
    mesh: object = None

    @nn.compact
    def __call__(self, batch: dict, *, cond_mask: jnp.ndarray = None,
                 train: bool, ops=None, carry=None) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        param_dtype = jnp.dtype(cfg.param_dtype)
        kw = dict(dtype=dtype, param_dtype=param_dtype)
        fused_gn = resolve_fused_gn(cfg.use_fused_groupnorm)
        blk_kw = dict(per_frame_gn=cfg.groupnorm_per_frame,
                      fused_gn=fused_gn,
                      fused_epilogue=resolve_fused_epilogue(
                          cfg.use_fused_epilogue),
                      **kw)
        num_resolutions = len(cfg.ch_mult)
        C = batch["z"].shape[-1]

        # `train` is threaded as a module attribute (static by construction)
        # so the blocks can be remat'd without static-argnum plumbing.
        Block = _remat_block(cfg.remat)

        def block(features, use_attn, h, emb, train, name):
            return Block(
                features=features,
                use_attn=use_attn,
                attn_heads=cfg.attn_heads,
                attn_out_proj=cfg.attn_out_proj,
                attn_use_flash=resolve_flash(cfg.use_flash_attention),
                attn_use_serving=resolve_serving_attention(
                    cfg.use_serving_attention),
                attn_mesh=(self.mesh if cfg.sequence_parallel else None),
                dropout=cfg.dropout,
                train=train,
                name=name,
                **blk_kw,
            )(h, emb)

        def run_op(kind, info, state):
            if kind == "prelude":
                logsnr_emb, pose_embs = ConditioningProcessor(
                    emb_ch=cfg.emb_ch,
                    num_resolutions=num_resolutions,
                    use_pos_emb=cfg.use_pos_emb,
                    use_ref_pose_emb=cfg.use_ref_pose_emb,
                    num_classes=cfg.num_classes,
                    name=info["cond"],
                    **kw,
                )(batch, cond_mask)
                # Frame stacking: cond frames first, noised target LAST.
                if "cond_feats" in batch:
                    # Conditioning cache (sample/service.py): the stem
                    # conv runs per frame, so the cond frames' features
                    # are loop-invariant across denoise steps — the
                    # caller computed them once (precompute_cond_feats)
                    # and only the noised target frame is convolved
                    # here. Bitwise identical to the joint conv below
                    # (per-frame batch rows are independent).
                    # init() never takes this path: param tree unchanged.
                    hz = batch["z"][:, None].astype(dtype)
                    hz = FrameConv(cfg.ch, name=info["stem"], **kw)(hz)
                    h = jnp.concatenate(
                        [batch["cond_feats"].astype(hz.dtype), hz], axis=1)
                else:
                    x = batch["x"]
                    if x.ndim == 4:  # (B,H,W,3) → (B,1,H,W,3)
                        x = x[:, None]
                    h = jnp.concatenate([x, batch["z"][:, None]],
                                        axis=1).astype(dtype)
                    h = FrameConv(cfg.ch, name=info["stem"], **kw)(h)
                return (h, (h,), logsnr_emb, tuple(pose_embs))

            h, hs, logsnr_emb, pose_embs = state

            def level_emb(i_level):
                # (B, 1, 1, 1, emb) + (B, F, H/2ˡ, W/2ˡ, emb) broadcast add.
                return logsnr_emb[:, None, None, None, :] + pose_embs[i_level]

            if kind == "down_block":
                use_attn = h.shape[3] in cfg.attn_resolutions
                h = block(info["features"], use_attn, h,
                          level_emb(info["level"]), train, info["name"])
                return (h, hs + (h,), logsnr_emb, pose_embs)
            if kind == "down_trans":
                # Strided transition conditioned with the NEXT level's pose
                # embedding (reference xunet.py:243-246).
                h = ResnetBlock(dropout=cfg.dropout, resample="down",
                                name=info["name"], **blk_kw)(
                    h, level_emb(info["level"] + 1), train=train)
                return (h, hs + (h,), logsnr_emb, pose_embs)
            if kind == "middle":
                # Bottleneck features = ch·ch_mult[-1], ref xunet.py:248-255.
                use_attn = h.shape[3] in cfg.attn_resolutions
                h = block(info["features"], use_attn, h,
                          level_emb(num_resolutions - 1), train,
                          info["name"])
                return (h, hs, logsnr_emb, pose_embs)
            if kind == "up_block":
                # Skip-concat then block (num_res_blocks+1 per level).
                use_attn = hs[-1].shape[3] in cfg.attn_resolutions
                h = jnp.concatenate([h, hs[-1]], axis=-1)
                h = block(info["features"], use_attn, h,
                          level_emb(info["level"]), train, info["name"])
                return (h, hs[:-1], logsnr_emb, pose_embs)
            if kind == "up_trans":
                # Upsample transition conditioned with the FINER level's
                # pose embedding (reference xunet.py:269-271).
                h = ResnetBlock(dropout=cfg.dropout, resample="up",
                                name=info["name"], **blk_kw)(
                    h, level_emb(info["level"] - 1), train=train)
                return (h, hs, logsnr_emb, pose_embs)
            assert kind == "final", kind
            assert not hs
            h = GroupNorm(per_frame=cfg.groupnorm_per_frame, act="swish",
                          fused=fused_gn, dtype=dtype, name=info["gn"])(h)
            # Zero-init output conv in float32 for stable noise predictions.
            out = FrameConv(C, zero_init=True, dtype=jnp.float32,
                            param_dtype=param_dtype, name=info["out"])(
                h.astype(jnp.float32))
            return out[:, -1]

        specs = pipeline_op_specs(cfg)
        a, b = (0, len(specs)) if ops is None else ops
        state = carry
        for kind, info in specs[a:b]:
            # og.<label> named scope: stamps each op's HLO with its
            # op-group label (the op_groups vocabulary) so profiler
            # traces attribute device time per group (obs/profiler.py).
            # Metadata only — no effect on the computation, the param
            # tree, or flax's module naming/rng folding.
            label = kind if kind in ("prelude", "final") else info["name"]
            with jax.named_scope(f"og.{label}"):
                state = run_op(kind, info, state)
        return state
