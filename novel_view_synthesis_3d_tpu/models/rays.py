"""Pinhole-camera ray generation in pure jnp (replaces visu3d).

The reference depends on visu3d 1.3.0 for camera rays
(/root/reference/model/xunet.py:159-171): it builds
`v3d.Camera(spec=PinholeCamera(resolution=(H, W), K), world_from_cam=
Transform(R, t)).rays()`, whose semantics are:

  - pixel centers at (u + 0.5, v + 0.5) for u ∈ [0, W), v ∈ [0, H)
  - camera-frame direction  d_cam = K⁻¹ · [u+0.5, v+0.5, 1]ᵀ
  - world direction         d = normalize(R · d_cam)
  - origin                  o = t   (camera position, broadcast per pixel)

This module implements exactly that in ~20 lines of jnp so it is trivially
jit/shard_map-traceable, differentiable, and free of the visu3d dependency.
K is assumed [[f, 0, cx], [0, f, cy], [0, 0, 1]] as produced by the SRN
`intrinsics.txt` parser (see data/srn.py), and is inverted in closed form.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def camera_rays(R: jnp.ndarray, t: jnp.ndarray, K: jnp.ndarray,
                resolution: Tuple[int, int],
                normalize: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel world-space rays for a batch of pinhole cameras.

    Args:
      R: (..., 3, 3) cam→world rotation.
      t: (..., 3) camera position in world frame.
      K: (..., 3, 3) intrinsics.
      resolution: (H, W).

    Returns:
      (pos, dir): both (..., H, W, 3); `pos` is t broadcast per pixel,
      `dir` the (optionally normalized) world-space ray direction.
    """
    H, W = resolution
    dt = R.dtype
    v, u = jnp.meshgrid(
        jnp.arange(H, dtype=dt) + 0.5, jnp.arange(W, dtype=dt) + 0.5,
        indexing="ij",
    )
    # Closed-form K⁻¹ for K = [[fx, 0, cx], [0, fy, cy], [0, 0, 1]]:
    # d_cam = ((u − cx)/fx, (v − cy)/fy, 1).
    fx = K[..., 0, 0][..., None, None]
    fy = K[..., 1, 1][..., None, None]
    cx = K[..., 0, 2][..., None, None]
    cy = K[..., 1, 2][..., None, None]
    x = (u - cx) / fx
    y = (v - cy) / fy
    d_cam = jnp.stack([x, y, jnp.ones_like(x)], axis=-1)  # (..., H, W, 3)

    d_world = jnp.einsum("...ij,...hwj->...hwi", R, d_cam)
    if normalize:
        d_world = d_world / jnp.linalg.norm(d_world, axis=-1, keepdims=True)
    pos = jnp.broadcast_to(t[..., None, None, :], d_world.shape)
    return pos, d_world
