"""X-UNet building blocks (clean-room Flax, TPU-first layout).

Capability-matches the blocks at /root/reference/model/xunet.py:46-140 with
two deliberate layout changes for TPU:

  1. All spatial convolutions operate on (B·F, H, W, C) via 2-D `nn.Conv`
     instead of the reference's 3-D `Conv(kernel=(1,3,3))` over (B,F,H,W,C).
     The math is identical (the frame-axis kernel is 1), but 2-D NHWC convs
     hit XLA:TPU's well-tuned conv→MXU path and avoid degenerate-dim layouts.
  2. GroupNorm defaults to **per-frame** statistics (reshape to (B·F,H,W,C)).
     The reference shares statistics across frames (xunet.py:46-52 applies
     flax GroupNorm over the full (B,2,H,W,C) view — SURVEY.md §2.2 quirk);
     set `per_frame=False` for bit-faithful reference behavior.

Frame count F is a free dimension (the reference hardcodes F=2).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.ops.fused_epilogue import (
    fits_vmem as epilogue_fits_vmem,
    fused_film_epilogue,
)
from novel_view_synthesis_3d_tpu.ops.fused_groupnorm import (
    fits_vmem,
    fused_group_norm,
)
from novel_view_synthesis_3d_tpu.ops.resample import (
    avgpool_downsample,
    nearest_neighbor_upsample,
)

nonlinearity = nn.swish

INV_SQRT2 = float(1.0 / np.sqrt(2.0))


def out_init_scale():
    """Zero-init for output convs (reference model/xunet.py:11-12)."""
    return nn.initializers.variance_scaling(0.0, "fan_in", "truncated_normal")


class FrameConv(nn.Module):
    """k×k spatial conv applied independently to every frame."""

    features: int
    kernel: int = 3
    stride: int = 1
    zero_init: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: jnp.ndarray) -> jnp.ndarray:
        B, F = h.shape[:2]
        h = h.reshape((B * F,) + h.shape[2:])
        h = nn.Conv(
            self.features,
            kernel_size=(self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            kernel_init=out_init_scale() if self.zero_init else nn.linear.default_kernel_init,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(h)
        return h.reshape((B, F) + h.shape[1:])


class _GNParams(nn.Module):
    """scale/bias params matching flax GroupNorm's tree leaf names, so the
    fused and XLA paths share one checkpoint layout (instantiated with
    name='GroupNorm_0', the auto-name the nn.GroupNorm submodule gets)."""

    features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype)
        return scale, bias


class GroupNorm(nn.Module):
    """32-group GroupNorm over (B, F, H, W, C), optional fused activation.

    `act='swish'` applies the nonlinearity INSIDE the norm op — on the
    fused Pallas path (ops/fused_groupnorm.py) the whole GN→swish chain is
    one HBM pass; on the XLA path it is applied after the norm (identical
    math, same param tree). `fused=True` requires per-frame statistics and
    falls back to XLA when a row slab exceeds the kernel's VMEM budget.
    """

    per_frame: bool = True
    act: Optional[str] = None
    fused: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: jnp.ndarray) -> jnp.ndarray:
        B, F, H, W, C = h.shape
        if self.fused and self.per_frame:
            if fits_vmem(H * W, C, h.dtype):
                scale, bias = _GNParams(features=C, name="GroupNorm_0")()
                # out_dtype=self.dtype matches the XLA branch's semantics:
                # nn.GroupNorm casts to the module dtype, THEN swish runs
                # in that dtype.
                y = fused_group_norm(h.reshape(B * F, H * W, C), scale,
                                     bias, 32, 1e-6, self.act, self.dtype)
                return y.reshape(B, F, H, W, C)
            # Silent fallbacks hide perf cliffs: paper256's top level
            # loses the fused kernel here and the byte budget regresses
            # with no trace. One line per (H·W, C, dtype) per process —
            # fired at trace time, so steady-state steps stay clean.
            from novel_view_synthesis_3d_tpu.utils.profiling import log_once

            log_once(
                ("fused_gn_fallback", H * W, C, str(h.dtype)),
                f"note: fused GroupNorm falling back to XLA for slab "
                f"(H·W={H * W}, C={C}, {h.dtype}): "
                f"{H * W * C * jnp.dtype(h.dtype).itemsize} bytes exceeds "
                "the kernel's VMEM budget (ops/fused_groupnorm.py) — this "
                "level pays ~3 HBM passes per GN instead of 2")
        norm = nn.GroupNorm(num_groups=32, dtype=self.dtype)
        if self.per_frame:
            y = norm(h.reshape(B * F, H, W, C)).reshape(B, F, H, W, C)
        else:
            # Reference-compat: statistics reduce over (F, H, W) jointly.
            y = norm(h)
        return nonlinearity(y) if self.act == "swish" else y


class FiLM(nn.Module):
    """Feature-wise linear modulation (reference model/xunet.py:54-61).

    `h=None` returns the raw (scale, shift) pair instead of applying the
    modulation — the fused-epilogue path (ops/fused_epilogue.py) feeds
    them to the Pallas kernel while this module keeps sole ownership of
    the Dense projection (same param tree either way)."""

    features: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: Optional[jnp.ndarray], emb: jnp.ndarray):
        emb = nn.Dense(2 * self.features, dtype=self.dtype,
                       param_dtype=self.param_dtype)(nonlinearity(emb))
        scale, shift = jnp.split(emb, 2, axis=-1)
        if h is None:
            return scale, shift
        return h * (1.0 + scale) + shift


class _GNParamsNested(nn.Module):
    """_GNParams one level down (…/GroupNorm_1/GroupNorm_0/{scale,bias}):
    the tree path a GroupNorm module's nn.GroupNorm child would occupy,
    so the fused-epilogue path shares the XLA path's checkpoint layout
    (instantiated with name='GroupNorm_1', the auto-name the second
    GroupNorm in a ResnetBlock gets)."""

    features: int

    @nn.compact
    def __call__(self):
        return _GNParams(features=self.features, name="GroupNorm_0")()


class ResnetBlock(nn.Module):
    """BigGAN-style residual block with optional 2× up/down resampling.

    Reference: model/xunet.py:63-92 — GN→swish→(resample)→conv→GN→FiLM→swish→
    dropout→zero-init conv, Dense skip projection on channel change, output
    scaled by 1/√2.
    """

    features: Optional[int] = None
    dropout: float = 0.0
    resample: Optional[str] = None
    per_frame_gn: bool = True
    fused_gn: bool = False
    fused_epilogue: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h_in: jnp.ndarray, emb: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        C = h_in.shape[-1]
        features = C if self.features is None else self.features
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        gn_kw = dict(per_frame=self.per_frame_gn, fused=self.fused_gn,
                     dtype=self.dtype)

        h = GroupNorm(act="swish", **gn_kw)(h_in)
        if self.resample is not None:
            updown = {
                "up": nearest_neighbor_upsample,
                "down": avgpool_downsample,
            }[self.resample]
            h = updown(h)
            h_in = updown(h_in)
        h = FrameConv(features, **kw)(h)
        B, F, H, W, _ = h.shape
        if (self.fused_epilogue and self.per_frame_gn
                and epilogue_fits_vmem(H * W, features, h.dtype)):
            # Fused GN → FiLM-modulate → swish tail (one HBM pass,
            # ops/fused_epilogue.py). The FiLM Dense stays in XLA; GN
            # params ride the XLA path's GroupNorm_1/GroupNorm_0 tree.
            gscale, gbias = _GNParamsNested(features=features,
                                            name="GroupNorm_1")()
            fscale, fshift = FiLM(features=features, **kw)(None, emb)
            flat = (B * F, H * W, features)
            h = fused_film_epilogue(
                h.reshape(flat),
                gscale, gbias,
                jnp.broadcast_to(fscale, h.shape).reshape(flat),
                jnp.broadcast_to(fshift, h.shape).reshape(flat),
                32, 1e-6, self.dtype).reshape(B, F, H, W, features)
        else:
            if self.fused_epilogue and self.per_frame_gn:
                from novel_view_synthesis_3d_tpu.utils.profiling import (
                    log_once)

                log_once(
                    ("fused_epilogue_fallback", H * W, features,
                     str(h.dtype)),
                    f"note: fused block epilogue falling back to XLA for "
                    f"slab (H·W={H * W}, C={features}, {h.dtype}): 3× "
                    "resident rows exceed the kernel's VMEM budget "
                    "(ops/fused_epilogue.py) — this level pays the "
                    "three-pass GN→FiLM→swish tail")
            h = FiLM(features=features, **kw)(GroupNorm(**gn_kw)(h), emb)
            h = nonlinearity(h)
        h = nn.Dropout(rate=self.dropout)(h, deterministic=not train)
        h = FrameConv(features, zero_init=True, **kw)(h)
        if C != features:
            h_in = nn.Dense(features, **kw)(h_in)
        return (h + h_in) * INV_SQRT2


class AttnLayer(nn.Module):
    """Multi-head dot-product attention over token sequences.

    Reference: model/xunet.py:94-103. The reference's output projection is
    commented out (xunet.py:126); `out_proj=True` enables a zero-init
    projection for configs that want it.
    """

    attn_heads: int = 4
    out_proj: bool = False
    use_flash: bool = False
    use_serving: bool = False  # forward-only Pallas serving kernel
    mesh: Optional[object] = None  # jax Mesh → ring attention over 'seq'
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, *, q: jnp.ndarray, kv: jnp.ndarray) -> jnp.ndarray:
        C = q.shape[-1]
        head_dim = C // self.attn_heads
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        qh = nn.DenseGeneral((self.attn_heads, head_dim), **kw)(q)
        kh = nn.DenseGeneral((self.attn_heads, head_dim), **kw)(kv)
        vh = nn.DenseGeneral((self.attn_heads, head_dim), **kw)(kv)
        if self.mesh is not None:
            # Sequence-parallel exact attention: tokens sharded over 'seq',
            # batch riding the 'data' axis, k/v blocks rotating via ppermute.
            from novel_view_synthesis_3d_tpu.parallel.mesh import DATA_AXIS
            from novel_view_synthesis_3d_tpu.parallel.ring_attention import (
                ring_self_attention)
            out = ring_self_attention(qh, kh, vh, self.mesh,
                                      batch_axis=DATA_AXIS)
        elif self.use_serving:
            # Inference twin of the flash kernel: no residuals, no VJP,
            # per-shape VMEM gate + coverage registry
            # (ops/serving_attention.py). Takes precedence over
            # use_flash — both fuse, this one is trace- and HBM-lighter
            # for forward-only step programs.
            from novel_view_synthesis_3d_tpu.ops.serving_attention import (
                serving_attention)
            out = serving_attention(qh, kh, vh)
        elif self.use_flash:
            from novel_view_synthesis_3d_tpu.ops.flash_attention import (
                flash_attention)
            out = flash_attention(qh, kh, vh)
        else:
            out = nn.dot_product_attention(qh, kh, vh)  # (B, L, heads, hd)
        if self.out_proj:
            return nn.DenseGeneral(C, axis=(-2, -1), kernel_init=out_init_scale(),
                                   **kw)(out)
        return out.reshape(out.shape[:-2] + (C,))


class AttnBlock(nn.Module):
    """Self- or cross-frame attention over flattened H·W token sequences.

    Reference: model/xunet.py:105-127. A single shared AttnLayer serves all
    frames (shared q/k/v weights). 'self': each frame attends to itself —
    batched over B·F in one call. 'cross': frame i attends to the
    concatenation of all *other* frames' pre-update tokens (for F=2 this is
    exactly the reference's frame0↔frame1 exchange). Residual scaled 1/√2.
    """

    attn_type: str
    attn_heads: int = 4
    out_proj: bool = False
    use_flash: bool = False
    use_serving: bool = False
    mesh: Optional[object] = None
    per_frame_gn: bool = True
    fused_gn: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h_in: jnp.ndarray) -> jnp.ndarray:
        B, F, H, W, C = h_in.shape
        h = GroupNorm(per_frame=self.per_frame_gn, fused=self.fused_gn,
                      dtype=self.dtype)(h_in)
        tokens = h.reshape(B, F, H * W, C)
        layer = AttnLayer(attn_heads=self.attn_heads, out_proj=self.out_proj,
                          use_flash=self.use_flash,
                          use_serving=self.use_serving, mesh=self.mesh,
                          dtype=self.dtype, param_dtype=self.param_dtype)
        if self.attn_type == "self":
            out = layer(q=tokens.reshape(B * F, H * W, C),
                        kv=tokens.reshape(B * F, H * W, C))
            out = out.reshape(B, F, H * W, C)
        elif self.attn_type == "cross":
            if F < 2:
                raise ValueError("cross-frame attention needs F >= 2")
            outs = []
            for i in range(F):
                others = [tokens[:, j] for j in range(F) if j != i]
                kv = jnp.concatenate(others, axis=1)  # (B, (F-1)·HW, C)
                outs.append(layer(q=tokens[:, i], kv=kv))
            out = jnp.stack(outs, axis=1)
        else:
            raise NotImplementedError(self.attn_type)
        out = out.reshape(B, F, H, W, C)
        return (out + h_in) * INV_SQRT2


class XUNetBlock(nn.Module):
    """ResnetBlock + optional (self-attn, cross-attn) pair.

    Reference: model/xunet.py:129-140.
    """

    features: int
    use_attn: bool = False
    attn_heads: int = 4
    attn_out_proj: bool = False
    attn_use_flash: bool = False
    attn_use_serving: bool = False
    attn_mesh: Optional[object] = None
    dropout: float = 0.0
    train: bool = False  # attribute (not call arg) so nn.remat needs no statics
    per_frame_gn: bool = True
    fused_gn: bool = False
    fused_epilogue: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, emb: jnp.ndarray) -> jnp.ndarray:
        kw = dict(per_frame_gn=self.per_frame_gn, fused_gn=self.fused_gn,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        attn_kw = dict(attn_heads=self.attn_heads, out_proj=self.attn_out_proj,
                       use_flash=self.attn_use_flash,
                       use_serving=self.attn_use_serving, mesh=self.attn_mesh,
                       **kw)
        h = ResnetBlock(features=self.features, dropout=self.dropout,
                        fused_epilogue=self.fused_epilogue,
                        **kw)(x, emb, train=self.train)
        if self.use_attn:
            h = AttnBlock(attn_type="self", **attn_kw)(h)
            if h.shape[1] >= 2:
                h = AttnBlock(attn_type="cross", **attn_kw)(h)
        return h
