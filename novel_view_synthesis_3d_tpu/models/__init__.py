from novel_view_synthesis_3d_tpu.models.rays import camera_rays  # noqa: F401
from novel_view_synthesis_3d_tpu.models.xunet import (  # noqa: F401
    ConditioningProcessor,
    XUNet,
)
