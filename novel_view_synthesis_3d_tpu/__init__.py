"""novel_view_synthesis_3d_tpu — a TPU-native framework for pose-conditional
novel view synthesis with diffusion models (3DiM-style X-UNet).

Built from scratch for JAX/XLA on TPU (jit / shard_map / NamedSharding /
Pallas), with the capability surface of the reference repo
`shiveshkhaitan/novel_view_synthesis_3d` (see SURVEY.md): X-UNet model,
DDPM training with classifier-free guidance, on-device ancestral sampling,
SRN ShapeNet dataset format, distributed data-parallel training, and
checkpoint/resume.
"""

__version__ = "0.1.0"

from novel_view_synthesis_3d_tpu.config import (  # noqa: F401
    Config,
    DataConfig,
    DiffusionConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    get_preset,
)
