"""Novel-view evaluation: sample views for held-out pairs, score PSNR/SSIM.

The reference has no evaluation path at all (its sampling.py displays images
in a blocking cv2 window, sampling.py:153-154, and computes nothing). This is
the quality-measurement loop the 3DiM paper's SRN-cars protocol implies:
condition on one view of an instance, synthesize other (ground-truth-posed)
views, and score the synthesis against the held-out real images.

Two protocols:
- ``single`` — every target view is sampled in one reverse process
  conditioned on the same fixed view (fast; one batched sampler call).
- ``autoregressive`` — the 3DiM paper protocol: targets are generated in
  sequence with stochastic conditioning over the growing pool of available
  views (sample/ddpm.py:autoregressive_generate), so later views are
  conditioned on earlier generated ones.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import Config
from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
from novel_view_synthesis_3d_tpu.diffusion.schedules import sampling_schedule
from novel_view_synthesis_3d_tpu.eval.metrics import fid, psnr, ssim
from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib
from novel_view_synthesis_3d_tpu.sample.ddpm import (
    autoregressive_generate,
    make_sampler,
    make_stochastic_sampler,
)


@dataclasses.dataclass
class EvalResult:
    psnr: float
    ssim: float
    num_views: int
    per_view_psnr: np.ndarray
    per_view_ssim: np.ndarray
    fid: Optional[float] = None
    # Honest labeling: the default Fréchet metric uses the deterministic
    # random-conv feature extractor (eval/metrics.py), which is valid for
    # relative comparisons but NOT comparable to published Inception-FID —
    # so it is reported as "fid_random". A caller who supplies a pretrained
    # feature_fn gets the plain "fid" key.
    fid_label: str = "fid_random"
    protocol: str = "single"
    # Relative output delta when the conditioning image is swapped across
    # instances (see cond_sensitivity). 0.0 means the model IGNORES its
    # conditioning image — the r2/r3 failure class (inert cross-frame
    # attention trains an unconditional pose-memorizer whose seen-pose
    # PSNR looks healthy). None when too few distinct instances to swap.
    cond_sens: Optional[float] = None

    def to_dict(self) -> dict:
        d = {
            "psnr": self.psnr,
            "ssim": self.ssim,
            "num_views": self.num_views,
            "protocol": self.protocol,
        }
        if self.fid is not None:
            d[self.fid_label] = self.fid
        if self.cond_sens is not None:
            d["cond_sens"] = self.cond_sens
        return d


def make_cond_sensitivity_fn(model, logsnr: float = 0.0):
    """Jitted conditioning-sensitivity probe: swap the cond image, measure
    the output delta.

    Returns fn(params, key, batch) -> scalar, where batch holds x/R1/t1/
    R2/t2/K/target (B ≥ 2, distinct conditioning images). The target is
    noised to the given logsnr (α = σ(logsnr); default 0.0 = mid-noise,
    α = ½), the denoiser is applied twice — once with the true
    conditioning images, once with them rolled by one along the batch
    axis (poses NOT rolled: only the image path is probed) — and the
    scalar is mean|Δout| / mean|out|.

    Cross-frame attention is the ONLY path from the conditioning image to
    the target-frame output (convs are per-frame, models/layers.py), so an
    inert-attention config — the r2/r3 postmortem class
    (results/RESULTS_r03.md) — yields EXACTLY 0.0 here while its seen-pose
    PSNR curve still looks healthy. A healthy conditioned model yields
    O(0.1–1). One forward pair per call: cheap enough for the in-loop
    probe at every eval point.
    """

    @jax.jit
    def fn(params, key, batch):
        target = batch["target"]
        B = target.shape[0]
        alpha = jax.nn.sigmoid(jnp.asarray(logsnr, jnp.float32))
        noise = jax.random.normal(key, target.shape)
        z = jnp.sqrt(alpha) * target + jnp.sqrt(1.0 - alpha) * noise
        mb = {k: batch[k] for k in ("x", "R1", "t1", "R2", "t2", "K")}
        mb["z"] = z
        mb["logsnr"] = jnp.full((B,), logsnr, jnp.float32)
        mask = jnp.ones((B,))
        out = model.apply({"params": params}, mb, cond_mask=mask,
                          train=False)
        swapped = dict(mb, x=jnp.roll(mb["x"], 1, axis=0))
        out_swap = model.apply({"params": params}, swapped, cond_mask=mask,
                               train=False)
        # (delta, scale) rather than the ratio: the ratio's degenerate
        # cases (vacuous swap, all-zero output) need host-side None
        # semantics — see cond_sensitivity.
        return (jnp.mean(jnp.abs(out - out_swap)),
                jnp.mean(jnp.abs(out)))

    return fn


# Below this output scale the ratio is meaningless, not alarming: a model
# whose output is ~identically zero (fresh zero-init head, collapsed run)
# would otherwise score delta/scale = 0/ε = 0.0 — the exact value documented
# as the inert-attention alarm.
_COND_SENS_MIN_SCALE = 1e-6


def cond_sensitivity(model, params, batch: dict, *, key,
                     logsnr: float = 0.0, fn=None) -> Optional[float]:
    """One-shot conditioning-sensitivity probe (see make_cond_sensitivity_fn).

    Returns None when the probe cannot distinguish pathology from
    degeneracy — fewer than 2 samples, all conditioning images identical
    (rolled == original ⇒ delta is 0 by construction), or an output that is
    itself ~0 (fresh zero-init head / collapsed run).

    `fn`: a cached make_cond_sensitivity_fn(model, logsnr) result; pass it
    from a loop (e.g. the trainer's in-loop probe) to avoid re-jitting —
    `model`/`logsnr` are ignored when given.
    """
    x = np.asarray(batch["x"])
    if x.shape[0] < 2 or not np.any(x != np.roll(x, 1, axis=0)):
        return None
    if fn is None:
        fn = make_cond_sensitivity_fn(model, logsnr)
    delta, scale = (float(v) for v in fn(params, key, batch))
    if scale < _COND_SENS_MIN_SCALE:
        return None
    return delta / scale


def evaluate_dataset(
    config: Config,
    model,
    params,
    dataset: SRNDataset,
    *,
    key: jax.Array,
    num_instances: Optional[int] = None,
    views_per_instance: int = 1,
    cond_view: int = 0,
    sample_steps: Optional[int] = None,
    batch_size: int = 8,
    compute_fid: bool = False,
    fid_feature_fn=None,
    protocol: str = "single",
    mesh=None,
    dump_comparisons: Optional[str] = None,
    max_comparisons: int = 8,
) -> EvalResult:
    """Sample novel views for held-out (cond, target) pairs and score them.

    For each of the first `num_instances` instances: condition on
    k = config.model.num_cond_frames CONSECUTIVE views starting at
    `cond_view` (k=1 is the reference's single-view protocol), synthesize
    `views_per_instance` of the remaining views at their ground-truth
    poses, and score PSNR/SSIM against the real images. The k cond views
    are excluded from the target pool, so an instance with V views yields
    at most V−k targets. Under protocol="autoregressive" all k views seed
    the stochastic-conditioning pool.

    `protocol`: "single" scores every target independently conditioned on
    the fixed view; "autoregressive" runs the 3DiM stochastic-conditioning
    protocol, where each generated view joins the conditioning pool for the
    next (`batch_size` then counts instances per sampler call).

    `fid_feature_fn`: optional pretrained (B,H,W,C)→(B,D) embedder; when
    given, the Fréchet metric is reported as "fid". Default None uses the
    deterministic random-conv features and reports "fid_random" (not
    comparable to published Inception-FID numbers).

    `mesh`: a jax Mesh — the conditioning batch is sharded over its 'data'
    axis and params replicated, so the reverse process runs data-parallel
    across chips (batch_size must be a multiple of the data-axis size).
    None = default-device sampling.

    `dump_comparisons`: optional PNG path — writes a
    [conditioning | ground truth | synthesis] row per scored pair (first
    `max_comparisons` pairs), the human-legible form of the PSNR table.
    """
    if protocol not in ("single", "autoregressive"):
        raise ValueError(f"unknown eval protocol {protocol!r}")
    dcfg = config.diffusion
    schedule = sampling_schedule(dcfg, sample_steps)
    if protocol == "autoregressive" and jax.process_count() > 1:
        # Every process would duplicate the full eval and race on any
        # output file (the batched pool/target inputs here are host-local).
        raise ValueError(
            "evaluate_dataset(protocol='autoregressive') is "
            "single-process only; on a pod, run eval on one host")
    if mesh is not None:
        if jax.process_count() > 1:
            # Every process assembles the FULL batch here; the multi-process
            # branch of shard_batch would treat it as a per-host shard and
            # P-plicate the work, and the sharded psnr/ssim outputs would
            # span non-addressable devices at device_get.
            raise ValueError(
                "evaluate_dataset(mesh=...) is single-process only; on a "
                "pod, run eval on one host (or mesh=None)")
        shards = mesh_lib.num_data_shards(mesh)
        if batch_size % shards != 0:
            raise ValueError(
                f"eval batch_size {batch_size} not divisible by the mesh "
                f"data axis ({shards})")
        params = mesh_lib.replicate(mesh, params)

    n_inst = (dataset.num_instances if num_instances is None
              else min(num_instances, dataset.num_instances))

    # Assemble (cond views, target views) per instance host-side. A k>1
    # model (model.num_cond_frames) is conditioned on k CONSECUTIVE views
    # starting at cond_view — the 3DiM multi-view conditioning the model
    # was trained with; k=1 keeps the reference's single-view protocol
    # (and the frame-axis-free record layout).
    k = config.model.num_cond_frames
    instances = []  # (x, R1, t1, K, [(target_img, target_pose)])
    for i in range(n_inst):
        inst = dataset.instances[i]
        cond_idx = [(cond_view + j) % len(inst) for j in range(k)]
        views = [inst.view(v) for v in cond_idx]
        if k == 1:
            x, pose1 = views[0]
            R1, t1 = pose1[:3, :3], pose1[:3, 3]
        else:
            x = np.stack([v[0] for v in views])
            R1 = np.stack([v[1][:3, :3] for v in views])
            t1 = np.stack([v[1][:3, 3] for v in views])
        others = [v for v in range(len(inst)) if v not in cond_idx]
        targets = [inst.view(v) for v in others[:views_per_instance]]
        if targets:
            instances.append((x, R1, t1, inst.K, targets))
    truths = [t for (_, _, _, _, targets) in instances for (t, _) in targets]
    if not truths:
        raise ValueError("no evaluation pairs (need ≥2 views per instance)")
    if compute_fid and len(truths) < 2:
        raise ValueError(
            "FID needs ≥2 evaluation pairs for a covariance estimate; "
            "raise num_instances/views_per_instance or drop compute_fid")

    # Standing conditioning-sensitivity probe (one forward pair over one
    # (cond, target) pair per instance — needs ≥2 distinct instances to
    # swap across). Runs before sampling so a cond_sens == 0.0 failure is
    # visible even if the (much slower) sampling loop is interrupted.
    sens = None
    if len(instances) >= 2:
        # Cap the probe batch: one pair per instance but no more than the
        # sampler's batch_size — a full-split eval (hundreds of instances)
        # must not stack them all into one jitted forward.
        probe = instances[:max(2, min(len(instances), batch_size))]
        sens_batch = jax.tree.map(jnp.asarray, {
            "x": np.stack([c[0] for c in probe]),
            "R1": np.stack([c[1] for c in probe]),
            "t1": np.stack([c[2] for c in probe]),
            "R2": np.stack([c[4][0][1][:3, :3] for c in probe]),
            "t2": np.stack([c[4][0][1][:3, 3] for c in probe]),
            "K": np.stack([c[3] for c in probe]),
            "target": np.stack([c[4][0][0] for c in probe]),
        })
        key, k_sens = jax.random.split(key)
        sens = cond_sensitivity(model, params, sens_batch, key=k_sens)

    all_psnr, all_ssim, all_imgs = [], [], []
    comparisons = []  # (cond, truth, pred) rows for dump_comparisons

    def add_comparison(cond_img, truth_img, pred_img):
        if dump_comparisons and len(comparisons) < max_comparisons:
            cond_img = np.asarray(cond_img)
            if cond_img.ndim == 4:  # k>1: show the first conditioning view
                cond_img = cond_img[0]
            comparisons.append((cond_img, np.asarray(truth_img),
                                np.asarray(pred_img)))

    def score(imgs, truth):
        all_psnr.append(np.asarray(jax.device_get(
            psnr(imgs, jnp.asarray(truth)))))
        all_ssim.append(np.asarray(jax.device_get(
            ssim(imgs, jnp.asarray(truth)))))
        if compute_fid:
            all_imgs.append(np.asarray(jax.device_get(imgs)))

    if protocol == "autoregressive":
        # 3DiM protocol: per instance, generate the target views in sequence
        # with stochastic conditioning over the pool of available views.
        # Batch instances together (autoregressive_generate is batched over
        # its leading axis); the pool length must match within a call, so a
        # short-tailed instance set falls back to the min target count. The
        # stochastic sampler is built ONCE and the tail chunk padded to
        # batch_size, so one compiled program serves every chunk.
        n_targets = min(len(t) for (_, _, _, _, t) in instances)
        if n_targets < views_per_instance:
            print(f"note: autoregressive eval truncated to {n_targets} "
                  f"target views per instance (requested "
                  f"{views_per_instance}; shortest instance bounds all)")
            truths = [t for (_, _, _, _, targets) in instances
                      for (t, _) in targets[:n_targets]]
        # A k>1 model's k conditioning views all seed the stochastic pool
        # (autoregressive_generate accepts (B, P0, …) pools natively);
        # k=1 keeps the paper's pool-of-one protocol.
        ar_sampler = make_stochastic_sampler(model, schedule, dcfg,
                                             max_pool=n_targets + k)
        for start in range(0, len(instances), batch_size):
            chunk = instances[start:start + batch_size]
            n = len(chunk)
            chunk = chunk + [chunk[-1]] * (batch_size - n)
            first_view = {
                "x": jnp.asarray(np.stack([c[0] for c in chunk])),
                "R1": jnp.asarray(np.stack([c[1] for c in chunk])),
                "t1": jnp.asarray(np.stack([c[2] for c in chunk])),
                "K": jnp.asarray(np.stack([c[3] for c in chunk])),
            }
            target_poses = {
                "R2": jnp.asarray(np.stack(
                    [[p[:3, :3] for (_, p) in c[4][:n_targets]]
                     for c in chunk])),
                "t2": jnp.asarray(np.stack(
                    [[p[:3, 3] for (_, p) in c[4][:n_targets]]
                     for c in chunk])),
            }
            if mesh is not None:
                # Shard the instance batch over the mesh 'data' axis; the
                # growing view pool inside autoregressive_generate inherits
                # the sharding from these inputs, so every reverse process
                # runs data-parallel across chips.
                first_view = mesh_lib.shard_batch(mesh, first_view)
                target_poses = mesh_lib.shard_batch(mesh, target_poses)
            truth = np.stack([[t for (t, _) in c[4][:n_targets]]
                              for c in chunk[:n]])  # (n, N, H, W, 3)
            key, k_s = jax.random.split(key)
            imgs = autoregressive_generate(
                model, schedule, dcfg, params, k_s, first_view, target_poses,
                max_pool=n_targets + k, sampler=ar_sampler)
            if dump_comparisons and len(comparisons) < max_comparisons:
                per_inst = np.asarray(jax.device_get(imgs[:n]))
                for j in range(n):
                    for ti in range(n_targets):
                        add_comparison(chunk[j][0], truth[j][ti],
                                       per_inst[j][ti])
            imgs = imgs[:n].reshape((-1,) + imgs.shape[2:])
            score(imgs, truth.reshape((-1,) + truth.shape[2:]))
    else:
        # Flatten to (cond, target) pairs; batch through the sampler (pad
        # the tail so one compilation serves all).
        sampler = make_sampler(model, schedule, dcfg)
        conds = []
        for (x, R1, t1, K, targets) in instances:
            for (_, pose2) in targets:
                conds.append({
                    "x": x, "R1": R1, "t1": t1,
                    "R2": pose2[:3, :3], "t2": pose2[:3, 3], "K": K,
                })
        for start in range(0, len(conds), batch_size):
            chunk = conds[start:start + batch_size]
            truth = np.stack(truths[start:start + batch_size])
            n = len(chunk)
            pad = batch_size - n
            stacked = {k: np.stack([c[k] for c in chunk] +
                                   [chunk[-1][k]] * pad)
                       for k in chunk[0]}
            key, k_s = jax.random.split(key)
            device_batch = jax.tree.map(jnp.asarray, stacked)
            if mesh is not None:
                device_batch = mesh_lib.shard_batch(mesh, device_batch)
            imgs = sampler(params, k_s, device_batch)
            imgs = imgs[:n]
            if dump_comparisons and len(comparisons) < max_comparisons:
                preds = np.asarray(jax.device_get(imgs))
                for j in range(n):
                    add_comparison(chunk[j]["x"], truth[j], preds[j])
            score(imgs, truth)

    if dump_comparisons and comparisons:
        from novel_view_synthesis_3d_tpu.utils.images import save_image_grid

        rows = np.stack([im for trip in comparisons for im in trip])
        save_image_grid(rows, dump_comparisons, cols=3)

    per_psnr = np.concatenate(all_psnr)
    per_ssim = np.concatenate(all_ssim)
    fid_value = None
    if compute_fid:
        fid_value = fid(np.stack(truths), np.concatenate(all_imgs),
                        feature_fn=fid_feature_fn)
    return EvalResult(
        psnr=float(per_psnr.mean()),
        ssim=float(per_ssim.mean()),
        num_views=len(per_psnr),
        per_view_psnr=per_psnr,
        per_view_ssim=per_ssim,
        fid=fid_value,
        fid_label="fid" if fid_feature_fn is not None else "fid_random",
        protocol=protocol,
        cond_sens=sens,
    )
