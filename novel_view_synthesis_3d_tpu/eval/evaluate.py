"""Novel-view evaluation: sample views for held-out pairs, score PSNR/SSIM.

The reference has no evaluation path at all (its sampling.py displays images
in a blocking cv2 window, sampling.py:153-154, and computes nothing). This is
the quality-measurement loop the 3DiM paper's SRN-cars protocol implies:
condition on one view of an instance, synthesize another (ground-truth-posed)
view, and score the synthesis against the held-out real image.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import Config
from novel_view_synthesis_3d_tpu.data.srn import SRNDataset
from novel_view_synthesis_3d_tpu.diffusion.schedules import sampling_schedule
from novel_view_synthesis_3d_tpu.eval.metrics import fid, psnr, ssim
from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib
from novel_view_synthesis_3d_tpu.sample.ddpm import make_sampler


@dataclasses.dataclass
class EvalResult:
    psnr: float
    ssim: float
    num_views: int
    per_view_psnr: np.ndarray
    per_view_ssim: np.ndarray
    fid: Optional[float] = None

    def to_dict(self) -> dict:
        d = {
            "psnr": self.psnr,
            "ssim": self.ssim,
            "num_views": self.num_views,
        }
        if self.fid is not None:
            d["fid"] = self.fid
        return d


def evaluate_dataset(
    config: Config,
    model,
    params,
    dataset: SRNDataset,
    *,
    key: jax.Array,
    num_instances: Optional[int] = None,
    views_per_instance: int = 1,
    cond_view: int = 0,
    sample_steps: Optional[int] = None,
    batch_size: int = 8,
    compute_fid: bool = False,
    mesh=None,
) -> EvalResult:
    """Sample novel views for held-out (cond, target) pairs and score them.

    For each of the first `num_instances` instances: condition on view
    `cond_view`, synthesize `views_per_instance` other views at their
    ground-truth poses, and score PSNR/SSIM against the real images.

    `mesh`: a jax Mesh — the conditioning batch is sharded over its 'data'
    axis and params replicated, so the reverse process runs data-parallel
    across chips (batch_size must be a multiple of the data-axis size).
    None = default-device sampling.
    """
    dcfg = config.diffusion
    schedule = sampling_schedule(dcfg, sample_steps)
    sampler = make_sampler(model, schedule, dcfg)
    if mesh is not None:
        if jax.process_count() > 1:
            # Every process assembles the FULL batch here; the multi-process
            # branch of shard_batch would treat it as a per-host shard and
            # P-plicate the work, and the sharded psnr/ssim outputs would
            # span non-addressable devices at device_get.
            raise ValueError(
                "evaluate_dataset(mesh=...) is single-process only; on a "
                "pod, run eval on one host (or mesh=None)")
        shards = mesh_lib.num_data_shards(mesh)
        if batch_size % shards != 0:
            raise ValueError(
                f"eval batch_size {batch_size} not divisible by the mesh "
                f"data axis ({shards})")
        params = mesh_lib.replicate(mesh, params)

    n_inst = (dataset.num_instances if num_instances is None
              else min(num_instances, dataset.num_instances))

    # Assemble all (cond, target) pairs host-side.
    conds, truths = [], []
    for i in range(n_inst):
        inst = dataset.instances[i]
        x, pose1 = inst.view(cond_view % len(inst))
        others = [v for v in range(len(inst)) if v != cond_view % len(inst)]
        for v in others[:views_per_instance]:
            target, pose2 = inst.view(v)
            conds.append({
                "x": x, "R1": pose1[:3, :3], "t1": pose1[:3, 3],
                "R2": pose2[:3, :3], "t2": pose2[:3, 3], "K": inst.K,
            })
            truths.append(target)
    if not conds:
        raise ValueError("no evaluation pairs (need ≥2 views per instance)")
    if compute_fid and len(conds) < 2:
        raise ValueError(
            "FID needs ≥2 evaluation pairs for a covariance estimate; "
            "raise num_instances/views_per_instance or drop compute_fid")

    # Batch through the sampler (pad the tail so one compilation serves all).
    all_psnr, all_ssim, all_imgs = [], [], []
    for start in range(0, len(conds), batch_size):
        chunk = conds[start:start + batch_size]
        truth = np.stack(truths[start:start + batch_size])
        n = len(chunk)
        pad = batch_size - n
        stacked = {k: np.stack([c[k] for c in chunk] +
                               [chunk[-1][k]] * pad)
                   for k in chunk[0]}
        key, k_s = jax.random.split(key)
        device_batch = jax.tree.map(jnp.asarray, stacked)
        if mesh is not None:
            device_batch = mesh_lib.shard_batch(mesh, device_batch)
        imgs = sampler(params, k_s, device_batch)
        imgs = imgs[:n]
        all_psnr.append(np.asarray(jax.device_get(
            psnr(imgs, jnp.asarray(truth)))))
        all_ssim.append(np.asarray(jax.device_get(
            ssim(imgs, jnp.asarray(truth)))))
        if compute_fid:
            all_imgs.append(np.asarray(jax.device_get(imgs)))

    per_psnr = np.concatenate(all_psnr)
    per_ssim = np.concatenate(all_ssim)
    fid_value = None
    if compute_fid:
        fid_value = fid(np.stack(truths), np.concatenate(all_imgs))
    return EvalResult(
        psnr=float(per_psnr.mean()),
        ssim=float(per_ssim.mean()),
        num_views=len(per_psnr),
        per_view_psnr=per_psnr,
        per_view_ssim=per_ssim,
        fid=fid_value,
    )
