from novel_view_synthesis_3d_tpu.eval.metrics import psnr, ssim  # noqa: F401
