"""Image quality metrics: PSNR and SSIM, pure jnp (jit/vmap-able).

The reference repo computes NO quality metrics anywhere (SURVEY.md §6); the
3DiM paper (arXiv 2210.04628, linked at /root/reference/README.md:2) reports
PSNR/SSIM on SRN ShapeNet cars — these are the paper-parity implementations:
PSNR over the full image, SSIM per Wang et al. 2004 with the standard 11×11
Gaussian window (σ=1.5), K1=0.01, K2=0.03.

Images are NHWC; `data_range` defaults to 2.0 (model space [-1, 1]).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def psnr(pred: jnp.ndarray, target: jnp.ndarray,
         data_range: float = 2.0) -> jnp.ndarray:
    """Peak signal-to-noise ratio in dB, per batch element.

    pred/target: (..., H, W, C); reduces over the last three axes.
    """
    mse = jnp.mean(jnp.square(pred - target), axis=(-3, -2, -1))
    return 10.0 * jnp.log10((data_range ** 2) / jnp.maximum(mse, 1e-20))


def _gaussian_kernel(size: int, sigma: float) -> np.ndarray:
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    g = np.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / g.sum()
    return np.outer(g, g)


def _filter2d(img: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Depthwise VALID 2-D filter on (B, H, W, C)."""
    C = img.shape[-1]
    k = jnp.broadcast_to(kernel[:, :, None, None], kernel.shape + (1, C))
    # NHWC, HWIO, depthwise via feature_group_count=C.
    return jax.lax.conv_general_dilated(
        img, k.astype(img.dtype), window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=C)


def ssim(pred: jnp.ndarray, target: jnp.ndarray, data_range: float = 2.0,
         window_size: int = 11, sigma: float = 1.5,
         k1: float = 0.01, k2: float = 0.03) -> jnp.ndarray:
    """Mean structural similarity per batch element (Wang et al. 2004).

    pred/target: (B, H, W, C) with H, W ≥ window_size. Gaussian-windowed
    means/variances, VALID padding (edge pixels excluded, as in the standard
    implementation).
    """
    if pred.ndim == 3:
        pred, target = pred[None], target[None]
    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    kernel = jnp.asarray(_gaussian_kernel(window_size, sigma))

    mu_x = _filter2d(pred, kernel)
    mu_y = _filter2d(target, kernel)
    mu_x2, mu_y2, mu_xy = mu_x * mu_x, mu_y * mu_y, mu_x * mu_y
    sigma_x2 = _filter2d(pred * pred, kernel) - mu_x2
    sigma_y2 = _filter2d(target * target, kernel) - mu_y2
    sigma_xy = _filter2d(pred * target, kernel) - mu_xy

    ssim_map = ((2.0 * mu_xy + c1) * (2.0 * sigma_xy + c2)) / (
        (mu_x2 + mu_y2 + c1) * (sigma_x2 + sigma_y2 + c2))
    return jnp.mean(ssim_map, axis=(-3, -2, -1))


# ---------------------------------------------------------------------------
# Multi-view consistency (trajectory serving / stochastic conditioning)
# ---------------------------------------------------------------------------
def adjacent_psnr(frames: jnp.ndarray,
                  data_range: float = 2.0) -> jnp.ndarray:
    """PSNR (dB) between each adjacent frame pair of an ordered orbit.

    frames: (N, H, W, C) with N >= 2 (or (B, N, H, W, C); the pair axis
    is -4 either way). On a smooth orbit, adjacent views overlap almost
    entirely, so adjacent-frame PSNR is a geometry-free proxy for 3D
    consistency: a model whose autoregressive frames drift (the failure
    mode stochastic conditioning exists to prevent, 3DiM §3.2) scores
    low even when each frame is individually plausible — which is why
    the registry gate can use it to judge TRAJECTORY quality where
    single-frame PSNR sees nothing wrong.
    """
    if frames.shape[-4] < 2:
        raise ValueError(
            f"adjacent_psnr needs >= 2 frames, got {frames.shape[-4]}")
    a = jnp.moveaxis(frames, -4, 0)
    return psnr(a[:-1], a[1:], data_range=data_range)


def multi_view_consistency(frames: jnp.ndarray,
                           data_range: float = 2.0) -> dict:
    """Orbit consistency summary: {'mean_db', 'min_db', 'per_pair'}.

    `mean_db` is the gate/eval headline (average adjacent-frame PSNR);
    `min_db` flags a single catastrophic frame a mean would smooth over.
    """
    pairs = adjacent_psnr(frames, data_range=data_range)
    return {
        "mean_db": float(jnp.mean(pairs)),
        "min_db": float(jnp.min(pairs)),
        "per_pair": np.asarray(pairs),
    }


# ---------------------------------------------------------------------------
# FID (Fréchet distance between feature distributions)
# ---------------------------------------------------------------------------
#
# The 3DiM paper reports FID on SRN cars. Canonical FID embeds images with a
# pretrained InceptionV3 pool3 head; pretrained weights are not available in
# this environment (no network egress), so the Fréchet math below is exact
# and the feature extractor is PLUGGABLE: pass `feature_fn` mapping a (B, H,
# W, C) image batch to (B, D) features (an Inception/CLIP embedder when
# weights are at hand). The default is a deterministic random-projection conv
# net — self-consistent across runs of this framework (fixed seed) and valid
# for relative comparisons between checkpoints, but NOT numerically
# comparable to published Inception-FID numbers.

def feature_stats(feats: jnp.ndarray):
    """(B, D) features → (mean (D,), covariance (D, D)). B ≥ 2 required."""
    feats = jnp.asarray(feats, jnp.float64 if jax.config.jax_enable_x64
                        else jnp.float32)
    mu = jnp.mean(feats, axis=0)
    centered = feats - mu
    sigma = centered.T @ centered / (feats.shape[0] - 1)
    return mu, sigma


def frechet_distance(mu1: jnp.ndarray, sigma1: jnp.ndarray,
                     mu2: jnp.ndarray, sigma2: jnp.ndarray,
                     eps: float = 1e-6) -> jnp.ndarray:
    """Fréchet distance ‖μ₁−μ₂‖² + tr(Σ₁+Σ₂−2(Σ₁Σ₂)^½) between Gaussians.

    tr((Σ₁Σ₂)^½) is computed as Σᵢ√λᵢ of the symmetric PSD matrix
    Σ₁^½ Σ₂ Σ₁^½ (same spectrum as Σ₁Σ₂), which keeps everything in
    eigvalsh territory — no non-symmetric sqrtm needed.
    """
    d = mu1.shape[-1]
    ident = jnp.eye(d, dtype=sigma1.dtype)
    sigma1 = sigma1 + eps * ident
    sigma2 = sigma2 + eps * ident

    w1, v1 = jnp.linalg.eigh(sigma1)
    sqrt_sigma1 = (v1 * jnp.sqrt(jnp.maximum(w1, 0.0))) @ v1.T
    inner = sqrt_sigma1 @ sigma2 @ sqrt_sigma1
    inner = 0.5 * (inner + inner.T)
    ev = jnp.maximum(jnp.linalg.eigvalsh(inner), 0.0)
    tr_sqrt = jnp.sum(jnp.sqrt(ev))

    diff = mu1 - mu2
    return (diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2)
            - 2.0 * tr_sqrt)


def make_random_conv_features(feature_dim: int = 512, seed: int = 0):
    """Deterministic random-projection conv feature extractor.

    Three stride-2 3×3 conv + leaky-relu stages (fixed Gaussian kernels from
    `seed`), global mean+std pooling per channel, then a fixed random
    projection to `feature_dim`. Captures multi-scale local statistics well
    enough to separate image distributions; see module note on comparability.
    """
    key = jax.random.PRNGKey(seed)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    chans = (3, 64, 128, 256)
    kernels = []
    for kk, cin, cout in zip((k1, k2, k3), chans[:-1], chans[1:]):
        fan_in = 3 * 3 * cin
        kernels.append(jax.random.normal(kk, (3, 3, cin, cout),
                                         jnp.float32) / np.sqrt(fan_in))
    proj = jax.random.normal(k4, (2 * chans[-1], feature_dim),
                             jnp.float32) / np.sqrt(2 * chans[-1])

    @jax.jit
    def feature_fn(images: jnp.ndarray) -> jnp.ndarray:
        h = jnp.asarray(images, jnp.float32)
        for k in kernels:
            h = jax.lax.conv_general_dilated(
                h, k, window_strides=(2, 2), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            h = jax.nn.leaky_relu(h, 0.2)
        mean = jnp.mean(h, axis=(1, 2))
        std = jnp.std(h, axis=(1, 2))
        return jnp.concatenate([mean, std], axis=-1) @ proj

    return feature_fn


def fid(real: jnp.ndarray, fake: jnp.ndarray, *, feature_fn=None,
        batch_size: int = 64) -> float:
    """Fréchet distance between two image sets (B, H, W, C) in [-1, 1].

    `feature_fn` defaults to the deterministic random-conv extractor; pass a
    pretrained embedder for Inception-comparable numbers.
    """
    if real.shape[0] < 2 or fake.shape[0] < 2:
        raise ValueError(
            f"FID needs ≥2 images per set for a covariance estimate, got "
            f"{real.shape[0]} real / {fake.shape[0]} fake")
    if feature_fn is None:
        feature_fn = make_random_conv_features()

    def embed(images):
        out = []
        for start in range(0, images.shape[0], batch_size):
            out.append(np.asarray(jax.device_get(
                feature_fn(jnp.asarray(images[start:start + batch_size])))))
        return jnp.asarray(np.concatenate(out))

    mu_r, sig_r = feature_stats(embed(real))
    mu_f, sig_f = feature_stats(embed(fake))
    return float(frechet_distance(mu_r, sig_r, mu_f, sig_f))
