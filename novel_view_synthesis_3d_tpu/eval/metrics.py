"""Image quality metrics: PSNR and SSIM, pure jnp (jit/vmap-able).

The reference repo computes NO quality metrics anywhere (SURVEY.md §6); the
3DiM paper (arXiv 2210.04628, linked at /root/reference/README.md:2) reports
PSNR/SSIM on SRN ShapeNet cars — these are the paper-parity implementations:
PSNR over the full image, SSIM per Wang et al. 2004 with the standard 11×11
Gaussian window (σ=1.5), K1=0.01, K2=0.03.

Images are NHWC; `data_range` defaults to 2.0 (model space [-1, 1]).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def psnr(pred: jnp.ndarray, target: jnp.ndarray,
         data_range: float = 2.0) -> jnp.ndarray:
    """Peak signal-to-noise ratio in dB, per batch element.

    pred/target: (..., H, W, C); reduces over the last three axes.
    """
    mse = jnp.mean(jnp.square(pred - target), axis=(-3, -2, -1))
    return 10.0 * jnp.log10((data_range ** 2) / jnp.maximum(mse, 1e-20))


def _gaussian_kernel(size: int, sigma: float) -> np.ndarray:
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    g = np.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / g.sum()
    return np.outer(g, g)


def _filter2d(img: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Depthwise VALID 2-D filter on (B, H, W, C)."""
    C = img.shape[-1]
    k = jnp.broadcast_to(kernel[:, :, None, None], kernel.shape + (1, C))
    # NHWC, HWIO, depthwise via feature_group_count=C.
    return jax.lax.conv_general_dilated(
        img, k.astype(img.dtype), window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=C)


def ssim(pred: jnp.ndarray, target: jnp.ndarray, data_range: float = 2.0,
         window_size: int = 11, sigma: float = 1.5,
         k1: float = 0.01, k2: float = 0.03) -> jnp.ndarray:
    """Mean structural similarity per batch element (Wang et al. 2004).

    pred/target: (B, H, W, C) with H, W ≥ window_size. Gaussian-windowed
    means/variances, VALID padding (edge pixels excluded, as in the standard
    implementation).
    """
    if pred.ndim == 3:
        pred, target = pred[None], target[None]
    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    kernel = jnp.asarray(_gaussian_kernel(window_size, sigma))

    mu_x = _filter2d(pred, kernel)
    mu_y = _filter2d(target, kernel)
    mu_x2, mu_y2, mu_xy = mu_x * mu_x, mu_y * mu_y, mu_x * mu_y
    sigma_x2 = _filter2d(pred * pred, kernel) - mu_x2
    sigma_y2 = _filter2d(target * target, kernel) - mu_y2
    sigma_xy = _filter2d(pred * target, kernel) - mu_xy

    ssim_map = ((2.0 * mu_xy + c1) * (2.0 * sigma_xy + c2)) / (
        (mu_x2 + mu_y2 + c1) * (sigma_x2 + sigma_y2 + c2))
    return jnp.mean(ssim_map, axis=(-3, -2, -1))
