"""InceptionV3 feature extractor for paper-comparable FID (pure JAX).

The 3DiM SRN-cars protocol (SURVEY.md §6) reports Fréchet distances over
InceptionV3 pool3 features (2048-d). This module implements the graph used
by the standard `pytorch-fid` package — torchvision's InceptionV3 with the
three FID-specific quirks of the original TF-slim export:

  * every in-block 3×3 stride-1 average pool uses count_include_pad=False;
  * Mixed_7c's pooling branch uses a MAX pool (FIDInceptionE_2);
  * inputs are bilinearly resized to 299×299 (half-pixel centers,
    align_corners=False) and normalized to [-1, 1].

Weights are NOT bundled (this environment has no network egress and no
cached checkpoint): `load_inception_features(npz)` builds the feature_fn
from an .npz produced by `tools/convert_inception.py` (which reads the
public `pt_inception-2015-12-05` state_dict with torch and re-keys
nothing — the npz uses the state_dict key names verbatim). Until a user
supplies weights, eval falls back to the honestly-labeled random-conv
Fréchet metric (eval/metrics.py "fid_random").

The reference has no quality evaluation at all (its sampling.py only
displays images; SURVEY.md §3.4).
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

BN_EPS = 0.001
FEATURE_DIM = 2048

# ---------------------------------------------------------------------------
# Declarative conv table: name -> (cin, cout, (kh, kw), (sh, sw), (ph, pw)).
# Names are the pytorch-fid/torchvision module paths; the npz holds
# "<name>.conv.weight" (O,I,H,W) and "<name>.bn.{weight,bias,running_mean,
# running_var}" per entry.
# ---------------------------------------------------------------------------


def _block_a(prefix: str, cin: int, pool: int) -> Dict[str, tuple]:
    return {
        f"{prefix}.branch1x1": (cin, 64, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch5x5_1": (cin, 48, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch5x5_2": (48, 64, (5, 5), (1, 1), (2, 2)),
        f"{prefix}.branch3x3dbl_1": (cin, 64, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3dbl_2": (64, 96, (3, 3), (1, 1), (1, 1)),
        f"{prefix}.branch3x3dbl_3": (96, 96, (3, 3), (1, 1), (1, 1)),
        f"{prefix}.branch_pool": (cin, pool, (1, 1), (1, 1), (0, 0)),
    }


def _block_b(prefix: str, cin: int) -> Dict[str, tuple]:
    return {
        f"{prefix}.branch3x3": (cin, 384, (3, 3), (2, 2), (0, 0)),
        f"{prefix}.branch3x3dbl_1": (cin, 64, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3dbl_2": (64, 96, (3, 3), (1, 1), (1, 1)),
        f"{prefix}.branch3x3dbl_3": (96, 96, (3, 3), (2, 2), (0, 0)),
    }


def _block_c(prefix: str, cin: int, c7: int) -> Dict[str, tuple]:
    return {
        f"{prefix}.branch1x1": (cin, 192, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch7x7_1": (cin, c7, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch7x7_2": (c7, c7, (1, 7), (1, 1), (0, 3)),
        f"{prefix}.branch7x7_3": (c7, 192, (7, 1), (1, 1), (3, 0)),
        f"{prefix}.branch7x7dbl_1": (cin, c7, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch7x7dbl_2": (c7, c7, (7, 1), (1, 1), (3, 0)),
        f"{prefix}.branch7x7dbl_3": (c7, c7, (1, 7), (1, 1), (0, 3)),
        f"{prefix}.branch7x7dbl_4": (c7, c7, (7, 1), (1, 1), (3, 0)),
        f"{prefix}.branch7x7dbl_5": (c7, 192, (1, 7), (1, 1), (0, 3)),
        f"{prefix}.branch_pool": (cin, 192, (1, 1), (1, 1), (0, 0)),
    }


def _block_d(prefix: str, cin: int) -> Dict[str, tuple]:
    return {
        f"{prefix}.branch3x3_1": (cin, 192, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3_2": (192, 320, (3, 3), (2, 2), (0, 0)),
        f"{prefix}.branch7x7x3_1": (cin, 192, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch7x7x3_2": (192, 192, (1, 7), (1, 1), (0, 3)),
        f"{prefix}.branch7x7x3_3": (192, 192, (7, 1), (1, 1), (3, 0)),
        f"{prefix}.branch7x7x3_4": (192, 192, (3, 3), (2, 2), (0, 0)),
    }


def _block_e(prefix: str, cin: int) -> Dict[str, tuple]:
    return {
        f"{prefix}.branch1x1": (cin, 320, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3_1": (cin, 384, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3_2a": (384, 384, (1, 3), (1, 1), (0, 1)),
        f"{prefix}.branch3x3_2b": (384, 384, (3, 1), (1, 1), (1, 0)),
        f"{prefix}.branch3x3dbl_1": (cin, 448, (1, 1), (1, 1), (0, 0)),
        f"{prefix}.branch3x3dbl_2": (448, 384, (3, 3), (1, 1), (1, 1)),
        f"{prefix}.branch3x3dbl_3a": (384, 384, (1, 3), (1, 1), (0, 1)),
        f"{prefix}.branch3x3dbl_3b": (384, 384, (3, 1), (1, 1), (1, 0)),
        f"{prefix}.branch_pool": (cin, 192, (1, 1), (1, 1), (0, 0)),
    }


def conv_table() -> Dict[str, tuple]:
    t: Dict[str, tuple] = {
        "Conv2d_1a_3x3": (3, 32, (3, 3), (2, 2), (0, 0)),
        "Conv2d_2a_3x3": (32, 32, (3, 3), (1, 1), (0, 0)),
        "Conv2d_2b_3x3": (32, 64, (3, 3), (1, 1), (1, 1)),
        "Conv2d_3b_1x1": (64, 80, (1, 1), (1, 1), (0, 0)),
        "Conv2d_4a_3x3": (80, 192, (3, 3), (1, 1), (0, 0)),
    }
    t.update(_block_a("Mixed_5b", 192, 32))
    t.update(_block_a("Mixed_5c", 256, 64))
    t.update(_block_a("Mixed_5d", 288, 64))
    t.update(_block_b("Mixed_6a", 288))
    t.update(_block_c("Mixed_6b", 768, 128))
    t.update(_block_c("Mixed_6c", 768, 160))
    t.update(_block_c("Mixed_6d", 768, 160))
    t.update(_block_c("Mixed_6e", 768, 192))
    t.update(_block_d("Mixed_7a", 768))
    t.update(_block_e("Mixed_7b", 1280))
    t.update(_block_e("Mixed_7c", 2048))
    return t


def expected_param_shapes() -> Dict[str, Tuple[int, ...]]:
    """state_dict key -> shape for every tensor the npz must carry.

    Conv weights use the torch (O, I, H, W) layout — the loader does the
    HWIO transpose — so a converter can dump the state_dict unmodified.
    """
    shapes: Dict[str, Tuple[int, ...]] = {}
    for name, (cin, cout, (kh, kw), _, _) in conv_table().items():
        shapes[f"{name}.conv.weight"] = (cout, cin, kh, kw)
        for p in ("weight", "bias", "running_mean", "running_var"):
            shapes[f"{name}.bn.{p}"] = (cout,)
    return shapes


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _avg_pool_3x3_nopad(x: jnp.ndarray) -> jnp.ndarray:
    """3×3 stride-1 SAME average pool with count_include_pad=False —
    the FID quirk: border windows divide by the number of VALID taps."""
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1), "SAME")
    counts = jax.lax.reduce_window(
        jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None], 0.0, jax.lax.add,
        (1, 3, 3, 1), (1, 1, 1, 1), "SAME")
    return summed / counts


def _max_pool(x: jnp.ndarray, window: int, stride: int,
              padding: str = "VALID") -> jnp.ndarray:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, window, window, 1),
        (1, stride, stride, 1), padding)


def _make_cbr(params: dict, table: Dict[str, tuple]):
    """conv+bn+relu by table name; BN folded into scale/shift at load."""

    def cbr(name: str, x: jnp.ndarray) -> jnp.ndarray:
        _, _, _, stride, (ph, pw) = table[name]
        w, scale, shift = params[name]
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jax.nn.relu(y * scale + shift)

    return cbr


def _forward(params: dict, images: jnp.ndarray) -> jnp.ndarray:
    """images (B, H, W, 3) in [-1, 1] -> pool3 features (B, 2048)."""
    table = conv_table()
    cbr = _make_cbr(params, table)
    # antialias=False: pytorch-fid's F.interpolate applies no antialias
    # filter, and jax.image.resize defaults to antialias=True — which
    # silently diverges on DOWNsampling (inputs larger than 299px).
    x = jax.image.resize(
        jnp.asarray(images, jnp.float32),
        (images.shape[0], 299, 299, images.shape[-1]), "bilinear",
        antialias=False)

    x = cbr("Conv2d_1a_3x3", x)
    x = cbr("Conv2d_2a_3x3", x)
    x = cbr("Conv2d_2b_3x3", x)
    x = _max_pool(x, 3, 2)
    x = cbr("Conv2d_3b_1x1", x)
    x = cbr("Conv2d_4a_3x3", x)
    x = _max_pool(x, 3, 2)

    def block_a(p, x):
        b1 = cbr(f"{p}.branch1x1", x)
        b5 = cbr(f"{p}.branch5x5_2", cbr(f"{p}.branch5x5_1", x))
        b3 = cbr(f"{p}.branch3x3dbl_3",
                 cbr(f"{p}.branch3x3dbl_2", cbr(f"{p}.branch3x3dbl_1", x)))
        bp = cbr(f"{p}.branch_pool", _avg_pool_3x3_nopad(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)

    def block_b(p, x):
        b3 = cbr(f"{p}.branch3x3", x)
        bd = cbr(f"{p}.branch3x3dbl_3",
                 cbr(f"{p}.branch3x3dbl_2", cbr(f"{p}.branch3x3dbl_1", x)))
        return jnp.concatenate([b3, bd, _max_pool(x, 3, 2)], axis=-1)

    def block_c(p, x):
        b1 = cbr(f"{p}.branch1x1", x)
        b7 = cbr(f"{p}.branch7x7_3",
                 cbr(f"{p}.branch7x7_2", cbr(f"{p}.branch7x7_1", x)))
        bd = x
        for i in range(1, 6):
            bd = cbr(f"{p}.branch7x7dbl_{i}", bd)
        bp = cbr(f"{p}.branch_pool", _avg_pool_3x3_nopad(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)

    def block_d(p, x):
        b3 = cbr(f"{p}.branch3x3_2", cbr(f"{p}.branch3x3_1", x))
        b7 = x
        for i in range(1, 5):
            b7 = cbr(f"{p}.branch7x7x3_{i}", b7)
        return jnp.concatenate([b3, b7, _max_pool(x, 3, 2)], axis=-1)

    def block_e(p, x, pool_max: bool):
        b1 = cbr(f"{p}.branch1x1", x)
        b3 = cbr(f"{p}.branch3x3_1", x)
        b3 = jnp.concatenate([cbr(f"{p}.branch3x3_2a", b3),
                              cbr(f"{p}.branch3x3_2b", b3)], axis=-1)
        bd = cbr(f"{p}.branch3x3dbl_2", cbr(f"{p}.branch3x3dbl_1", x))
        bd = jnp.concatenate([cbr(f"{p}.branch3x3dbl_3a", bd),
                              cbr(f"{p}.branch3x3dbl_3b", bd)], axis=-1)
        pooled = (_max_pool(x, 3, 1, "SAME") if pool_max
                  else _avg_pool_3x3_nopad(x))
        bp = cbr(f"{p}.branch_pool", pooled)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)

    x = block_a("Mixed_5b", x)
    x = block_a("Mixed_5c", x)
    x = block_a("Mixed_5d", x)
    x = block_b("Mixed_6a", x)
    x = block_c("Mixed_6b", x)
    x = block_c("Mixed_6c", x)
    x = block_c("Mixed_6d", x)
    x = block_e("Mixed_7b", block_d("Mixed_7a", block_c("Mixed_6e", x)),
                pool_max=False)
    # FIDInceptionE_2: the TF-slim export's LAST block pools with MAX.
    x = block_e("Mixed_7c", x, pool_max=True)
    return jnp.mean(x, axis=(1, 2))  # global pool3 -> (B, 2048)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _fold_params(raw: Dict[str, np.ndarray]) -> dict:
    """Validate against expected_param_shapes and fold BN into per-channel
    scale/shift: y = conv(x)·scale + shift with
    scale = γ/√(σ²+ε), shift = β − μ·scale."""
    expected = expected_param_shapes()
    missing = sorted(set(expected) - set(raw))
    if missing:
        raise ValueError(
            f"inception weights missing {len(missing)} tensors "
            f"(first: {missing[:3]}); expected the pytorch-fid "
            "state_dict key set — regenerate with tools/convert_inception.py")
    params = {}
    for name, (cin, cout, (kh, kw), _, _) in conv_table().items():
        w = np.asarray(raw[f"{name}.conv.weight"], np.float32)
        if w.shape != (cout, cin, kh, kw):
            raise ValueError(
                f"{name}.conv.weight has shape {w.shape}, expected "
                f"{(cout, cin, kh, kw)}")
        gamma = np.asarray(raw[f"{name}.bn.weight"], np.float32)
        beta = np.asarray(raw[f"{name}.bn.bias"], np.float32)
        mean = np.asarray(raw[f"{name}.bn.running_mean"], np.float32)
        var = np.asarray(raw[f"{name}.bn.running_var"], np.float32)
        for arr, p in ((gamma, "bn.weight"), (beta, "bn.bias"),
                       (mean, "bn.running_mean"), (var, "bn.running_var")):
            if arr.shape != (cout,):
                raise ValueError(
                    f"{name}.{p} has shape {arr.shape}, expected {(cout,)}")
        scale = gamma / np.sqrt(var + BN_EPS)
        shift = beta - mean * scale
        params[name] = (jnp.asarray(w.transpose(2, 3, 1, 0)),  # OIHW->HWIO
                        jnp.asarray(scale), jnp.asarray(shift))
    return params


def make_feature_fn(raw: Dict[str, np.ndarray], batch_size: int = 32):
    """feature_fn for eval/metrics.fid from a raw state_dict-keyed dict.

    Chunks of `batch_size` are PADDED to a fixed shape so the 94-conv
    299×299 graph compiles exactly once, no matter what slice sizes the
    caller (e.g. fid()'s embed loop) hands in — per-tail-shape recompiles
    of this graph cost far more than the padded rows."""
    params = _fold_params(raw)

    @jax.jit
    def features(images: jnp.ndarray) -> jnp.ndarray:
        return _forward(params, images)

    def feature_fn(images):
        imgs = np.asarray(images)
        out = []
        for start in range(0, imgs.shape[0], batch_size):
            chunk = imgs[start:start + batch_size]
            n = chunk.shape[0]
            if n < batch_size:
                chunk = np.concatenate(
                    [chunk, np.zeros((batch_size - n,) + chunk.shape[1:],
                                     chunk.dtype)])
            out.append(np.asarray(jax.device_get(
                features(jnp.asarray(chunk))))[:n])
        return jnp.asarray(np.concatenate(out))

    return feature_fn


def load_inception_features(npz_path: str, batch_size: int = 32):
    """feature_fn from an .npz written by tools/convert_inception.py.

    Pass the result as `fid_feature_fn` to eval/evaluate.evaluate_dataset
    (or --inception-npz on the eval CLI): the Fréchet metric is then
    reported under the paper-comparable "fid" label instead of
    "fid_random".
    """
    if not os.path.exists(npz_path):
        raise FileNotFoundError(
            f"inception weights not found: {npz_path!r} (generate with "
            "tools/convert_inception.py from the public "
            "pt_inception-2015-12-05 checkpoint)")
    with np.load(npz_path) as z:
        raw = {k: z[k] for k in z.files}
    return make_feature_fn(raw, batch_size=batch_size)
