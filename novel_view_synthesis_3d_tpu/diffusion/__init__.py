from novel_view_synthesis_3d_tpu.diffusion.schedules import (  # noqa: F401
    DiffusionSchedule,
    cosine_beta_schedule,
    logsnr_schedule_cosine,
    make_schedule,
    respace,
    sampling_schedule,
)
