"""Canonical diffusion-process math (single source of truth).

The reference duplicates this math three times — forward noising inside the
torch dataset (`/root/reference/dataset/data_loader.py:15-25,94-100`), and the
reverse-process tables + helpers in the sampler
(`/root/reference/sampling.py:16-53,73-76`). Here there is exactly one
implementation, built as float64 numpy tables (matching the reference's
float64 table construction) packed into a jit-traversable pytree, with
`q_sample` executed **on device inside the train step** rather than on CPU in
a data-loader worker.

Math (DDPM, Nichol & Dhariwal cosine schedule, T=1000):
  ᾱ(t) = cos²(((t/T + s)/(1 + s)) · π/2) / ᾱ(0),  β_t = 1 − ᾱ_t/ᾱ_{t−1}
  q(z_t | x₀) = N(√ᾱ_t x₀, (1−ᾱ_t) I)
  x̂₀ = √(1/ᾱ_t) z_t − √(1/ᾱ_t − 1) ε̂
  q(z_{t−1} | z_t, x₀) = N(c₁ x₀ + c₂ z_t, β̃_t I),
    c₁ = β_t √ᾱ_{t−1}/(1−ᾱ_t), c₂ = (1−ᾱ_{t−1})√α_t/(1−ᾱ_t),
    β̃_t = β_t (1−ᾱ_{t−1})/(1−ᾱ_t)
  logsnr(u) = −2 log tan(a·u + b), b = atan(e^{−λmax/2}),
    a = atan(e^{−λmin/2}) − b   (u = t/T ∈ [0,1])
"""

from __future__ import annotations

from typing import Optional

import flax.struct
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import DiffusionConfig


def cosine_beta_schedule(timesteps: int, s: float = 0.008) -> np.ndarray:
    """Cosine β schedule (float64), clipped to [0, 0.9999].

    Behavior-matches /root/reference/dataset/data_loader.py:15-25 (which is
    itself the schedule of https://openreview.net/forum?id=-NEXDKk8gZ).
    """
    steps = timesteps + 1
    x = np.linspace(0, timesteps, steps, dtype=np.float64)
    alphas_cumprod = np.cos(((x / timesteps) + s) / (1 + s) * np.pi * 0.5) ** 2
    alphas_cumprod = alphas_cumprod / alphas_cumprod[0]
    betas = 1.0 - (alphas_cumprod[1:] / alphas_cumprod[:-1])
    return np.clip(betas, 0.0, 0.9999)


def linear_beta_schedule(timesteps: int) -> np.ndarray:
    """Linear β schedule (Ho et al. 2020), float64.

    The DDPM paper's 1e-4 → 0.02 ladder is defined at T=1000; other T scale
    the endpoints by 1000/T so the continuous-time diffusion is preserved.
    The reference has no linear option (cosine only, data_loader.py:15-25);
    this is a framework extension.
    """
    scale = 1000.0 / timesteps
    # Clip like cosine_beta_schedule: for very small T the scaled endpoint
    # exceeds 1 and unclipped betas would turn the tables NaN/inf.
    return np.clip(np.linspace(scale * 1e-4, scale * 0.02, timesteps,
                               dtype=np.float64), 0.0, 0.9999)


def shifted_cosine_beta_schedule(timesteps: int, shift: float, *,
                                 logsnr_min: float = -20.0,
                                 logsnr_max: float = 20.0) -> np.ndarray:
    """β table whose ᾱ follows the SHIFTED cosine logsnr (float64).

    ᾱ_t = σ(logsnr_cosine((t+1)/T) + shift): the discrete table realizes
    exactly the shifted noise level the model is conditioned on (simple
    diffusion, arXiv 2301.11093 §2.3 — shift 2·log(64/S) for resolution S).
    shift=0 reproduces a sigmoid-parameterized cosine schedule.
    """
    u = np.arange(1, timesteps + 1, dtype=np.float64) / timesteps
    logsnr = logsnr_schedule_cosine(u, logsnr_min=logsnr_min,
                                    logsnr_max=logsnr_max) + shift
    acp = 1.0 / (1.0 + np.exp(-logsnr))  # sigmoid
    acp_prev = np.concatenate([[1.0], acp[:-1]])
    return np.clip(1.0 - acp / acp_prev, 0.0, 0.9999)


def logsnr_schedule_cosine(t, *, logsnr_min: float = -20.0, logsnr_max: float = 20.0):
    """logsnr(t) for continuous t ∈ [0, 1].

    Behavior-matches /root/reference/sampling.py:73-76 and
    /root/reference/dataset/data_loader.py:94-97. Works on numpy or jnp input.
    """
    xp = np if isinstance(t, (float, int, np.ndarray, np.floating)) else jnp
    b = xp.arctan(xp.exp(-0.5 * logsnr_max))
    a = xp.arctan(xp.exp(-0.5 * logsnr_min)) - b
    return -2.0 * xp.log(xp.tan(a * t + b))


@flax.struct.dataclass
class DiffusionSchedule:
    """Precomputed per-timestep tables as a pytree of f32 device arrays.

    All gather-by-t helpers take integer timestep arrays of shape (B,) (or
    scalars) and broadcast against image tensors (B, ..., C).
    """

    betas: jnp.ndarray
    alphas_cumprod: jnp.ndarray
    alphas_cumprod_prev: jnp.ndarray
    sqrt_alphas_cumprod: jnp.ndarray
    sqrt_one_minus_alphas_cumprod: jnp.ndarray
    sqrt_recip_alphas_cumprod: jnp.ndarray
    sqrt_recipm1_alphas_cumprod: jnp.ndarray
    posterior_variance: jnp.ndarray
    posterior_log_variance_clipped: jnp.ndarray
    posterior_mean_coef1: jnp.ndarray
    posterior_mean_coef2: jnp.ndarray
    # Continuous-time logsnr schedule parameters.
    logsnr_min: float = flax.struct.field(pytree_node=False, default=-20.0)
    logsnr_max: float = flax.struct.field(pytree_node=False, default=20.0)
    # Map from respaced index -> original timestep (identity if not respaced);
    # logsnr must always be evaluated at ORIGINAL t/T.
    timestep_map: jnp.ndarray = None
    num_original_timesteps: int = flax.struct.field(pytree_node=False, default=1000)
    # Non-cosine schedules condition on the EXACT per-timestep
    # log(ᾱ/(1−ᾱ)) of the original (un-respaced) table instead of the
    # closed-form cosine logsnr (which would misdescribe the actual noise
    # level). None → use the cosine formula (reference behavior).
    logsnr_table: Optional[jnp.ndarray] = None

    @property
    def num_timesteps(self) -> int:
        return self.betas.shape[0]

    # -- indexing helper ------------------------------------------------
    def _extract(self, table: jnp.ndarray, t, like: jnp.ndarray) -> jnp.ndarray:
        """table[t] broadcast to rank of `like` (batch dims lead)."""
        vals = jnp.take(table, t, axis=0)
        return vals.reshape(vals.shape + (1,) * (like.ndim - vals.ndim))

    # -- forward process ------------------------------------------------
    def q_sample(self, x0: jnp.ndarray, t, noise: jnp.ndarray) -> jnp.ndarray:
        """z_t = √ᾱ_t x₀ + √(1−ᾱ_t) ε  (ref data_loader.py:100, on device)."""
        return (
            self._extract(self.sqrt_alphas_cumprod, t, x0) * x0
            + self._extract(self.sqrt_one_minus_alphas_cumprod, t, x0) * noise
        )

    # -- reverse process ------------------------------------------------
    def predict_start_from_noise(self, z_t, t, noise):
        """x̂₀ from ε̂ (ref sampling.py:43-44)."""
        return (
            self._extract(self.sqrt_recip_alphas_cumprod, t, z_t) * z_t
            - self._extract(self.sqrt_recipm1_alphas_cumprod, t, z_t) * noise
        )

    def q_posterior(self, x0, z_t, t):
        """Mean / variance / clipped-log-variance of q(z_{t−1}|z_t, x₀)
        (ref sampling.py:46-53)."""
        mean = (
            self._extract(self.posterior_mean_coef1, t, z_t) * x0
            + self._extract(self.posterior_mean_coef2, t, z_t) * z_t
        )
        var = self._extract(self.posterior_variance, t, z_t)
        log_var = self._extract(self.posterior_log_variance_clipped, t, z_t)
        return mean, var, log_var

    def predict_noise_from_start(self, z_t, t, x0):
        """ε̂ implied by x̂₀ — exact inverse of predict_start_from_noise."""
        return (
            self._extract(self.sqrt_recip_alphas_cumprod, t, z_t) * z_t - x0
        ) / self._extract(self.sqrt_recipm1_alphas_cumprod, t, z_t)

    # -- v-parameterization (Salimans & Ho 2022, progressive distillation) --
    def v_from_eps_x0(self, t, eps, x0):
        """v = √ᾱ_t ε − √(1−ᾱ_t) x₀ — the training target for
        objective='v'."""
        return (
            self._extract(self.sqrt_alphas_cumprod, t, eps) * eps
            - self._extract(self.sqrt_one_minus_alphas_cumprod, t, eps) * x0
        )

    def predict_start_from_v(self, z_t, t, v):
        """x̂₀ = √ᾱ_t z_t − √(1−ᾱ_t) v."""
        return (
            self._extract(self.sqrt_alphas_cumprod, t, z_t) * z_t
            - self._extract(self.sqrt_one_minus_alphas_cumprod, t, z_t) * v
        )

    def ddim_step(self, x0, z_t, t, noise, eta: float):
        """One DDIM update z_t → z_{t−1} (Song et al. 2021 eq. 12).

        η=0 is the deterministic DDIM ODE (σ=0, `noise` unused); η=1 matches
        the ancestral posterior variance. Lives here with q_posterior so the
        reverse-process math has one home; the sampler only picks which
        update to call.
        """
        acp = self._extract(self.alphas_cumprod, t, z_t)
        acp_prev = self._extract(self.alphas_cumprod_prev, t, z_t)
        eps_hat = self.predict_noise_from_start(z_t, t, x0)
        sigma = (eta * jnp.sqrt((1.0 - acp_prev) / (1.0 - acp))
                 * jnp.sqrt(jnp.maximum(1.0 - acp / acp_prev, 0.0)))
        dir_zt = jnp.sqrt(
            jnp.maximum(1.0 - acp_prev - sigma ** 2, 0.0)) * eps_hat
        nonzero = jnp.reshape(  # scalar or per-sample t
            (t > 0).astype(z_t.dtype),
            jnp.shape(t) + (1,) * (z_t.ndim - jnp.ndim(t)))
        return jnp.sqrt(acp_prev) * x0 + dir_zt + nonzero * sigma * noise

    def dpmpp_2m_step(self, x0, x0_prev, z_t, t, first):
        """One DPM-Solver++(2M) update z_t → z_{t−1} (Lu et al. 2022,
        arXiv 2211.01095, Algorithm 2, data-prediction form).

        Second-order multistep: extrapolate the denoised prediction with the
        PREVIOUS step's x̂₀ (`x0_prev`, the network's x̂₀ at t+1) before the
        exponential-integrator update. In half-logsnr λ = log(α/σ):

          h = λ_{t−1} − λ_t,  r = (λ_t − λ_{t+1}) / h
          D̄ = x̂₀ + (x̂₀ − x̂₀_prev) / (2r)
          z_{t−1} = (σ_{t−1}/σ_t) z_t + α_{t−1}(1 − e^{−h}) D̄

        The update line is algebraically the η=0 DDIM step with D̄ in place
        of x̂₀ (substitute σ_{t−1}α_t/σ_t = α_{t−1}e^{−h} into ddim_step), so
        it reuses `ddim_step` — one home for the exponential-integrator
        algebra. The first step (`first`, no history yet) and the final step
        (t=0, where h = λ_0⁺ − λ_0 is unbounded and r → 0 would blow up the
        extrapolation) fall back to the first-order update D̄ = x̂₀ — the
        standard `lower_order_final` stabilization. Deterministic: no noise
        is consumed. The reference has only the 1000-step ancestral host
        loop (sampling.py:116-167); this is a framework extension for
        ~8× fewer sampling steps at comparable quality.
        """
        acp_t = self._extract(self.alphas_cumprod, t, z_t)
        acp_prev = self._extract(self.alphas_cumprod_prev, t, z_t)
        t_last = jnp.minimum(jnp.asarray(t) + 1, self.num_timesteps - 1)
        acp_last = self._extract(self.alphas_cumprod, t_last, z_t)

        def lam(a):
            # Clip so λ stays finite even where an f32 table rounds ᾱ to
            # exactly 1 (shifted-cosine near t=0) or 0; only the ratio r
            # sees these values, and the affected steps are the low-order
            # fallbacks anyway.
            a = jnp.clip(a, 1e-20, 1.0 - 1e-7)
            return 0.5 * (jnp.log(a) - jnp.log1p(-a))

        h = lam(acp_prev) - lam(acp_t)
        h_last = lam(acp_t) - lam(acp_last)
        low_order = jnp.asarray(first) | (t == 0)
        low_order = jnp.reshape(
            low_order,
            jnp.shape(low_order) + (1,) * (z_t.ndim - jnp.ndim(low_order)))
        r = jnp.where(low_order, 1.0, h_last / jnp.maximum(h, 1e-20))
        d_bar = jnp.where(
            low_order, x0,
            x0 + (x0 - x0_prev) / jnp.maximum(2.0 * r, 1e-20))
        return self.ddim_step(d_bar, z_t, t, 0.0, 0.0)

    # -- conditioning signal --------------------------------------------
    def logsnr(self, t) -> jnp.ndarray:
        """logsnr at (respaced) integer timestep t, evaluated at original t/T.

        The reference computes logsnr at t/1000 for both training
        (data_loader.py:110) and sampling (sampling.py:151).
        """
        t_orig = jnp.take(self.timestep_map, t, axis=0)
        if self.logsnr_table is not None:
            return jnp.take(self.logsnr_table, t_orig, axis=0)
        u = t_orig.astype(jnp.float32) / float(self.num_original_timesteps)
        return logsnr_schedule_cosine(
            u, logsnr_min=self.logsnr_min, logsnr_max=self.logsnr_max
        )


def _tables_from_betas(betas: np.ndarray) -> dict:
    alphas = 1.0 - betas
    alphas_cumprod = np.cumprod(alphas, axis=0)
    alphas_cumprod_prev = np.append(1.0, alphas_cumprod[:-1])
    posterior_variance = (
        betas * (1.0 - alphas_cumprod_prev) / (1.0 - alphas_cumprod)
    )
    # log clipped: t=0 posterior variance is 0, replace with t=1's value
    # (standard DDPM practice; matches reference sampling.py:37-38). A
    # SINGLE-step ladder (progressive distillation's endpoint; respaced
    # steps=1) has no t=1: floor the lone value instead — the final
    # step adds no noise (the t>0 mask zeroes the term), so the floored
    # log-variance is never read, it just must not be log(0) = -inf.
    if len(posterior_variance) > 1:
        clipped = np.append(posterior_variance[1], posterior_variance[1:])
    else:
        clipped = np.maximum(posterior_variance, 1e-20)
    posterior_log_variance_clipped = np.log(clipped)
    return dict(
        betas=betas,
        alphas_cumprod=alphas_cumprod,
        alphas_cumprod_prev=alphas_cumprod_prev,
        sqrt_alphas_cumprod=np.sqrt(alphas_cumprod),
        sqrt_one_minus_alphas_cumprod=np.sqrt(1.0 - alphas_cumprod),
        sqrt_recip_alphas_cumprod=np.sqrt(1.0 / alphas_cumprod),
        sqrt_recipm1_alphas_cumprod=np.sqrt(1.0 / alphas_cumprod - 1.0),
        posterior_variance=posterior_variance,
        posterior_log_variance_clipped=posterior_log_variance_clipped,
        posterior_mean_coef1=(
            betas * np.sqrt(alphas_cumprod_prev) / (1.0 - alphas_cumprod)
        ),
        posterior_mean_coef2=(
            (1.0 - alphas_cumprod_prev) * np.sqrt(alphas) / (1.0 - alphas_cumprod)
        ),
    )


def _betas_for(config: DiffusionConfig) -> np.ndarray:
    if config.logsnr_shift != 0.0 and config.schedule != "shifted_cosine":
        # Dropping the shift silently would train at the wrong noise level —
        # the exact misconfig the shift exists to fix at high resolution.
        raise ValueError(
            f"diffusion.logsnr_shift={config.logsnr_shift} has no effect "
            f"with schedule={config.schedule!r}; use "
            "schedule='shifted_cosine'")
    if config.schedule == "cosine":
        return cosine_beta_schedule(config.timesteps, s=config.cosine_s)
    if config.schedule == "linear":
        return linear_beta_schedule(config.timesteps)
    if config.schedule == "shifted_cosine":
        return shifted_cosine_beta_schedule(
            config.timesteps, config.logsnr_shift,
            logsnr_min=config.logsnr_min, logsnr_max=config.logsnr_max)
    raise ValueError(f"unknown schedule {config.schedule!r}")


def _exact_logsnr_table(config: DiffusionConfig,
                        acp: np.ndarray) -> Optional[jnp.ndarray]:
    """Per-timestep log(ᾱ/(1−ᾱ)) for non-cosine schedules (clipped to the
    configured logsnr range, matching the cosine path's ±20 clip). `acp` is
    the float64 alphas_cumprod of the ORIGINAL (un-respaced) schedule."""
    if config.schedule == "cosine":
        return None  # closed-form cosine logsnr — reference behavior
    table = np.clip(np.log(acp / (1.0 - acp)),
                    config.logsnr_min, config.logsnr_max)
    return jnp.asarray(table, dtype=jnp.float32)


def make_schedule(config: DiffusionConfig) -> DiffusionSchedule:
    betas = _betas_for(config)
    f64 = _tables_from_betas(betas)
    tables = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in f64.items()}
    return DiffusionSchedule(
        **tables,
        logsnr_min=config.logsnr_min,
        logsnr_max=config.logsnr_max,
        timestep_map=jnp.arange(config.timesteps, dtype=jnp.int32),
        num_original_timesteps=config.timesteps,
        logsnr_table=_exact_logsnr_table(config, f64["alphas_cumprod"]),
    )


def sampling_schedule(config: DiffusionConfig,
                      num_steps: Optional[int] = None) -> DiffusionSchedule:
    """Schedule for sampling: respaced to `num_steps` (default
    config.sample_timesteps) unless that equals the training timestep count,
    in which case the full schedule is built directly."""
    num_steps = config.sample_timesteps if num_steps is None else num_steps
    if num_steps < 1:
        raise ValueError(f"sample steps must be >= 1, got {num_steps}")
    if num_steps == config.timesteps:
        return make_schedule(config)
    return respace(config, num_steps)


def respace(schedule_config: DiffusionConfig, num_steps: int) -> DiffusionSchedule:
    """Respaced schedule for fast sampling (e.g. 256 of 1000 steps).

    Selects an evenly-spaced subsequence of the original timesteps and
    rebuilds β so that ᾱ over the subsequence matches the original ᾱ at the
    kept timesteps (the standard DDPM-respacing construction). The returned
    schedule's `timestep_map` lets `logsnr()` keep reporting original-time
    values, which is what the model was conditioned on during training.
    """
    T = schedule_config.timesteps
    if num_steps > T:
        raise ValueError(f"cannot respace {T} steps to {num_steps}")
    betas = _betas_for(schedule_config)
    acp = np.cumprod(1.0 - betas, axis=0)
    use = np.linspace(0, T - 1, num_steps).round().astype(np.int64)
    use = np.unique(use)
    last = 1.0
    new_betas = []
    for t in use:
        new_betas.append(1.0 - acp[t] / last)
        last = acp[t]
    new_betas = np.asarray(new_betas, dtype=np.float64)
    tables = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in _tables_from_betas(new_betas).items()}
    return DiffusionSchedule(
        **tables,
        logsnr_min=schedule_config.logsnr_min,
        logsnr_max=schedule_config.logsnr_max,
        timestep_map=jnp.asarray(use, dtype=jnp.int32),
        num_original_timesteps=T,
        logsnr_table=_exact_logsnr_table(schedule_config, acp),
    )
