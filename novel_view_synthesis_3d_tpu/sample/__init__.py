from novel_view_synthesis_3d_tpu.sample.ddpm import (  # noqa: F401
    autoregressive_generate,
    make_sampler,
    make_stochastic_sampler,
)
