"""On-device DDPM ancestral sampler with classifier-free guidance.

TPU-native redesign of /root/reference/sampling.py:116-167, which runs 1000
host-side numpy steps, each dispatching TWO un-jitted Flax forward passes
(cond + uncond CFG). Here the ENTIRE reverse process is one XLA program:

  - `lax.scan` over the (optionally respaced) timestep ladder — no host
    round-trips, no per-step dispatch overhead;
  - CFG computed in a single forward pass on a doubled batch (2B) with
    cond_mask = [1…1, 0…0] instead of two applies — keeps the MXU fed with
    one large matmul stream per step;
  - guidance weight w, respacing (e.g. 256 of 1000 steps) and x̂₀ clipping
    are config fields (reference hardcodes w=3 at sampling.py:134);
  - k>1 stochastic conditioning (3DiM paper §3.2): each denoise step picks a
    random view from the conditioning pool — implemented with a traced
    `randint` + `jnp.take` inside the scan so one compilation serves any
    pool size up to the padded max.

Per-step math (reference sampling.py:119-151):
  ε̂ = (1+w)·ε̂_cond − w·ε̂_uncond
  x̂₀ = clip(√(1/ᾱ_t) z − √(1/ᾱ_t − 1) ε̂, ±1)
  z ← posterior_mean(x̂₀, z, t) + 1{t>0} · exp(½ log σ̃²_t) · ε′

`diffusion.sampler='ddim'` swaps the ancestral update for the DDIM
non-Markovian one (Song et al. 2021) — deterministic at `ddim_eta=0`,
ancestral-variance at `ddim_eta=1`; `diffusion.sampler='dpm++'` uses the
DPM-Solver++(2M) second-order multistep solver (Lu et al. 2022) for
comparable quality at ~8× fewer steps. The reference has only the
1000-step ancestral loop.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import DiffusionConfig
from novel_view_synthesis_3d_tpu.diffusion.schedules import DiffusionSchedule
from novel_view_synthesis_3d_tpu.models.xunet import (
    precompute_cond_feats,
    precompute_pose_embs,
)
from novel_view_synthesis_3d_tpu.ops import fused_step as fused_step_lib


def _raw_eps(model, params, model_batch: dict, pose_embs=None,
             cond_feats=None):
    """(ε̂_cond, ε̂_uncond) network outputs via one doubled-batch forward.

    `pose_embs`: per-level pose embeddings already computed for the
    DOUBLED (cond+uncond) layout — injected after the doubling so they are
    not concatenated twice. See models/xunet.precompute_pose_embs.
    `cond_feats`: stem features of the conditioning frame(s) for the
    doubled layout (models/xunet.precompute_cond_feats) — with them the
    step program convolves only the noised target frame."""
    B = model_batch["z"].shape[0]
    doubled = jax.tree.map(lambda a: jnp.concatenate([a, a], axis=0), model_batch)
    mask = jnp.concatenate([jnp.ones((B,)), jnp.zeros((B,))])
    if pose_embs is not None:
        doubled["pose_embs"] = pose_embs
    if cond_feats is not None:
        doubled["cond_feats"] = cond_feats
    eps = model.apply({"params": params}, doubled, cond_mask=mask, train=False)
    eps_cond, eps_uncond = jnp.split(eps, 2, axis=0)
    return eps_cond, eps_uncond


def _cfg_eps(model, params, model_batch: dict, w: float,
             pose_embs=None):
    """(guided, conditional) network outputs; CFG combine applied here.
    The conditional output rides along for cfg_rescale."""
    eps_cond, eps_uncond = _raw_eps(model, params, model_batch,
                                    pose_embs=pose_embs)
    return (1.0 + w) * eps_cond - w * eps_uncond, eps_cond


def _doubled_pose_embs(model, params, cond: dict):
    """Pose embeddings for _cfg_eps's doubled layout, computed once per
    trajectory: conditional half with the mask on, unconditional half with
    the pose embedding zeroed — exactly what the in-loop mask produced."""
    B = cond["x"].shape[0]
    doubled = jax.tree.map(lambda a: jnp.concatenate([a, a], axis=0), cond)
    mask = jnp.concatenate([jnp.ones((B,)), jnp.zeros((B,))])
    return precompute_pose_embs(model, params, doubled, mask)


def _per_row_encode(model, params, cond: dict, mask):
    """Conditioning-branch encode, one row at a time.

    Returns the same `(pose_embs, cond_feats)` a batched
    `precompute_pose_embs` / `precompute_cond_feats` call would, but
    computed as B independent B=1 encodes concatenated back together.
    This is the cond cache's bit-identity keystone: XLA's conv lowering
    is BATCH-SIZE dependent (a row of a B=4 pose encode can differ ~1e-6
    from the same row encoded at B=1, observed on the multi-device CPU
    test mesh), so the cache — which encodes per request at admission,
    per bank entry at frame boundaries, and consumes rows stacked into
    arbitrary ring batches — standardizes EVERY encode on the B=1 row
    computation. A B=1 encode subgraph produces identical bits whether
    it runs standalone (the admission program) or embedded in a larger
    program (the uncached step recomputing it in-jit), so cached and
    uncached rows match bitwise at any batch composition
    (tests/test_cond_cache.py)."""
    B = cond["x"].shape[0]
    pose_rows, feat_rows = [], []
    for i in range(B):
        row = jax.tree.map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, i, 1, 0), cond)
        m = jax.lax.dynamic_slice_in_dim(mask, i, 1, 0)
        pose_rows.append(precompute_pose_embs(model, params, row, m))
        feat_rows.append(precompute_cond_feats(model, params, row))
    pose_embs = tuple(
        jnp.concatenate([r[lvl] for r in pose_rows], axis=0)
        for lvl in range(len(pose_rows[0])))
    cond_feats = jnp.concatenate(feat_rows, axis=0)
    return pose_embs, cond_feats


def make_cond_encode_fn(model, *, param_transform=None):
    """Jitted conditioning-branch encode for the serving cond cache.

      encode(params, cond, mask) -> (pose_embs, cond_feats)

    with `pose_embs` a per-level tuple of (B, F, H/2ˡ, W/2ˡ, emb) pose
    embeddings (CFG mask baked in — zeros(B) encodes the uncond half)
    and `cond_feats` the (B, Fc, H, W, ch) stem features of the cond
    frame(s). The service (sample/service.py) calls this ONCE at
    admission — or once per frame-bank encode for trajectories, with B
    = k_max and the current target pose broadcast — and the results
    live device-resident on the ring slot; `make_slot_step_fn` /
    `make_bank_step_fn` built with `cond_cache=True` consume them as
    device arguments instead of re-running rays → posenc → convs every
    denoise step. A separate jitted callable (like make_bank_commit_fn)
    so the step-program cache's entry accounting is untouched; compiles
    once per (B, H, W) admission shape, never on the warm step path.

    Internally row-unrolled (_per_row_encode) so a k_max-batched bank
    encode yields bit-identical rows to the B=1 admission encode — the
    invariant the steppers' gather/recompute equivalence rests on.

    `param_transform` must match the step program's (the int8 path
    dequantizes in-jit) so cached activations are computed from exactly
    the weights the step program would have used."""

    @jax.jit
    def encode(params, cond, mask):
        if param_transform is not None:
            params = param_transform(params)
        return _per_row_encode(model, params, cond, mask)

    return encode


def _assemble_cached_cond(cc3):
    """Doubled (cond ‖ uncond) pose embeddings + stem features from the
    cached halves: `cc3 = (pose_c, pose_u, feats_c)` with pose_c per-level
    (B, …), pose_u per-level (1, …) — the shared uncond half, broadcast
    here IN-program so guidance pairs store one encode — and feats_c
    (B, Fc, H, W, ch), which is CFG-mask-independent (only the pose
    embedding is zeroed) so the same tensor serves both halves. Pinned
    with optimization_barrier: the forward must see materialized inputs,
    exactly like the uncached program's in-jit conv outputs, so XLA
    cannot fuse the assembly into the UNet and drift the two programs a
    ulp apart (the barrier note above _resolve_request_fused)."""
    pose_c, pose_u, feats_c = cc3
    pose_embs = tuple(
        jnp.concatenate([pc, jnp.broadcast_to(pu, pc.shape)], axis=0)
        for pc, pu in zip(pose_c, pose_u))
    cond_feats = jnp.concatenate([feats_c, feats_c], axis=0)
    return jax.lax.optimization_barrier((pose_embs, cond_feats))


def _step_noise(key, z):
    """N(0,1) noise for one reverse step.

    `key` is either a single PRNG key (one stream for the whole batch —
    the training-side samplers' historical behavior, bit-preserved) or a
    (B, 2) stacked key vector: one independent stream PER SAMPLE, which
    makes row i of a batched reverse process depend only on (cond_i,
    key_i) — the property `make_request_sampler` needs so the serving
    micro-batcher's padding and batch composition cannot change any
    request's image."""
    if key.ndim == 2:
        return jax.vmap(lambda k: jax.random.normal(k, z.shape[1:]))(key)
    return jax.random.normal(key, z.shape)


def _posterior_sample(schedule: DiffusionSchedule, x0, z, t, key):
    """Draw z_{t−1} ~ q(z_{t−1}|z_t, x̂₀); noiseless at t=0."""
    mean, _, log_var = schedule.q_posterior(x0, z, t)
    noise = _step_noise(key, z)
    nonzero = jnp.reshape(  # no noise at the final step; scalar or (B,) t
        (t > 0).astype(z.dtype), jnp.shape(t) + (1,) * (z.ndim - jnp.ndim(t)))
    return mean + nonzero * jnp.exp(0.5 * log_var) * noise


def _make_x0_fn(schedule: DiffusionSchedule, objective: str):
    """x̂₀ from the network output under the configured objective."""
    if objective == "eps":
        return schedule.predict_start_from_noise
    if objective == "x0":
        return lambda z, t, out: out
    if objective == "v":
        return schedule.predict_start_from_v
    raise ValueError(f"unknown objective {objective!r}")


def _make_update(schedule: DiffusionSchedule, config: DiffusionConfig,
                 memoryless: bool = False):
    """Bind the configured reverse-process update (ddpm | ddim | dpm++),
    converting the network output (eps | x0 | v per diffusion.objective) to
    x̂₀ first. Returns `(update, init_aux)`:

      update(z, t, outs, key, aux) -> (z_next, aux_next)
      init_aux(z0) -> initial per-trajectory solver state

    `aux` is empty for the memoryless samplers (ddpm, ddim) and carries the
    previous step's x̂₀ for the multistep dpm++ solver (DPM-Solver++(2M),
    Lu et al. 2022) — the scan carry threads it across steps.

    `memoryless=True` declares that the caller changes the conditioning
    between steps (stochastic conditioning re-draws the pool view every
    denoise step), so consecutive x̂₀ predictions are NOT samples of one
    ODE trajectory: the 2M extrapolation would read the conditioning jump
    as curvature and deterministically amplify it. dpm++ then degrades to
    its first-order update (= η=0 DDIM); ddpm/ddim are unaffected.

    CFG is applied in the network's output space before this conversion
    (guidance in eps-space and v-space coincide up to the linear maps here).
    `update` takes the (guided, conditional) output pair from _cfg_eps: the
    conditional branch feeds cfg_rescale (Lin et al. 2023) — after guidance,
    x̂₀ is rescaled toward the conditional prediction's per-sample std and
    blended with weight φ = config.cfg_rescale (0 = off, reference behavior).
    """
    x0_fn = _make_x0_fn(schedule, config.objective)
    clip_denoised = config.clip_denoised
    phi = config.cfg_rescale
    if not 0.0 <= phi <= 1.0:
        raise ValueError(f"cfg_rescale must be in [0, 1], got {phi}")

    def to_x0(z, t, outs):
        guided, cond_out = outs
        x0 = x0_fn(z, t, guided)
        if phi > 0.0:
            x0_c = x0_fn(z, t, cond_out)
            axes = tuple(range(1, x0.ndim))
            std_c = jnp.std(x0_c, axis=axes, keepdims=True)
            std_g = jnp.std(x0, axis=axes, keepdims=True)
            rescaled = x0 * (std_c / jnp.maximum(std_g, 1e-8))
            x0 = phi * rescaled + (1.0 - phi) * x0
        if clip_denoised:
            x0 = jnp.clip(x0, -1.0, 1.0)
        return x0

    def no_aux(z0):
        return ()

    if config.sampler == "ddim":
        eta = config.ddim_eta

        def update(z, t, outs, key, aux):
            noise = _step_noise(key, z)
            return schedule.ddim_step(to_x0(z, t, outs), z, t, noise, eta), aux

        return update, no_aux
    if config.sampler == "ddpm":

        def update(z, t, outs, key, aux):
            return _posterior_sample(schedule, to_x0(z, t, outs), z, t,
                                     key), aux

        return update, no_aux
    if config.sampler == "dpm++":
        if memoryless:

            def update(z, t, outs, key, aux):
                return schedule.ddim_step(to_x0(z, t, outs), z, t,
                                          0.0, 0.0), aux

            return update, no_aux

        def update(z, t, outs, key, aux):
            x0 = to_x0(z, t, outs)
            first = t >= schedule.num_timesteps - 1
            return schedule.dpmpp_2m_step(x0, aux, z, t, first), x0

        # The first step is first-order (no history); the zeros are never
        # read, they just give the scan carry a stable structure.
        return update, jnp.zeros_like
    raise ValueError(f"unknown sampler {config.sampler!r}")


def make_sampler(model, schedule: DiffusionSchedule, config: DiffusionConfig,
                 trajectory_every: int = 0,
                 trajectory_views: Optional[int] = None):
    """Jitted sampler for a fixed conditioning layout (k = model's Fc).

    sample(params, key, cond) -> (B, H, W, 3) images in [-1, 1], where cond
    holds x, R1, t1, R2, t2, K (the clean conditioning view(s) + poses).

    `trajectory_every=k` (k > 0) makes the sampler ALSO return the
    partially-denoised z after every k-th reverse step:
    sample(...) -> (final, trajectory) with trajectory
    (n_frames, B', H, W, 3) and final[:B'] == trajectory[-1], where
    n_frames = ceil(num_timesteps / k). k need not divide num_timesteps:
    the T//k full chunks run through a nested scan and any remainder steps
    run as one flat scan whose end state is appended as the last frame, so
    the final image is always captured. The RNG stream — and therefore the
    final image — is bit-identical to the flat sampler in every case.
    `trajectory_views` limits B' to the first n batch entries so a consumer
    that only wants one view's denoising film doesn't buy the whole batch's
    trajectory in HBM (B' = B when None).
    """
    w = config.guidance_weight
    update, init_aux = _make_update(schedule, config)
    T = schedule.num_timesteps
    if trajectory_every < 0 or trajectory_every > T:
        raise ValueError(
            f"trajectory_every must be in [0, {T}]; got {trajectory_every}")

    def body(cond, params, pose_embs, carry, t):
        z, key, aux = carry
        key, k_step = jax.random.split(key)
        batch = dict(cond, z=z,
                     logsnr=jnp.full((z.shape[0],), schedule.logsnr(t)))
        outs = _cfg_eps(model, params, batch, w, pose_embs=pose_embs)
        z, aux = update(z, t, outs, k_step, aux)
        return (z, key, aux), None

    @jax.jit
    def sample(params, key, cond: dict) -> jnp.ndarray:
        z_shape = cond["x"].shape[:1] + cond["x"].shape[-3:]  # (B, H, W, 3)
        key, k_init = jax.random.split(key)
        z0 = jax.random.normal(k_init, z_shape)
        ts = jnp.arange(T - 1, -1, -1)
        # Cameras are fixed for the whole reverse process: compute the
        # pose-conditioning path (rays → posenc → per-level convs) ONCE
        # here instead of every scan step — pure win, identical math.
        pose_embs = _doubled_pose_embs(model, params, cond)
        step = partial(body, cond, params, pose_embs)
        carry0 = (z0, key, init_aux(z0))

        if not trajectory_every:
            (z, _, _), _ = jax.lax.scan(step, carry0, ts)
            return z

        def outer(carry, ts_chunk):
            carry, _ = jax.lax.scan(step, carry, ts_chunk)
            z = carry[0]
            return carry, (z if trajectory_views is None
                           else z[:trajectory_views])

        n_chunks, rem = divmod(T, trajectory_every)
        chunks = ts[:n_chunks * trajectory_every].reshape(
            n_chunks, trajectory_every)
        carry, traj = jax.lax.scan(outer, carry0, chunks)
        if rem:
            carry, _ = jax.lax.scan(step, carry, ts[-rem:])
            z = carry[0]
            last = z if trajectory_views is None else z[:trajectory_views]
            traj = jnp.concatenate([traj, last[None]], axis=0)
        return carry[0], traj

    return sample


# Why the serving samplers pin the update's inputs with
# jax.lax.optimization_barrier before the per-step math: XLA is free to
# fuse the UNet epilogue / RNG / gather producers INTO the elementwise
# update chain, and its FMA-contraction choices there differ between
# program shapes — which would put the fused and unfused programs (and
# the two schedulers) a ulp apart before the update math even runs. The
# barrier makes every producer subgraph identical across programs, so
# one shared implementation (ops/fused_step.py: the Pallas kernel or
# its unfused reference twin) yields BIT-identical samplers — asserted
# in tier-1 (tests/test_fused_step.py). The Pallas call is a natural
# materialization boundary anyway; the unfused side forgoes only
# epilogue fusions whose producers materialize regardless. The
# training-side `make_sampler` is untouched (golden bit-compat).


def _resolve_request_fused(config: DiffusionConfig) -> bool:
    """Resolve diffusion.fused_step for the whole-request sampler.

    dpm++ 2M needs cross-step x̂₀ history, which a single fused step
    cannot express: an explicit True is a loud error (config.validate
    catches it earlier with the same message class), while 'auto'
    silently keeps the unfused multistep scan."""
    use = fused_step_lib.resolve_fused_step(config.fused_step)
    if use and config.sampler == "dpm++":
        if config.fused_step is True:
            raise ValueError(
                "diffusion.fused_step=True requires sampler 'ddpm' or "
                "'ddim' — the dpm++ 2M multistep update carries x̂₀ "
                "history across steps and is not expressible as one "
                "fused step (use 'auto' to fuse where possible)")
        return False
    return use


def _sched_coef_row(schedule: DiffusionSchedule, t) -> jnp.ndarray:
    """(len(STEP_COEF_KEYS),) coefficient vector at traced timestep t.

    Device-side gather of exactly the values the stepper's host-side
    StepBank packs per row (sample/stepper.py) — the fused kernel
    consumes one contract whether coefficients arrive from the host
    bank (slot stepper) or from these on-device tables (scan sampler)."""
    return jnp.stack([
        schedule.logsnr(t),
        jnp.take(schedule.sqrt_recip_alphas_cumprod, t),
        jnp.take(schedule.sqrt_recipm1_alphas_cumprod, t),
        jnp.take(schedule.sqrt_alphas_cumprod, t),
        jnp.take(schedule.sqrt_one_minus_alphas_cumprod, t),
        jnp.take(schedule.posterior_mean_coef1, t),
        jnp.take(schedule.posterior_mean_coef2, t),
        jnp.take(schedule.posterior_log_variance_clipped, t),
        jnp.take(schedule.alphas_cumprod, t),
        jnp.take(schedule.alphas_cumprod_prev, t),
        (t > 0).astype(jnp.float32),
    ])


def make_request_sampler(model, schedule: DiffusionSchedule,
                         config: DiffusionConfig, *,
                         param_transform=None):
    """Per-sample-keyed sampler for the serving micro-batcher
    (sample/service.py).

    sample(params, keys, cond) -> (B, H, W, 3) with keys a (B, 2) stack
    of PRNG keys: row i's init noise and every per-step draw come from
    keys[i]'s stream ONLY, so the output row depends on (cond row i,
    keys[i]) alone — coalescing a request into any bucket, alongside any
    co-riders or pad rows, reproduces its solo image (CPU/TPU row math is
    per-sample; see test_serve.py padding-invariance tests). The
    training-side `make_sampler` keeps its single-key whole-batch stream
    untouched (bit-compatibility with every golden/e2e test).

    The model forward, CFG doubling, and pose-embedding hoist are shared
    with `make_sampler`; only the RNG layout differs.

    `diffusion.fused_step` routes the per-step update (CFG combine, x̂₀
    reconstruction + clip, ddpm/ddim update, noise add) through the
    fused Pallas kernel (ops/fused_step.py) — identical RNG stream and
    operation order, one HBM pass instead of ~a dozen elementwise HLOs.
    `param_transform` (optional) is applied to `params` INSIDE the jit —
    the int8 serving path passes the dequantizer here so weights rest in
    HBM quantized (sample/precision.py).
    """
    w = config.guidance_weight
    T = schedule.num_timesteps
    use_fused = _resolve_request_fused(config)
    # ddpm/ddim run the shared per-step implementation (fused kernel or
    # its unfused reference twin, ops/fused_step.py — the same code the
    # slot stepper runs, so the two schedulers stay bit-aligned); dpm++
    # keeps the _make_update multistep scan (never fused).
    shared_impl = config.sampler in ("ddpm", "ddim")
    if shared_impl:
        update, init_aux = None, lambda z0: ()
        impl_eta = config.ddim_eta if config.sampler == "ddim" else 0.0
    else:
        update, init_aux = _make_update(schedule, config)
        impl_eta = 0.0

    @jax.jit
    def sample(params, keys, cond: dict) -> jnp.ndarray:
        if param_transform is not None:
            params = param_transform(params)
        z_shape = cond["x"].shape[-3:]  # (H, W, 3)
        both = jax.vmap(jax.random.split)(keys)       # (B, 2, 2)
        keys0, k_init = both[:, 0], both[:, 1]
        z0 = jax.vmap(lambda k: jax.random.normal(k, z_shape))(k_init)
        ts = jnp.arange(T - 1, -1, -1)
        pose_embs = _doubled_pose_embs(model, params, cond)
        B = keys.shape[0]
        # Per-shape fusion decision at trace time: rows past the VMEM
        # slab budget keep the unfused chain (same policy as the fused
        # GroupNorm's over-VMEM fallback).
        fused = (shared_impl and use_fused
                 and fused_step_lib.fits_vmem(int(np.prod(z_shape))))

        def body(carry, t):
            z, ks, aux = carry
            both = jax.vmap(jax.random.split)(ks)
            ks, k_step = both[:, 0], both[:, 1]
            batch = dict(cond, z=z,
                         logsnr=jnp.full((z.shape[0],), schedule.logsnr(t)))
            if shared_impl:
                ec, eu = _raw_eps(model, params, batch,
                                  pose_embs=pose_embs)
                # k_step is (B, 2): per-sample noise streams.
                noise = _step_noise(k_step, z)
                coefs = jnp.broadcast_to(
                    _sched_coef_row(schedule, t),
                    (B, len(STEP_COEF_KEYS)))
                wvec = jnp.full((B,), w, jnp.float32)
                # Pinned inputs + one shared implementation: the fused
                # and unfused programs are bit-identical (see the
                # barrier note above _resolve_request_fused).
                z_in, ec, eu, noise, coefs, wvec = \
                    jax.lax.optimization_barrier(
                        (z, ec, eu, noise, coefs, wvec))
                step_impl = (fused_step_lib.fused_denoise_step if fused
                             else fused_step_lib.unfused_reference_step)
                z = step_impl(
                    z_in, ec, eu, noise, coefs, wvec,
                    sampler=config.sampler, objective=config.objective,
                    eta=impl_eta, cfg_rescale=config.cfg_rescale,
                    clip_denoised=config.clip_denoised)
                return (z, ks, aux), None
            outs = _cfg_eps(model, params, batch, w, pose_embs=pose_embs)
            z, aux = update(z, t, outs, k_step, aux)
            return (z, ks, aux), None

        (z, _, _), _ = jax.lax.scan(body, (z0, keys0, init_aux(z0)), ts)
        return z

    return sample


# Per-row schedule coefficients the slot stepper feeds as ONE DEVICE
# ARGUMENT — a (B, len(STEP_COEF_KEYS)) float32 matrix, column i holding
# STEP_COEF_KEYS[i] — covering every table value the per-step update math
# reads, so the compiled program depends on the bucket SHAPE only, never on
# a row's step count, schedule position, or guidance weight. One packed
# matrix instead of a dict of scalars keeps the per-step host→device
# traffic to a single transfer (the stepper uploads fresh coefficients
# EVERY step — this is its hottest host-side path). The bank that gathers
# rows per request lives in sample/stepper.py.
STEP_COEF_KEYS = (
    "logsnr",             # network conditioning at the row's original t
    "sqrt_recip_acp",     # √(1/ᾱ_t)   (eps→x0, and ddim's ε̂ inversion)
    "sqrt_recipm1_acp",   # √(1/ᾱ_t−1)
    "sqrt_acp",           # √ᾱ_t       (v→x0)
    "sqrt_1macp",         # √(1−ᾱ_t)
    "pm_coef1",           # ddpm posterior mean coefficients
    "pm_coef2",
    "post_log_var",       # ddpm clipped posterior log-variance
    "acp",                # ᾱ_t, ᾱ_{t−1} (ddim update)
    "acp_prev",
    "nonzero",            # 1.0 while t > 0 (no noise at the final step)
)

# The fused kernel bakes these column indices in (ops/fused_step.py);
# the two layouts must never drift.
assert tuple(fused_step_lib._COEF_COLS) == STEP_COEF_KEYS
assert fused_step_lib._W_COL == len(STEP_COEF_KEYS)


def make_slot_step_fn(model, config: DiffusionConfig, *,
                      param_transform=None, cond_cache=False):
    """ONE reverse-process step over a ring batch with per-row schedules.

    The serving stepper's device program (sample/service.py,
    docs/DESIGN.md "Continuous batching & distillation"):

      step(params, z, keys, first, cond, coefs, w)
        -> (z_next, keys_next, finite)

    with z (B, H, W, 3), keys a (B, 2) per-row PRNG carry, `first` a (B,)
    bool marking rows entering the ring THIS step, `coefs` a
    (B, len(STEP_COEF_KEYS)) float32 matrix (every schedule table value
    the update reads, gathered on host per row — one packed transfer per
    step), and w the (B,) per-row guidance
    weight. Rows are fully independent: row i's output depends on
    (z_i, keys_i, cond_i, coefs_i, w_i) alone, so a request's image is
    bit-identical whether it steps solo or interleaved with any co-riders
    joining/leaving the ring — the ring-composition invariance the service
    asserts (tests/test_stepper.py).

    Rows with first=True draw their init noise HERE, reproducing
    `make_request_sampler`'s pre-scan key split exactly: split(key) →
    (carry, k_init), z₀ = N(0,1) from k_init; every row then splits its
    carry into (next_carry, k_step) exactly like the scan body — so a
    request stepped t times through this program sees the same RNG stream
    (and the same per-step math) as the whole-request sampler.

    The compiled program depends on the BUCKET SHAPE only: a mixed
    4-step/256-step batch, or mixed guidance weights, runs one program —
    t/steps_remaining/w are device arguments (the program-cache key
    contract, docs/DESIGN.md). `sampler='dpm++'` runs its first-order
    (history-free) update here — ring membership changes between steps,
    so multistep history is invalid, the same rule `_make_update` applies
    to stochastic conditioning; serve with serve.scheduler='request' for
    exact 2M.

    `diffusion.fused_step` routes everything after the UNet forward
    (CFG combine → x̂₀ + clip → update → noise add) through the fused
    Pallas kernel (ops/fused_step.py), consuming the SAME (B, K) coefs
    matrix — one HBM pass per step instead of ~a dozen elementwise
    HLOs, identical math and RNG stream. `param_transform` (optional)
    is applied to `params` INSIDE the jit — the int8 serving path
    passes the dequantizer here (sample/precision.py).

    `finite` is a (B,) bool — a device-side all-reduce of
    isfinite(z_next) per row, the in-ring anomaly mask the service's
    quarantine consumes (docs/DESIGN.md "Serving survivability"). It is
    computed FROM z_next and never feeds back into the update, so
    clean-path z/keys bits are untouched, and an extra output does not
    change the program-cache identity (still bucket/shape-only).

    `cond_cache=True` returns the cached-conditioning twin:

      step(params, z, keys, first, cond, coefs, w, cc)
        -> (z_next, keys_next, finite)

    with `cc = (pose_c, pose_u, feats_c)` the admission-time encode
    (make_cond_encode_fn): per-level (B, …) cond-half pose embeddings,
    the shared (1, …) uncond half, and the (B, Fc, H, W, ch) cond stem
    features — all device arguments stacked by the service from its
    ring slots, so the program identity stays bucket/shape-only. The
    doubled CFG layout is assembled in-program (_assemble_cached_cond)
    and the UNet convolves only the noised target frame
    (models/xunet.py `cond_feats` seam); everything else — RNG stream,
    update math, anomaly mask — is byte-for-byte the uncached body, and
    the two programs produce BIT-identical rows
    (tests/test_cond_cache.py)."""
    phi = config.cfg_rescale
    if not 0.0 <= phi <= 1.0:
        raise ValueError(f"cfg_rescale must be in [0, 1], got {phi}")
    clip_denoised = config.clip_denoised
    objective = config.objective
    if objective not in ("eps", "x0", "v"):
        raise ValueError(f"unknown objective {objective!r}")
    sampler = config.sampler
    eta = config.ddim_eta if sampler == "ddim" else 0.0
    if sampler == "dpm++":
        sampler = "ddim"  # first-order fallback (see docstring)
    if sampler not in ("ddpm", "ddim"):
        raise ValueError(f"unknown sampler {config.sampler!r}")
    # The stepper's dpm++ fallback is already first-order ddim, so the
    # fused kernel serves every sampler the stepper does.
    use_fused = fused_step_lib.resolve_fused_step(config.fused_step)

    logsnr_col = STEP_COEF_KEYS.index("logsnr")

    @jax.jit
    def step(params, z, keys, first, cond, coefs, w):
        if param_transform is not None:
            params = param_transform(params)
        B = z.shape[0]
        # Rows entering the ring draw init noise from their own stream.
        both = jax.vmap(jax.random.split)(keys)
        k_carry, k_init = both[:, 0], both[:, 1]
        z0 = jax.vmap(lambda k: jax.random.normal(k, z.shape[1:]))(k_init)
        fmask = first.reshape((B,) + (1,) * (z.ndim - 1))
        z = jnp.where(fmask, z0.astype(z.dtype), z)
        keys = jnp.where(first[:, None], k_carry, keys)
        # Per-step draw: identical split layout to the scan body.
        both = jax.vmap(jax.random.split)(keys)
        keys_next, k_step = both[:, 0], both[:, 1]

        # Cond branch: computed in-program, but row-unrolled through the
        # SAME B=1 encode computation (_per_row_encode) and the same
        # _assemble_cached_cond barrier as the cached twin's admission
        # encodes, so the downstream UNet sees bit-identical inputs and
        # identical traced structure in both programs — a batched encode
        # here would drift co-riding rows ~1e-6 from their admission
        # encodes (tests/test_cond_cache.py pins array_equal).
        pose_c, feats_c = _per_row_encode(model, params, cond,
                                          jnp.ones((B,)))
        pose_u = precompute_pose_embs(
            model, params, jax.tree.map(lambda a: a[:1], cond),
            jnp.zeros((1,)))
        pose_embs, cond_feats = _assemble_cached_cond(
            (pose_c, pose_u, feats_c))
        batch = dict(cond, z=z, logsnr=coefs[:, logsnr_col])
        ec, eu = _raw_eps(model, params, batch, pose_embs=pose_embs,
                          cond_feats=cond_feats)
        noise = _step_noise(k_step, z)
        # Pin the update's inputs so both branches see identical bits
        # (see the barrier note above _resolve_request_fused).
        z_in, ec, eu, noise, coefs_in, w_in = jax.lax.optimization_barrier(
            (z, ec, eu, noise, coefs, w))
        fused = use_fused and fused_step_lib.fits_vmem(
            int(np.prod(z.shape[1:])))
        # Per-shape trace-time decision (over-VMEM rows keep the
        # unfused chain, same policy as fused GroupNorm).
        step_impl = (fused_step_lib.fused_denoise_step if fused
                     else fused_step_lib.unfused_reference_step)
        z_next = step_impl(
            z_in, ec, eu, noise, coefs_in, w_in, sampler=sampler,
            objective=objective, eta=eta, cfg_rescale=phi,
            clip_denoised=clip_denoised)
        # Per-row anomaly mask: reduced on device so the host learns
        # "row i went non-finite" from a (B,) bool instead of pulling
        # the latent back every step. Read-only over z_next.
        finite = jnp.all(jnp.isfinite(z_next).reshape(B, -1), axis=1)
        return z_next, keys_next, finite

    @jax.jit
    def step_cached(params, z, keys, first, cond, coefs, w, cc):
        # Cached-conditioning twin (see docstring): identical body
        # except the cond branch arrives as device arguments.
        if param_transform is not None:
            params = param_transform(params)
        B = z.shape[0]
        both = jax.vmap(jax.random.split)(keys)
        k_carry, k_init = both[:, 0], both[:, 1]
        z0 = jax.vmap(lambda k: jax.random.normal(k, z.shape[1:]))(k_init)
        fmask = first.reshape((B,) + (1,) * (z.ndim - 1))
        z = jnp.where(fmask, z0.astype(z.dtype), z)
        keys = jnp.where(first[:, None], k_carry, keys)
        both = jax.vmap(jax.random.split)(keys)
        keys_next, k_step = both[:, 0], both[:, 1]

        pose_embs, cond_feats = _assemble_cached_cond(cc)
        batch = dict(cond, z=z, logsnr=coefs[:, logsnr_col])
        ec, eu = _raw_eps(model, params, batch, pose_embs=pose_embs,
                          cond_feats=cond_feats)
        noise = _step_noise(k_step, z)
        z_in, ec, eu, noise, coefs_in, w_in = jax.lax.optimization_barrier(
            (z, ec, eu, noise, coefs, w))
        fused = use_fused and fused_step_lib.fits_vmem(
            int(np.prod(z.shape[1:])))
        step_impl = (fused_step_lib.fused_denoise_step if fused
                     else fused_step_lib.unfused_reference_step)
        z_next = step_impl(
            z_in, ec, eu, noise, coefs_in, w_in, sampler=sampler,
            objective=objective, eta=eta, cfg_rescale=phi,
            clip_denoised=clip_denoised)
        finite = jnp.all(jnp.isfinite(z_next).reshape(B, -1), axis=1)
        return z_next, keys_next, finite

    return step_cached if cond_cache else step


def make_bank_step_fn(model, config: DiffusionConfig, k_max: int, *,
                      param_transform=None, cond_cache=False):
    """`make_slot_step_fn` with an optional per-row FRAME BANK — the
    trajectory-serving stepper program (sample/service.py; docs/DESIGN.md
    "Trajectory serving & stochastic conditioning").

      step(params, z, keys, first, cond, coefs, w, R2, t2,
           bank_x, bank_R, bank_t, bank_state)
        -> (z_next, keys_next, finite)

    On top of the slot-step contract: `bank_x` (B, k_max, H, W, C) holds
    each row's clean conditioning frames (the request's source view plus
    every frame it has generated so far, committed in-jit by
    `make_bank_commit_fn`), `bank_R`/`bank_t` their poses, and
    `bank_state` a (B, 2) int32 of [count, latest]. Rows with count > 0
    are TRAJECTORY rows: their conditioning view is drawn from the bank
    — uniformly over the first `count` entries with a third per-row PRNG
    split when `diffusion.stochastic_cond` is True (the 3DiM protocol),
    or the `latest` entry when False — and their target pose comes from
    the per-step (B, 3, 3)/(B, 3) `R2`/`t2` device arguments (the host
    uploads the CURRENT frame's pose each step, like the schedule
    coefficients, so advancing to the next orbit pose never rebuilds the
    ring). Rows with count == 0 are SINGLE-SHOT rows: they read their
    conditioning from `cond` exactly like `make_slot_step_fn`, and —
    crucially — consume the IDENTICAL per-row RNG stream (the pick split
    is computed for every row but single-shot rows select the two-way
    split results), so a single-shot request is BIT-identical whether it
    rides this program next to trajectory rows or the bank-free program
    of a service with serve.k_max=0 (tests/test_trajectory.py).

    The bank gather happens BEFORE the UNet forward, so
    `diffusion.fused_step` routes the post-forward update through the
    same fused Pallas kernel unchanged. k_max is part of the program
    SHAPE (one service = one k_max); everything per-request — step
    count, guidance, pose, bank fill — is a device argument, so the
    program identity stays bucket/shape-only and mixed single-shot +
    trajectory traffic compiles nothing after warmup.

    `cond_cache=True` returns the cached-conditioning twin:

      step(params, z, keys, first, cond, coefs, w, R2, t2,
           bank_x, bank_R, bank_t, bank_state, cc)
        -> (z_next, keys_next, finite)

    with `cc = (pose_c, pose_u, feats_c, bank_pose, bank_feats)`:
    the slot-step triple plus per-level (B, k_max, …) bank-entry pose
    embeddings and (B, k_max, Fc, H, W, ch) bank-entry stem features —
    every bank entry encoded against the row's CURRENT target pose at
    the frame boundary (sample/service.py re-encodes when the target
    advances, exactly when it restacks R2/t2). The stochastic pick
    gathers the cached EMBEDDINGS with the same idx (per-row encode
    commutes with the gather bitwise), single-shot rows select the
    request-level cache, and the raw bank_x/bank_R/bank_t stay in the
    signature only for the commit path's carry structure — the forward
    never reads them, so XLA drops the gathers. RNG stream and update
    math are byte-for-byte the uncached body.
    """
    if k_max < 1:
        raise ValueError(
            f"make_bank_step_fn: k_max={k_max} must be >= 1 (a bank-less "
            "stepper is make_slot_step_fn)")
    stochastic = config.stochastic_cond
    if stochastic not in (True, False):
        raise ValueError(
            f"diffusion.stochastic_cond={stochastic!r} must be True "
            "(random bank view per step) or False (most recent frame)")
    phi = config.cfg_rescale
    if not 0.0 <= phi <= 1.0:
        raise ValueError(f"cfg_rescale must be in [0, 1], got {phi}")
    clip_denoised = config.clip_denoised
    objective = config.objective
    if objective not in ("eps", "x0", "v"):
        raise ValueError(f"unknown objective {objective!r}")
    sampler = config.sampler
    eta = config.ddim_eta if sampler == "ddim" else 0.0
    if sampler == "dpm++":
        sampler = "ddim"  # first-order fallback, as in make_slot_step_fn
    if sampler not in ("ddpm", "ddim"):
        raise ValueError(f"unknown sampler {config.sampler!r}")
    use_fused = fused_step_lib.resolve_fused_step(config.fused_step)
    logsnr_col = STEP_COEF_KEYS.index("logsnr")

    @jax.jit
    def step(params, z, keys, first, cond, coefs, w, R2, t2,
             bank_x, bank_R, bank_t, bank_state):
        if param_transform is not None:
            params = param_transform(params)
        B = z.shape[0]
        count, latest = bank_state[:, 0], bank_state[:, 1]
        traj = count > 0
        # Init-noise draw for rows entering the ring: identical split
        # layout to make_slot_step_fn (and make_request_sampler).
        both = jax.vmap(jax.random.split)(keys)
        k_carry, k_init = both[:, 0], both[:, 1]
        z0 = jax.vmap(lambda k: jax.random.normal(k, z.shape[1:]))(k_init)
        fmask = first.reshape((B,) + (1,) * (z.ndim - 1))
        z = jnp.where(fmask, z0.astype(z.dtype), z)
        keys = jnp.where(first[:, None], k_carry, keys)
        # Per-step draw. Trajectory rows need a THIRD stream for the
        # stochastic-conditioning pick; single-shot rows must consume
        # the exact two-way split of the bank-free program, so both
        # splits are computed and selected per row — never assume
        # split(k, 3)[:2] == split(k, 2).
        two = jax.vmap(jax.random.split)(keys)
        if stochastic:
            three = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
            keys_next = jnp.where(traj[:, None], three[:, 0], two[:, 0])
            k_step = jnp.where(traj[:, None], three[:, 1], two[:, 1])
            idx = jax.vmap(
                lambda k, n: jax.random.randint(k, (), 0, n))(
                    three[:, 2], jnp.maximum(count, 1))
        else:
            keys_next, k_step = two[:, 0], two[:, 1]
            idx = latest
        # Bank gather, then per-row select against the request cond.
        take = lambda bank: jax.vmap(  # noqa: E731
            lambda b, i: jax.lax.dynamic_index_in_dim(
                b, i, 0, keepdims=False))(bank, idx)
        x_eff = jnp.where(traj.reshape((B, 1, 1, 1)),
                          take(bank_x), cond["x"])
        R1_eff = jnp.where(traj.reshape((B, 1, 1)),
                           take(bank_R), cond["R1"])
        t1_eff = jnp.where(traj.reshape((B, 1)),
                           take(bank_t), cond["t1"])
        # Pin the effective conditioning: the forward must see
        # materialized inputs, exactly like the bank-free program's cond
        # PARAMETERS, so XLA cannot fuse the gather/select producers
        # into the UNet and drift single-shot rows a ulp apart (the
        # same rationale as the update barrier below).
        x_eff, R1_eff, t1_eff, R2_in, t2_in = jax.lax.optimization_barrier(
            (x_eff, R1_eff, t1_eff, R2, t2))
        eff = {"x": x_eff, "R1": R1_eff, "t1": t1_eff,
               "R2": R2_in, "t2": t2_in, "K": cond["K"]}
        # Same row-unrolled encode + assembly barrier as the cached twin
        # (see the make_slot_step_fn note): every encode everywhere is
        # the B=1 row computation, so encoding the gathered view here
        # commutes bitwise with the cached twin's gather over bank
        # entries that were themselves row-encoded at the frame boundary.
        pose_c, feats_c = _per_row_encode(model, params, eff,
                                          jnp.ones((B,)))
        pose_u = precompute_pose_embs(
            model, params, jax.tree.map(lambda a: a[:1], eff),
            jnp.zeros((1,)))
        pose_embs, cond_feats = _assemble_cached_cond(
            (pose_c, pose_u, feats_c))
        batch = dict(eff, z=z, logsnr=coefs[:, logsnr_col])
        ec, eu = _raw_eps(model, params, batch, pose_embs=pose_embs,
                          cond_feats=cond_feats)
        noise = _step_noise(k_step, z)
        z_in, ec, eu, noise, coefs_in, w_in = jax.lax.optimization_barrier(
            (z, ec, eu, noise, coefs, w))
        fused = use_fused and fused_step_lib.fits_vmem(
            int(np.prod(z.shape[1:])))
        step_impl = (fused_step_lib.fused_denoise_step if fused
                     else fused_step_lib.unfused_reference_step)
        z_next = step_impl(
            z_in, ec, eu, noise, coefs_in, w_in, sampler=sampler,
            objective=objective, eta=eta, cfg_rescale=phi,
            clip_denoised=clip_denoised)
        # Same read-only per-row anomaly mask as make_slot_step_fn —
        # vital here: a non-finite frame committed to the bank would
        # poison every later frame's stochastic conditioning.
        finite = jnp.all(jnp.isfinite(z_next).reshape(B, -1), axis=1)
        return z_next, keys_next, finite

    @jax.jit
    def step_cached(params, z, keys, first, cond, coefs, w, R2, t2,
                    bank_x, bank_R, bank_t, bank_state, cc):
        # Cached-conditioning twin (see docstring): identical RNG head
        # and pick, but the gather runs over cached EMBEDDINGS and the
        # raw bank_x/bank_R/bank_t are never read (kept for the carry
        # structure only — XLA drops them).
        if param_transform is not None:
            params = param_transform(params)
        pose_c, pose_u, feats_c, bank_pose, bank_feats = cc
        B = z.shape[0]
        count, latest = bank_state[:, 0], bank_state[:, 1]
        traj = count > 0
        both = jax.vmap(jax.random.split)(keys)
        k_carry, k_init = both[:, 0], both[:, 1]
        z0 = jax.vmap(lambda k: jax.random.normal(k, z.shape[1:]))(k_init)
        fmask = first.reshape((B,) + (1,) * (z.ndim - 1))
        z = jnp.where(fmask, z0.astype(z.dtype), z)
        keys = jnp.where(first[:, None], k_carry, keys)
        two = jax.vmap(jax.random.split)(keys)
        if stochastic:
            three = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
            keys_next = jnp.where(traj[:, None], three[:, 0], two[:, 0])
            k_step = jnp.where(traj[:, None], three[:, 1], two[:, 1])
            idx = jax.vmap(
                lambda k, n: jax.random.randint(k, (), 0, n))(
                    three[:, 2], jnp.maximum(count, 1))
        else:
            keys_next, k_step = two[:, 0], two[:, 1]
            idx = latest
        # Same per-row gather/select as the uncached body, lifted from
        # pixels to cached activations: the per-row encode commutes with
        # the gather bitwise (the bank entries were encoded row-wise at
        # the frame boundary), and single-shot rows select the
        # request-level cache. _assemble_cached_cond pins the assembled
        # result, so the forward sees materialized inputs exactly like
        # the uncached program's eff barrier.
        take = lambda bank: jax.vmap(  # noqa: E731
            lambda b, i: jax.lax.dynamic_index_in_dim(
                b, i, 0, keepdims=False))(bank, idx)
        tmask = traj.reshape((B, 1, 1, 1, 1))
        sel_pose = tuple(
            jnp.where(tmask, take(bp), pc)
            for bp, pc in zip(bank_pose, pose_c))
        sel_feats = jnp.where(tmask, take(bank_feats), feats_c)
        pose_embs, cond_feats = _assemble_cached_cond(
            (sel_pose, pose_u, sel_feats))
        batch = dict(cond, z=z, logsnr=coefs[:, logsnr_col])
        ec, eu = _raw_eps(model, params, batch, pose_embs=pose_embs,
                          cond_feats=cond_feats)
        noise = _step_noise(k_step, z)
        z_in, ec, eu, noise, coefs_in, w_in = jax.lax.optimization_barrier(
            (z, ec, eu, noise, coefs, w))
        fused = use_fused and fused_step_lib.fits_vmem(
            int(np.prod(z.shape[1:])))
        step_impl = (fused_step_lib.fused_denoise_step if fused
                     else fused_step_lib.unfused_reference_step)
        z_next = step_impl(
            z_in, ec, eu, noise, coefs_in, w_in, sampler=sampler,
            objective=objective, eta=eta, cfg_rescale=phi,
            clip_denoised=clip_denoised)
        finite = jnp.all(jnp.isfinite(z_next).reshape(B, -1), axis=1)
        return z_next, keys_next, finite

    return step_cached if cond_cache else step


def make_bank_commit_fn():
    """In-jit frame-bank writeback for the trajectory stepper.

      commit(bank_x, bank_R, bank_t, frame, pos, R2, t2)
        -> (bank_x, bank_R, bank_t)

    Writes `frame` — the device-resident row of the stepper latent that
    just finished denoising — into position `pos` of ONE slot's bank
    ((k_max, H, W, C) arrays, sample/stepper.FrameBank), with the pose
    it was generated at: the finished frame joins its own conditioning
    pool WITHOUT a host round-trip, so the next frame's stochastic
    conditioning reads it straight from HBM. `pos` is a traced scalar —
    one compiled program per (k_max, H, W) shape serves every slot,
    every ring bucket, and every sliding-window position."""

    @jax.jit
    def commit(bank_x, bank_R, bank_t, frame, pos, R2, t2):
        bank_x = jax.lax.dynamic_update_slice(
            bank_x, frame[None].astype(bank_x.dtype), (pos, 0, 0, 0))
        bank_R = jax.lax.dynamic_update_slice(
            bank_R, R2[None].astype(bank_R.dtype), (pos, 0, 0))
        bank_t = jax.lax.dynamic_update_slice(
            bank_t, t2[None].astype(bank_t.dtype), (pos, 0))
        return bank_x, bank_R, bank_t

    return commit


def make_stochastic_sampler(model, schedule: DiffusionSchedule,
                            config: DiffusionConfig, max_pool: int,
                            precompute_pose: Optional[bool] = None):
    """Sampler with 3DiM stochastic conditioning over a view pool.

    cond pool: x (B, max_pool, H, W, 3), R1 (B, max_pool, 3, 3),
    t1 (B, max_pool, 3); `num_views` (traced scalar ≤ max_pool) bounds the
    per-step random choice, so one compiled program serves a growing pool
    (autoregressive generation never recompiles).

    `precompute_pose`: hoist the pose-conditioning path out of the scan —
    embeddings for every (pool view, target) pair are computed once and
    indexed per step, and the unconditional CFG half is computed once
    through the real masked pipeline (conv biases and learned pos/ref
    embeddings survive the mask, so it is NOT zeros). Identical math to
    the in-loop path; costs max_pool× pose-embedding HBM residency for the
    whole trajectory, so None (default) auto-disables when that exceeds
    ~512 MB (e.g. 256px paper-scale pools) and falls back to in-loop
    computation.
    """
    w = config.guidance_weight
    # memoryless: the conditioning view is re-drawn every denoise step, so
    # multistep solver history is invalid here (see _make_update).
    update, init_aux = _make_update(schedule, config, memoryless=True)

    @partial(jax.jit, static_argnames=())
    def sample(params, key, pool: dict, target_pose: dict,
               num_views: jnp.ndarray) -> jnp.ndarray:
        B, P, H, W, C = pool["x"].shape
        key, k_init = jax.random.split(key)
        z0 = jax.random.normal(k_init, (B, H, W, C))
        ts = jnp.arange(schedule.num_timesteps - 1, -1, -1)

        do_pre = precompute_pose
        if do_pre is None:
            # Level-0 embedding is (B, P, F, H, W, emb_ch); finer levels
            # add ~1/3 more. Auto-disable past ~512 MB residency.
            mcfg = model.config
            itemsize = jnp.dtype(mcfg.dtype).itemsize
            est = (4 / 3) * B * P * 2 * H * W * mcfg.emb_ch * itemsize
            do_pre = est <= 512 * 1024 * 1024

        pose_all = uncond_embs = None
        if do_pre:
            flat = {
                "x": pool["x"].reshape(B * P, H, W, C),
                "R1": pool["R1"].reshape(B * P, 3, 3),
                "t1": pool["t1"].reshape(B * P, 3),
                "R2": jnp.broadcast_to(target_pose["R2"][:, None],
                                       (B, P, 3, 3)).reshape(B * P, 3, 3),
                "t2": jnp.broadcast_to(target_pose["t2"][:, None],
                                       (B, P, 3)).reshape(B * P, 3),
                "K": jnp.broadcast_to(target_pose["K"][:, None],
                                      (B, P, 3, 3)).reshape(B * P, 3, 3),
            }
            pose_all = [p.reshape((B, P) + p.shape[1:])
                        for p in precompute_pose_embs(
                            model, params, flat, jnp.ones((B * P,)))]
            # Unconditional half ONCE through the real masked path; it is
            # pool-independent (the mask zeroes the pose embedding before
            # the convs), so any single pair serves.
            pair0 = {
                "x": pool["x"][:, 0], "R1": pool["R1"][:, 0],
                "t1": pool["t1"][:, 0], "R2": target_pose["R2"],
                "t2": target_pose["t2"], "K": target_pose["K"],
            }
            uncond_embs = precompute_pose_embs(model, params, pair0,
                                               jnp.zeros((B,)))

        def body(carry, t):
            z, key, aux = carry
            key, k_pick, k_step = jax.random.split(key, 3)
            # Stochastic conditioning: uniform over the first num_views
            # entries of the pool, re-drawn EVERY denoising step.
            idx = jax.random.randint(k_pick, (), 0, num_views)
            doubled_emb = None
            if do_pre:
                doubled_emb = tuple(
                    jnp.concatenate(
                        [jax.lax.dynamic_index_in_dim(p, idx, axis=1,
                                                      keepdims=False), u],
                        axis=0)
                    for p, u in zip(pose_all, uncond_embs))
            batch = {
                "x": jax.lax.dynamic_index_in_dim(pool["x"], idx, axis=1,
                                                  keepdims=False),
                "R1": jax.lax.dynamic_index_in_dim(pool["R1"], idx, axis=1,
                                                   keepdims=False),
                "t1": jax.lax.dynamic_index_in_dim(pool["t1"], idx, axis=1,
                                                   keepdims=False),
                "R2": target_pose["R2"],
                "t2": target_pose["t2"],
                "K": target_pose["K"],
                "z": z,
                "logsnr": jnp.full((B,), schedule.logsnr(t)),
            }
            outs = _cfg_eps(model, params, batch, w, pose_embs=doubled_emb)
            z, aux = update(z, t, outs, k_step, aux)
            return (z, key, aux), None

        (z, _, _), _ = jax.lax.scan(body, (z0, key, init_aux(z0)), ts)
        return z

    return sample


def autoregressive_generate(model, schedule: DiffusionSchedule,
                            config: DiffusionConfig, params, key,
                            first_view: dict, target_poses: dict,
                            max_pool: Optional[int] = None,
                            sampler=None) -> jnp.ndarray:
    """Generate a trajectory of novel views autoregressively.

    Starting from the real view(s) in `first_view` (x (B,H,W,3) for one
    view — the 3DiM paper protocol — or (B,P0,H,W,3) for a pool of P0 real
    captures; R1/t1 ranked alike; K (B,3,3)), each target pose in
    `target_poses` (R2/t2: (B, N, …)) is sampled with stochastic
    conditioning over ALL available views, and the result joins the pool.
    Returns (B, N, H, W, 3). One compiled sampler serves every iteration
    (the pool is padded to `max_pool`). A caller looping over many batches
    should build the sampler once with `make_stochastic_sampler` and pass
    it as `sampler` so each call reuses the same jit cache.
    """
    if first_view["x"].ndim == 4:  # single real view → pool of one
        first_view = dict(
            first_view,
            x=first_view["x"][:, None],
            R1=first_view["R1"][:, None],
            t1=first_view["t1"][:, None],
        )
    B, P0, H, W, C = first_view["x"].shape
    N = target_poses["R2"].shape[1]
    max_pool = max_pool or (N + P0)
    if max_pool < P0:
        raise ValueError(f"max_pool {max_pool} < {P0} initial views")
    if sampler is None:
        sampler = make_stochastic_sampler(model, schedule, config, max_pool)

    # Pool padded with repeats of the first view (never selected: idx < n).
    pool = {
        "x": jnp.concatenate(
            [first_view["x"], jnp.broadcast_to(
                first_view["x"][:, :1], (B, max_pool - P0, H, W, C))], 1),
        "R1": jnp.concatenate(
            [first_view["R1"], jnp.broadcast_to(
                first_view["R1"][:, :1], (B, max_pool - P0, 3, 3))], 1),
        "t1": jnp.concatenate(
            [first_view["t1"], jnp.broadcast_to(
                first_view["t1"][:, :1], (B, max_pool - P0, 3))], 1),
    }
    outs = []
    for i in range(N):
        key, k_i = jax.random.split(key)
        target_pose = {
            "R2": target_poses["R2"][:, i],
            "t2": target_poses["t2"][:, i],
            "K": first_view["K"],
        }
        # Valid slots: views generated past a small max_pool are not stored
        # (guard below), so the draw range must cap at capacity — an
        # uncapped count would make randint exceed the pool and JAX's index
        # clamping would silently bias selection toward the last slot.
        img = sampler(params, k_i, pool, target_pose,
                      jnp.asarray(min(P0 + i, max_pool), jnp.int32))
        outs.append(img)
        if P0 + i < max_pool:
            pool["x"] = pool["x"].at[:, P0 + i].set(img)
            pool["R1"] = pool["R1"].at[:, P0 + i].set(target_pose["R2"])
            pool["t1"] = pool["t1"].at[:, P0 + i].set(target_pose["t2"])
    return jnp.stack(outs, axis=1)
