"""Host-side schedule bank for the step-level serving scheduler.

The stepper's device program (`sample/ddpm.make_slot_step_fn`) is keyed
on the bucket SHAPE only; everything schedule-dependent — a row's
timestep position, its respaced ladder, its guidance weight — rides as
device arguments. This module owns the host side of that contract: for
each requested sampling-step count it builds (once, cached) the float32
coefficient tables of the respaced schedule, exactly the values
`DiffusionSchedule`'s jitted gathers would produce on device, so a host
`coefs[name][t]` gather feeds the program the same numbers the
whole-request `lax.scan` sampler reads from its on-device tables.

One bank per step count, one program per bucket: a mixed 4-step/256-step
warm sweep compiles NOTHING (asserted by tools/serve_bench.py and
tests/test_stepper.py) — the fix for the PR 3 cache key folding `steps`
into the program identity, which under step-level scheduling would have
recompiled per step-count.

The packed (B, K) matrix is ALSO the fused denoise-step kernel's
row-parameter contract (ops/fused_step.py consumes these exact columns
as device arguments; an import-time assert pins its baked indices to
STEP_COEF_KEYS), so `diffusion.fused_step` changes the program BODY,
never this host-side protocol or the cache-key shape.
"""

from __future__ import annotations

import threading
from typing import Dict

import jax.numpy as jnp
import numpy as np

from novel_view_synthesis_3d_tpu.config import DiffusionConfig
from novel_view_synthesis_3d_tpu.diffusion.schedules import sampling_schedule
from novel_view_synthesis_3d_tpu.sample.ddpm import STEP_COEF_KEYS


class StepBank:
    """Per-step-count coefficient tables (numpy float32, host-resident).

    `n` is the ACTUAL respaced ladder length (`respace` dedups timesteps,
    so n <= requested steps). A request walks t = n-1, n-2, …, 0; its
    per-step device argument is `table[t]`, one packed
    (len(STEP_COEF_KEYS),) row — the stepper stacks one such row per
    slot into the (B, K) matrix `make_slot_step_fn` consumes, so the
    whole ring's schedule state moves host→device in ONE transfer per
    step. `coefs` exposes the same values as named column views.
    """

    __slots__ = ("steps", "n", "table", "coefs")

    def __init__(self, config: DiffusionConfig, steps: int):
        sched = sampling_schedule(config, steps)
        n = sched.num_timesteps
        ts = jnp.arange(n)
        self.steps = int(steps)
        self.n = int(n)
        by_name: Dict[str, np.ndarray] = {
            # logsnr evaluated through the schedule's own jnp path (one
            # vectorized call) so the values match what the scan sampler
            # computes per step on device.
            "logsnr": np.asarray(sched.logsnr(ts), np.float32),
            "sqrt_recip_acp": np.asarray(
                sched.sqrt_recip_alphas_cumprod, np.float32),
            "sqrt_recipm1_acp": np.asarray(
                sched.sqrt_recipm1_alphas_cumprod, np.float32),
            "sqrt_acp": np.asarray(sched.sqrt_alphas_cumprod, np.float32),
            "sqrt_1macp": np.asarray(
                sched.sqrt_one_minus_alphas_cumprod, np.float32),
            "pm_coef1": np.asarray(sched.posterior_mean_coef1, np.float32),
            "pm_coef2": np.asarray(sched.posterior_mean_coef2, np.float32),
            "post_log_var": np.asarray(
                sched.posterior_log_variance_clipped, np.float32),
            "acp": np.asarray(sched.alphas_cumprod, np.float32),
            "acp_prev": np.asarray(sched.alphas_cumprod_prev, np.float32),
            "nonzero": (np.arange(n) > 0).astype(np.float32),
        }
        assert set(by_name) == set(STEP_COEF_KEYS)
        # (n, K) with columns in STEP_COEF_KEYS order — the layout the
        # compiled step program indexes.
        self.table = np.stack([by_name[k] for k in STEP_COEF_KEYS], axis=1)
        self.coefs: Dict[str, np.ndarray] = {
            k: self.table[:, i] for i, k in enumerate(STEP_COEF_KEYS)}


class FrameBank:
    """One trajectory request's DEVICE-RESIDENT frame bank.

    `x`/`R`/`t` are jax device arrays of shape (k_max, H, W, C) /
    (k_max, 3, 3) / (k_max, 3) holding the request's clean conditioning
    views: the source view at seed time, then every generated frame,
    committed in-jit by `sample/ddpm.make_bank_commit_fn` straight from
    the stepper's batched latent — a finished frame joins its own
    conditioning pool without touching the host. The serving stepper
    stacks the ring's banks (a device-side jnp.stack) into the
    (B, k_max, …) tensors `make_bank_step_fn` gathers from; because the
    per-slot arrays are the authoritative copy, a ring rebuild restacks
    bit-identically to what the previous carry held — trajectory rows
    stay ring-composition invariant.

    Overflow policy: SLIDING WINDOW over the most recent `cap` views
    (ring-buffer writes at total % cap, count saturates at cap). Chosen
    over reservoir sampling because it is deterministic — same request,
    same bank content, bit-identical orbit — and recency is what keeps
    long orbits locally consistent; the tradeoff (the original real
    view eventually leaves the window on orbits longer than cap) is
    deliberate and tested (tests/test_trajectory.py). `cap` may be
    smaller than the service-wide array size `k_max`: the program shape
    never changes per request, only the effective window."""

    __slots__ = ("k_max", "cap", "x", "R", "t", "count", "total")

    def __init__(self, k_max: int, cap: int, x0: np.ndarray,
                 R0: np.ndarray, t0: np.ndarray):
        if not 1 <= cap <= k_max:
            raise ValueError(
                f"FrameBank cap={cap} must be in [1, k_max={k_max}]")
        import jax as _jax

        self.k_max = int(k_max)
        self.cap = int(cap)
        H, W, C = np.asarray(x0).shape
        x = np.zeros((k_max, H, W, C), np.float32)
        R = np.zeros((k_max, 3, 3), np.float32)
        t = np.zeros((k_max, 3), np.float32)
        x[0], R[0], t[0] = x0, R0, t0
        # One upload per trajectory — the request's whole conditioning
        # lifetime happens on device after this. device_put COMMITS the
        # arrays, matching the placement of the jitted commit outputs
        # that replace them, so the commit program compiles exactly once
        # per (k_max, H, W) shape.
        self.x, self.R, self.t = _jax.device_put(
            (x, R, t), _jax.devices()[0])
        self.count = 1  # valid entries (saturates at cap)
        self.total = 1  # views ever written (window position source)

    def commit(self, commit_fn, frame_dev, R2: np.ndarray,
               t2: np.ndarray) -> int:
        """Write one finished frame (a device array row of the stepper's
        latent) at the sliding-window position via the jitted commit
        program; returns the position written."""
        pos = self.total % self.cap
        self.x, self.R, self.t = commit_fn(
            self.x, self.R, self.t, frame_dev,
            np.int32(pos), np.asarray(R2, np.float32),
            np.asarray(t2, np.float32))
        self.total += 1
        self.count = min(self.total, self.cap)
        return pos

    @property
    def latest(self) -> int:
        """Position of the most recent entry (stochastic_cond=False)."""
        return (self.total - 1) % self.cap


class ScheduleBank:
    """Thread-safe cache of StepBanks keyed by requested step count.

    Banks are tiny (n × 11 float32 scalars) and immutable, so the cache
    never evicts — a service serving every step count from 1 to
    diffusion.timesteps holds at most that many rows of coefficients.
    """

    def __init__(self, config: DiffusionConfig):
        self._config = config
        self._banks: Dict[int, StepBank] = {}
        self._lock = threading.Lock()
        # Build/hit counters: a bank build is a host-side schedule
        # respace (cheap, but each one is a NEW step count seen — the
        # service summary surfaces them so a bench run can show its
        # step-class mix at a glance).
        self.builds = 0
        self.hits = 0

    def get(self, steps: int) -> StepBank:
        with self._lock:
            bank = self._banks.get(steps)
            if bank is None:
                bank = self._banks[steps] = StepBank(self._config, steps)
                self.builds += 1
            else:
                self.hits += 1
            return bank

    def counters(self) -> dict:
        with self._lock:
            return {"banks_built": self.builds, "bank_hits": self.hits,
                    "step_classes": sorted(self._banks)}
