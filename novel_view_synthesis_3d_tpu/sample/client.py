"""Shared client-side retry/backoff for the serving surface.

One implementation of the structured-rejection retry loop, used by the
`nvs3d serve` CLI client AND the fleet router (serve/router.py): a
`Rejected(retryable=True)` carries `retry_after_s` — the server's own
estimate of when capacity returns — and the client honors it with
jitter so a herd of rejected clients doesn't re-arrive in lockstep.
Two drifting copies of this loop is exactly how a fleet ends up with
one polite client and one retry-storming one.
"""

from __future__ import annotations

import random
import time


def submit_with_retry(submit, *, retries: int = 4, sleep=None, rng=None):
    """Call `submit` (a zero-arg closure over service.submit/
    submit_trajectory), honoring the service's structured rejections.

    A rejection with `retryable=True` carries `retry_after_s` — the
    server's own estimate of when capacity returns (brownout shed,
    drain-for-restart, queue full). The client waits that long plus up
    to 50% jitter (so a herd of rejected clients doesn't re-arrive in
    lockstep) and retries, at most `retries` more times; a non-retryable
    rejection or an exhausted budget re-raises the last error.

    `sleep`/`rng` are injection points for tests (real time.sleep and a
    fresh random.Random by default).
    """
    sleep = sleep if sleep is not None else time.sleep
    rng = rng if rng is not None else random.Random()
    for attempt in range(retries + 1):
        try:
            return submit()
        except Exception as e:
            if not getattr(e, "retryable", False) or attempt == retries:
                raise
            sleep(retry_delay_s(e, attempt, rng))


def retry_delay_s(error, attempt: int, rng=None) -> float:
    """Backoff for one retryable rejection: the server's retry_after_s
    when it named one, else exponential from 50ms, plus up to 50%
    jitter. Exposed separately so the router's failover loop (which
    retries against a DIFFERENT replica, not the rejecting one) can
    share the same backoff arithmetic."""
    rng = rng if rng is not None else random.Random()
    base = float(getattr(error, "retry_after_s", 0.0) or 0.0)
    if base <= 0.0:
        base = 0.05 * (2 ** attempt)
    return base * (1.0 + 0.5 * rng.random())
