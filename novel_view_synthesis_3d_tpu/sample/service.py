"""Sampling service: step-level continuous batching over a slot ring.

The ROADMAP north star is "serve heavy traffic from millions of users",
but until this module sampling was a one-shot CLI path: every request
shape compiled a fresh XLA program and requests ran one at a time at
batch sizes far below what keeps an accelerator's MXU fed. The reverse
process is 100s of UNet steps on a doubled-batch (CFG), so per-request
latency is dominated by device compute — exactly the regime where
micro-batching (torchgpipe, arXiv 2004.09910) and keeping the device fed
from the host side (MinatoLoader, arXiv 2509.10712) pay off.

Two schedulers share the front-end (serve.scheduler):

  - 'step' (default; docs/DESIGN.md "Continuous batching & distillation"):
    a persistent STEPPER — the diffusion analogue of LLM continuous
    batching. One compiled denoise-STEP program per bucket shape
    (sample/ddpm.make_slot_step_fn) runs over a ring of active request
    slots, each slot carrying its own (z, t, cond, keys, steps_remaining,
    model_version). New arrivals join the ring BETWEEN steps (filling
    padded slots), finished rows exit and respond immediately — a 4-step
    distilled request never waits behind a 256-step one. Heterogeneous
    per-row step counts and guidance weights ride in ONE batch: the
    schedule position t and w are device arguments (host-gathered by
    sample/stepper.ScheduleBank), never compile-time constants, so the
    program cache is keyed on bucket/shape only and a mixed 4/256-step
    warm sweep compiles nothing. Per-sample key threading makes each
    row's image bit-identical whether it stepped solo or interleaved
    with others joining/leaving mid-flight (ring-composition
    invariance, tests/test_stepper.py). A pending hot swap DRAINS the
    ring first: in-flight requests finish on their start version, queued
    arrivals ride the new one.

    TRAJECTORY SERVING rides the stepper (serve.k_max > 0; docs/DESIGN.md
    "Trajectory serving & stochastic conditioning"): `submit_trajectory`
    takes a source view plus an N-pose orbit, and the slot carries a
    device-resident FRAME BANK — (k_max, H, W, C) clean frames + poses.
    Each denoise step draws the row's conditioning view from its bank
    with the slot's PRNG carry (stochastic conditioning as an in-jit
    gather, 3DiM §3.2), a finished frame streams to the client AND is
    committed back into its own bank in-jit, and the next frame re-enters
    the ring without a host round-trip (fresh init noise via the `first`
    flag; the next pose rides the per-step device arguments). Because
    bank fill, pose, schedule, and guidance are all device arguments,
    mixed single-shot + trajectory traffic runs ONE program per bucket —
    and with serve.k_max=0 the stepper compiles the exact bank-free
    program, so single-shot serving is bit-identical to a build without
    trajectory support (zero-cost when unused). Hot swaps still drain
    the ring: an in-flight orbit finishes ALL frames on its start
    version (orbit consistency beats swap latency); the orbit deadline
    is re-checked at each frame's admission, and a mid-orbit expiry
    returns the completed frames in a structured TrajectoryExpired.
  - 'request': the PR 3 whole-request dispatcher (one lax.scan per
    coalesced same-program group), kept as the serve_bench baseline and
    for exact dpm++ 2M serving.

Shared architecture (docs/DESIGN.md "Serving"):

  - a BOUNDED request queue with backpressure: a submit past
    `serve.queue_depth` is rejected immediately with a reason (and an
    events.csv `reject` row — the trainer's fault-event convention)
    instead of growing tail latency without bound;
  - a worker thread COALESCES queued requests into one batch: it holds
    the oldest request open for `serve.flush_timeout_ms` so co-riders
    can join, up to `serve.max_batch`, and pads the group to the next
    power-of-two BUCKET size (pad rows are repeats of the last request
    and are sliced off the result — `make_request_sampler`'s per-sample
    RNG streams guarantee padding cannot change any request's image);
  - an LRU SAMPLER-PROGRAM CACHE keyed by (bucket, image size, k,
    sampler/steps/guidance config): warm traffic never recompiles, and
    the bucket ladder bounds the number of distinct programs to
    log2(max_batch)+1 per sampler config;
  - per-request DEADLINES: a request still queued past its deadline is
    rejected (deadline_exceeded) rather than served uselessly late;
  - SHARD-AWARE dispatch: when the service is built over a device mesh,
    buckets that divide the mesh 'data' axis dispatch through
    `parallel/mesh.shard_batch`, so a multi-chip mesh serves one
    coalesced batch data-parallel; ragged buckets dispatch replicated
    over the same mesh (params live on the mesh's device set, so this
    is the placement-compatible fallback — wasteful, never wrong);
  - instrumentation via `utils/profiling.ServiceStats`: per-request
    queue-wait / compile / device spans and a requests-per-second
    counter (tools/serve_bench.py reads these);
  - SERVING PRECISION (docs/DESIGN.md "Serving precision & fused
    kernels"): `serve.precision` decides what _stage_params puts on
    device — f32 as published, bf16 cast, or weight-only int8 with
    in-jit dequant (sample/precision.py) — for the initial weights AND
    every hot swap; the program-cache keys fold (precision, fused_step)
    in, and `diffusion.fused_step` routes the per-step update through
    the fused Pallas kernel (ops/fused_step.py) in both schedulers;
  - ZERO-DOWNTIME HOT RELOAD (docs/DESIGN.md "Model lifecycle"):
    `swap_params` stages a new param tree on the same placement (mesh
    replication or default device) ALONGSIDE the live one, and the
    worker thread flips the (params, model_version) reference BETWEEN
    dispatches — a dispatch in flight finishes on the version it
    started on, queued requests ride the new one. The sampler-program
    cache is keyed on shapes/config, not params, so every warm program
    survives the swap (zero recompiles — asserted by
    tools/serve_bench.py --hot-swap and tests/test_registry.py); the
    old tree's service-owned device buffers are freed after the flip.
    Every response and event row carries `model_version`; the
    registry's RegistryWatcher drives this from a channel pointer.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from novel_view_synthesis_3d_tpu import obs
from novel_view_synthesis_3d_tpu.obs import reqtrace
from novel_view_synthesis_3d_tpu.obs import slo as slo_lib
from novel_view_synthesis_3d_tpu.utils import faultinject
from novel_view_synthesis_3d_tpu.config import DiffusionConfig, ServeConfig
from novel_view_synthesis_3d_tpu.diffusion.schedules import sampling_schedule
from novel_view_synthesis_3d_tpu.parallel import mesh as mesh_lib
from novel_view_synthesis_3d_tpu.ops.fused_step import resolve_fused_step
from novel_view_synthesis_3d_tpu.sample import precision as precision_lib
from novel_view_synthesis_3d_tpu.sample.ddpm import (
    make_bank_commit_fn,
    make_bank_step_fn,
    make_cond_encode_fn,
    make_request_sampler,
    make_slot_step_fn,
)
from novel_view_synthesis_3d_tpu.sample.stepper import FrameBank, ScheduleBank
from novel_view_synthesis_3d_tpu.utils.profiling import ServiceStats

COND_KEYS = ("x", "R1", "t1", "R2", "t2", "K")
# Conditioning a trajectory request must supply (its frames' target
# poses come from the pose list, not the cond dict).
TRAJ_COND_KEYS = ("x", "R1", "t1", "K")


class ServeError(RuntimeError):
    """Base class for request-level serving failures."""


class Rejected(ServeError):
    """Request refused at submit time (backpressure / bad input).

    The refusal is STRUCTURED (docs/DESIGN.md "Serving survivability"):
    `retryable=True` means the request itself was fine and the service
    was merely loaded/draining/restarting — clients should back off
    `retry_after_s` (plus jitter; cli.submit_with_retry) and resubmit.
    `retryable=False` (malformed conditioning, bad step count) means a
    retry would fail identically."""

    def __init__(self, message: str, *, retryable: bool = False,
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.retryable = retryable
        self.retry_after_s = float(retry_after_s)


class SampleAnomaly(ServeError):
    """A ring row's latent went non-finite and the slot was quarantined.

    The per-row finite mask (a device-side reduce folded into the step
    program, sample/ddpm.make_slot_step_fn) flagged this request's z;
    after `serve.anomaly_strikes` consecutive strikes the slot is
    EVICTED — its co-riders are untouched (ring-composition invariance
    means the poison cannot spread across rows) and nothing non-finite
    is ever streamed, resolved, or committed to a frame bank. Retryable:
    the usual causes (distilled/int8 students under guidance-weight
    extremes) are stochastic, so the same request often serves clean on
    resubmit. For trajectory tickets the frames already streamed ride
    along (`frames`); `frame_index` names the first frame NOT
    delivered."""

    retryable = True

    def __init__(self, message: str, *,
                 frames: Optional[List[np.ndarray]] = None,
                 frame_index: int = 0, retry_after_s: float = 0.0):
        super().__init__(message)
        self.frames = list(frames) if frames else []
        self.frame_index = int(frame_index)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(ServeError):
    """Request expired in the queue before dispatch."""


class TrajectoryExpired(DeadlineExceeded):
    """A trajectory request's deadline passed mid-orbit.

    Expiry is checked at each FRAME's admission (the frame boundary):
    frames already denoised were delivered on the ticket's stream and
    ride along here — the structured partial result — while
    `frame_index` names the first frame that was NOT generated."""

    def __init__(self, message: str, *, frames: List[np.ndarray],
                 frame_index: int):
        super().__init__(message)
        self.frames = frames
        self.frame_index = frame_index


def _normalize_poses(poses) -> tuple:
    """Trajectory pose list → ((N, 3, 3) R2, (N, 3) t2), loudly."""
    if isinstance(poses, dict):
        R = np.asarray(poses.get("R2"), np.float32)
        t = np.asarray(poses.get("t2"), np.float32)
    else:
        arr = np.asarray(poses, np.float32)
        if arr.ndim != 3 or arr.shape[-2:] != (4, 4):
            raise Rejected(
                "trajectory poses must be an (N, 4, 4) cam→world stack "
                f"or {{'R2': (N, 3, 3), 't2': (N, 3)}}; got shape "
                f"{arr.shape}")
        R, t = arr[:, :3, :3], arr[:, :3, 3]
    if (R.ndim != 3 or R.shape[-2:] != (3, 3)
            or t.shape != (R.shape[0], 3)):
        raise Rejected(
            f"trajectory poses malformed: R2 {R.shape}, t2 {t.shape} "
            "(want (N, 3, 3) and (N, 3))")
    return np.ascontiguousarray(R), np.ascontiguousarray(t)


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest power-of-two bucket >= n, capped at max_batch."""
    if n < 1:
        raise ValueError(f"bucket_for: n={n} must be >= 1")
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class Ticket:
    """Handle for one submitted request; `result()` blocks until served.

    `timing` (populated at resolution) carries the request's spans:
    queue_wait_s, device_s (or compile_s for the batch that warmed its
    program), plus the bucket and real batch size it rode in."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.timing: dict = {}
        # Registry version the request was served on ("" pre-resolution
        # or for services constructed without one).
        self.model_version: str = ""
        self._done = threading.Event()
        self._image: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not served within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._image

    # -- resolution (worker thread) ------------------------------------
    def _resolve(self, image: np.ndarray, timing: dict) -> None:
        self._image = image
        self.timing.update(timing)
        self._done.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()


class TrajectoryTicket:
    """Handle for one trajectory request: frames STREAM as they complete.

    `frames()` yields (frame_index, image) in order, blocking until each
    is denoised — the client renders the orbit while later frames are
    still on device. `result()` blocks for the whole orbit and returns
    the stacked (N, H, W, 3) array. A mid-orbit deadline expiry raises
    `TrajectoryExpired` from both, carrying every completed frame."""

    def __init__(self, request_id: int, num_frames: int):
        self.request_id = request_id
        self.num_frames = num_frames
        self.timing: dict = {}
        self.model_version: str = ""
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._frames: List[np.ndarray] = []
        self._frame_timing: List[dict] = []
        self._waiters: List[threading.Event] = []
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def frames_completed(self) -> int:
        with self._lock:
            return len(self._frames)

    def frames(self, timeout: Optional[float] = None):
        """Yield (frame_index, image) as each frame completes."""
        i = 0
        while i < self.num_frames:
            img = self._wait_frame(i, timeout)
            yield i, img
            i += 1

    def next_frame(self, index: int,
                   timeout: Optional[float] = None) -> np.ndarray:
        return self._wait_frame(index, timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"trajectory {self.request_id} not finished within "
                f"{timeout}s ({self.frames_completed()}/"
                f"{self.num_frames} frames)")
        if self._error is not None:
            raise self._error
        with self._lock:
            return np.stack(self._frames)

    # -- internals -----------------------------------------------------
    def _wait_frame(self, index: int, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if index < len(self._frames):
                    return self._frames[index]
                if self._error is not None:
                    raise self._error
                if self._done.is_set():
                    raise ServeError(
                        f"trajectory {self.request_id} finished without "
                        f"frame {index}")
                ev = threading.Event()
                self._waiters.append(ev)
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if not ev.wait(left):
                raise TimeoutError(
                    f"frame {index} of trajectory {self.request_id} not "
                    f"served within {timeout}s")

    def _notify(self) -> None:
        for ev in self._waiters:
            ev.set()
        self._waiters.clear()

    # -- resolution (worker thread) ------------------------------------
    def _deliver(self, image: np.ndarray, timing: dict) -> None:
        with self._lock:
            self._frames.append(image)
            self._frame_timing.append(timing)
            self._notify()

    def _complete(self, timing: dict) -> None:
        self.timing.update(timing)
        with self._lock:
            self._notify()
        self._done.set()

    def _fail(self, error: BaseException) -> None:
        with self._lock:
            self._error = error
            self._notify()
        self._done.set()


class _Request:
    __slots__ = ("ticket", "cond", "key", "program_key", "t_submit",
                 "deadline_s", "trace_id", "swaps_at_submit",
                 "swap_drains", "rides", "responded")

    def __init__(self, ticket: Ticket, cond: Dict[str, np.ndarray],
                 key: np.ndarray, program_key: tuple, t_submit: float,
                 deadline_s: float):
        self.ticket = ticket
        self.cond = cond
        self.key = key
        self.program_key = program_key
        self.t_submit = t_submit
        self.deadline_s = deadline_s  # 0 = none
        # Request-scoped trace context (obs/reqtrace.py): the trace id
        # minted (or client-supplied) at submission, the swap counter
        # snapshot for swap-drain attribution, the number of ring
        # dispatches this request rode, and the responded latch (one
        # request_respond span per request, whatever path ends it).
        self.trace_id = ""
        self.swaps_at_submit = 0
        self.swap_drains = 0
        self.rides = 0
        self.responded = False

    @property
    def shape(self) -> tuple:
        return tuple(self.cond["x"].shape[:2])

    @property
    def is_traj(self) -> bool:
        return False


class _TrajRequest(_Request):
    """A trajectory request: N target poses, one frame bank, one slot."""

    __slots__ = ("poses_R", "poses_t", "k_cap")

    def __init__(self, ticket: TrajectoryTicket, cond, key, program_key,
                 t_submit, deadline_s, poses_R: np.ndarray,
                 poses_t: np.ndarray, k_cap: int):
        super().__init__(ticket, cond, key, program_key, t_submit,
                         deadline_s)
        self.poses_R = poses_R  # (N, 3, 3)
        self.poses_t = poses_t  # (N, 3)
        self.k_cap = k_cap

    @property
    def is_traj(self) -> bool:
        return True

    @property
    def num_frames(self) -> int:
        return int(self.poses_R.shape[0])


class _Slot:
    """One active request's ring state (step scheduler).

    Carries exactly what the tentpole contract names: the evolving latent
    `z` (host numpy between re-bucketings, device-resident on the carry
    fast path), the ladder position `t` (steps_remaining = t + 1), the
    conditioning (on the request), the per-row PRNG carry `keys`, and the
    model_version the row was admitted under (pinned: swaps drain the
    ring, so a slot never changes weights mid-flight)."""

    __slots__ = ("req", "bank", "w", "z", "keys", "first", "t", "version",
                 "t_admit", "device_s", "compile_s", "steps_done",
                 "bucket0", "batch0", "fbank", "frame_index", "frame_t0",
                 "strikes", "cc", "cc_bank")

    def __init__(self, req: _Request, bank, version: str, t_admit: float,
                 fbank: Optional[FrameBank] = None):
        self.req = req
        self.bank = bank
        self.w = float(req.program_key[3])
        self.z: Optional[np.ndarray] = None  # drawn on device at step 1
        self.keys = np.asarray(req.key, np.uint32)
        self.first = True
        self.t = bank.n - 1
        self.version = version
        self.t_admit = t_admit
        self.device_s = 0.0
        self.compile_s = 0.0
        self.steps_done = 0
        self.bucket0 = 0
        self.batch0 = 0
        # Trajectory state: the device-resident frame bank (None for
        # single-shot rows) and the index of the frame being denoised.
        self.fbank = fbank
        self.frame_index = 0
        self.frame_t0 = t_admit
        # Consecutive non-finite steps (the device-side anomaly mask);
        # at serve.anomaly_strikes the slot is quarantined.
        self.strikes = 0
        # Conditioning cache (serve.cond_cache): the admission-time
        # encode results, device-resident for the slot's lifetime and
        # pinned — like the weights — to the version the row was
        # admitted under (swaps drain the ring, so neither can change
        # mid-flight). `cc` is (pose_c tuple, feats_c) at B=1;
        # `cc_bank` is the per-bank-entry encode for trajectory rows
        # (re-encoded at each frame boundary against the next target
        # pose), None for single-shot rows.
        self.cc = None
        self.cc_bank = None

    @property
    def shape(self) -> tuple:
        return self.req.shape

    @property
    def is_traj(self) -> bool:
        return self.fbank is not None

    def target_pose(self) -> tuple:
        """(R2, t2) of the frame this slot is currently denoising."""
        if self.is_traj:
            return (self.req.poses_R[self.frame_index],
                    self.req.poses_t[self.frame_index])
        return self.req.cond["R2"], self.req.cond["t2"]


class SamplerProgramCache:
    """LRU of compiled request-sampler programs.

    Keyed by (bucket, H, W, steps, guidance, sampler, cfg_rescale,
    ddim_eta, objective, schedule, precision, fused_step) — see
    `SamplingService._cache_key`:
    everything that changes the XLA program a served batch runs.
    `builds` counts cache misses
    (each one is a retrace + compile); `jit_entries()` sums the live
    jitted functions' compiled-executable counts — the counter the
    zero-recompile-after-warmup assertion reads (tools/serve_bench.py,
    tests/test_serve.py)."""

    def __init__(self, factory: Callable[..., Callable], capacity: int,
                 on_build: Optional[Callable[[tuple, float], None]] = None):
        self._factory = factory
        self._capacity = max(1, capacity)
        self._entries: "collections.OrderedDict[tuple, dict]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.builds = 0
        self.hits = 0
        # Build observer (the service's compile-ledger hook): called with
        # (key, trace wall seconds) for each factory build this cache
        # KEPT — raced duplicate builds are dropped unrecorded, matching
        # the `builds` counter the zero-recompile asserts read.
        self._on_build = on_build

    def get(self, key: tuple, *factory_args) -> dict:
        """Entry dict {fn, warm} for `key`, building (and evicting) as
        needed. `warm` flips True after the entry's first dispatch — the
        span-labeling bit (first call = compile span)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
        t0 = time.perf_counter()
        fn = self._factory(*factory_args)
        build_s = time.perf_counter() - t0
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:  # raced another builder
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            entry = {"fn": fn, "warm": False}
            self._entries[key] = entry
            self.builds += 1
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
        if self._on_build is not None:
            try:
                self._on_build(key, build_s)
            except Exception:
                pass  # ledger bookkeeping must never fail a dispatch
        return entry

    def jit_entries(self) -> int:
        with self._lock:
            fns = [e["fn"] for e in self._entries.values()]
        total = 0
        for fn in fns:
            size = getattr(fn, "_cache_size", None)
            total += int(size()) if callable(size) else 1
        return total

    def counters(self) -> dict:
        with self._lock:
            n = len(self._entries)
            builds, hits = self.builds, self.hits
        return {"programs_built": builds, "cache_hits": hits,
                "programs_live": n, "jit_cache_entries": self.jit_entries()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class SamplingService:
    """Micro-batching front-end over `make_request_sampler`.

    submit() is thread-safe and non-blocking (reject-on-full); a single
    worker thread batches, dispatches, and resolves tickets. One service
    instance serves ONE model + checkpoint; per-request knobs (seed,
    sample_steps, guidance_weight, deadline) ride on the request, and
    requests are only coalesced with others running the same program.
    """

    def __init__(self, model, params, diffusion: DiffusionConfig,
                 serve: Optional[ServeConfig] = None, *,
                 mesh=None, results_folder: Optional[str] = None,
                 start: bool = True, tracer=None, flight=None,
                 profiler=None, model_version: str = ""):
        self.model = model
        self.diffusion = diffusion
        self.serve = serve or ServeConfig()
        self.mesh = mesh
        self._results_folder = results_folder or self.serve.results_folder
        # Flight recorder (obs/flight.py): always on. `nvs3d serve`
        # passes RunTelemetry's (whose bus tap already sees every span);
        # embedded/test use gets its own ring fed by _append_event and
        # the self-constructed tracer below.
        self.flight = (flight if flight is not None
                       else obs.FlightRecorder(self._results_folder))
        # Serving precision (sample/precision.py): how _stage_params
        # representations weights on device (f32 as-published / bf16
        # cast / weight-only int8 + in-jit dequant), folded into every
        # program-cache key. One service serves ONE precision — mixing
        # precisions means mixing model qualities mid-stream.
        self.precision = precision_lib.validate_precision(
            self.serve.precision)
        self._param_transform = precision_lib.make_resolver(self.precision)
        self.stats = ServiceStats()
        # Unified telemetry (obs/): the serving pipeline's spans
        # (queue_wait → batch_form → compile/device → respond) flow into
        # the shared registry's per-phase histogram — the same
        # /metrics surface the trainer feeds. `nvs3d serve` passes its
        # own tracer so trace.json lands next to the request PNGs;
        # embedded/test use gets a default one.
        self.tracer = tracer if tracer is not None else obs.Tracer(
            registry=obs.get_registry(), on_complete=self._flight_span)
        self._requests_total = obs.get_registry().counter(
            "nvs3d_requests_total", "requests served (resolved tickets)")
        self._rejects_total = obs.get_registry().counter(
            "nvs3d_rejects_total",
            "requests refused (backpressure, deadline)")
        self._model_swaps_total = obs.get_registry().counter(
            "nvs3d_model_swaps_total",
            "zero-downtime param swaps applied by the sampling service")
        self._model_version_gauge = obs.get_registry().gauge(
            "nvs3d_model_version",
            "live model version (label) and its training step (value)")
        # Trajectory serving gauges (docs/DESIGN.md "Trajectory serving
        # & stochastic conditioning").
        self._frames_total = obs.get_registry().counter(
            "nvs3d_frames_total",
            "trajectory frames denoised and streamed to clients")
        self._frames_per_sec = obs.get_registry().gauge(
            "nvs3d_frames_per_sec",
            "trajectory frame delivery rate since the first frame")
        self._traj_active = obs.get_registry().gauge(
            "nvs3d_trajectories_active",
            "trajectory requests currently holding a ring slot")
        self._frames_count = 0
        self._frames_t0: Optional[float] = None
        self._traj_in_ring = 0
        # Conditioning-cache telemetry (docs/DESIGN.md "Conditioning
        # cache & fused serving attention"): a hit is one ring row served
        # a step from cached activations, a miss is one encode-program
        # run (admission, uncond fill, or trajectory frame boundary).
        self._cond_hits_total = obs.get_registry().counter(
            "nvs3d_cond_cache_hits_total",
            "ring row-steps served from cached conditioning activations")
        self._cond_misses_total = obs.get_registry().counter(
            "nvs3d_cond_cache_misses_total",
            "conditioning encode runs (admissions, uncond fills, "
            "trajectory frame boundaries)")
        self._cond_resident_gauge = obs.get_registry().gauge(
            "nvs3d_cond_cache_resident_bytes",
            "device bytes held by cached conditioning activations "
            "(ring slots + the shared uncond cache)")
        # Survivability surfaces (docs/DESIGN.md "Serving
        # survivability"): anomaly quarantine, drain state, supervised
        # worker restarts, and the brownout ladder.
        self._anomalies_total = obs.get_registry().counter(
            "nvs3d_sample_anomalies_total",
            "ring rows quarantined for non-finite latents")
        self._worker_restarts_total = obs.get_registry().counter(
            "nvs3d_worker_restarts_total",
            "supervised restarts of the sampling worker thread")
        self._serve_state_gauge = obs.get_registry().gauge(
            "nvs3d_serve_state",
            "service lifecycle: 0=serving, 1=draining, 2=stopped")
        self._brownout_gauge = obs.get_registry().gauge(
            "nvs3d_brownout_level",
            "brownout ladder level: 0=serving, 1=degraded, 2=shedding")
        self._serve_state_gauge.set(0.0)
        self.anomalies = 0
        self.worker_restarts = 0
        self.dispatches = 0
        # Continuous profiler (obs/profiler.py, obs.profile.serve_*):
        # windows counted in dispatches, advanced on the worker thread
        # at each dispatch site. `nvs3d serve` passes one wired to its
        # RunTelemetry bus; embedded/test use defaults to None (off).
        self._profiler = profiler
        # Compile ledger (obs/compiles.py): every sampler-program build
        # lands in compiles.jsonl with a field-named fingerprint, so a
        # recompile names the knob that changed (bucket, steps, shape…) —
        # what serve_bench's zero-recompile asserts print as the culprit.
        self._compile_ledger = obs.CompileLedger(
            self._results_folder, registry=obs.get_registry())
        # /healthz progress heartbeat: stamped at every dispatch; a probe
        # reads last_dispatch_age_s to tell wedged-but-listening from
        # merely idle (pair it with queue depth).
        self._last_dispatch_t = time.time()
        self._draining = False
        self._drained_ev = threading.Event()
        self._brownout_level = 0
        self._ring_debt = 0
        self._events_lock = threading.Lock()
        # SLO engine (obs/slo.py): scores every finished request
        # against serve.slo.targets; None when no targets are declared.
        slo_cfg = self.serve.slo
        slo_targets = slo_lib.parse_targets(slo_cfg.targets)
        self.slo: Optional[slo_lib.SLOEngine] = None
        if slo_targets:
            self.slo = slo_lib.SLOEngine(
                targets=slo_targets, objective=slo_cfg.objective,
                fast_window_s=slo_cfg.fast_window_s,
                slow_window_s=slo_cfg.slow_window_s,
                fast_burn=slo_cfg.fast_burn,
                slow_burn=slo_cfg.slow_burn,
                registry=obs.get_registry(),
                event_cb=self._slo_event)
        # Live (params, model_version) pair — ONE attribute so readers
        # (the dispatch loop, _log_event) always see a consistent pair;
        # swaps stage a replacement and the worker flips it between
        # dispatches (_apply_pending_swap).
        staged, owned = self._stage_params(params)
        self._live = (staged, model_version)
        self._owned_ids = owned
        self._pending_swap: Optional[dict] = None
        self._swaps = 0
        if model_version:
            self._model_version_gauge.set(0.0, version=model_version)
        # Bucket ladder: powers of two up to max_batch; with a mesh, only
        # buckets the 'data' axis divides evenly are shard-dispatchable —
        # the others still serve, on the default device.
        self._buckets = []
        b = 1
        while b <= self.serve.max_batch:
            self._buckets.append(b)
            b *= 2
        # Trajectory serving (serve.k_max > 0): the stepper runs the
        # bank-enabled program so ring slots may carry a device-resident
        # frame bank. 0 keeps the EXACT bank-free program — trajectory
        # support is zero-cost (and bit-identical) when unused.
        self._k_max = int(self.serve.k_max)
        if self._k_max < 0:
            raise ValueError(f"serve.k_max={self.serve.k_max} must be >= 0")
        if self._k_max > 0 and self.serve.scheduler != "step":
            raise ValueError(
                f"serve.k_max={self._k_max} requires serve.scheduler="
                "'step' — trajectory frames re-enter the stepper ring "
                "between denoise steps (config.validate names the same "
                "constraint)")
        # Conditioning cache (serve.cond_cache; docs/DESIGN.md
        # "Conditioning cache & fused serving attention"): compute the
        # request's cond-branch activations ONCE at admission and feed
        # the step program device arguments instead of re-running rays →
        # posenc → per-level convs every denoise step.
        self._cond_cache = bool(self.serve.cond_cache)
        if self._cond_cache and self.serve.scheduler != "step":
            raise ValueError(
                "serve.cond_cache=True requires serve.scheduler='step' — "
                "the cache lives on stepper ring slots (config.validate "
                "names the same constraint)")
        if self.serve.scheduler == "step":
            # Stepper programs depend on bucket/shape ONLY (t, steps and
            # guidance ride as device args); the host-side coefficient
            # bank supplies per-row schedule values per dispatch.
            self._programs = SamplerProgramCache(
                self._build_step_program, self.serve.program_cache_entries,
                on_build=self._record_build)
            self._banks = ScheduleBank(self.diffusion)
            # Per-bucket all-False `first` vectors, staged once: the
            # carry fast path reuses them instead of re-uploading.
            self._false_cache: Dict[int, object] = {}
            # Zero frame banks for single-shot rows riding a bank-
            # enabled ring, staged once per (H, W) shape.
            self._zero_bank_cache: Dict[tuple, tuple] = {}
            # The in-jit frame commit program (one jitted callable;
            # XLA caches one executable per (k_max, H, W) shape).
            self._commit_fn = make_bank_commit_fn() if self._k_max else None
            # Admission-time conditioning encode (one jitted callable;
            # XLA caches one executable per (B, H, W) encode shape —
            # B=1 requests/uncond, B=k_max trajectory banks). The
            # per-(H, W) uncond cache is GLOBAL (the CFG uncond half is
            # pose- and image-independent — only conv biases + learned
            # embeddings survive the mask) and is invalidated on every
            # hot swap; per-request caches die with their ring slot.
            self._encode_fn = (make_cond_encode_fn(
                self.model, param_transform=self._param_transform)
                if self._cond_cache else None)
            self._uncond_cache: Dict[tuple, tuple] = {}
            self._zero_cc_cache: Dict[tuple, tuple] = {}
            self._encode_entries = 0
            self._cc_hits = 0
            self._cc_misses = 0
        else:
            self._programs = SamplerProgramCache(
                self._build_program, self.serve.program_cache_entries,
                on_build=self._record_build)
            self._banks = None
        self._lock = threading.Lock()
        self._queue_cv = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._next_id = 0
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "SamplingService":
        if self._worker is None:
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._run_supervised, daemon=True,
                name="sampling-service")
            self._worker.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop the worker; queued-but-undispatched requests fail with a
        RETRYABLE Rejected('service stopped').

        `timeout` (default serve.stop_timeout_s) bounds the worker join.
        A join that times out means the worker is WEDGED mid-dispatch —
        the service writes a stall-style all-thread-stacks diagnosis
        (stall_serve_stop_<n>.txt, the PR 2 watchdog convention) and
        raises instead of silently leaking a live thread that still owns
        the device."""
        timeout = self.serve.stop_timeout_s if timeout is None else timeout
        self._stop.set()
        with self._queue_cv:
            self._queue_cv.notify_all()
        worker = self._worker
        if worker is not None:
            worker.join(timeout=timeout)
            if worker.is_alive():
                self._dump_stop_stall(worker, timeout)
                raise RuntimeError(
                    f"sampling-service worker still alive after "
                    f"{timeout:.1f}s join (stop()): thread-stack "
                    f"diagnosis written under {self._results_folder!r} "
                    "(stall_serve_stop_*.txt)")
            self._worker = None
        if self._profiler is not None:
            # Close out a window left open mid-capture; the worker is
            # joined, so no dispatch races the stop_trace/parse.
            self._profiler.close()
        self._serve_state_gauge.set(2.0)
        # A swap staged but not yet applied must not leave its waiter
        # hanging: apply it inline (no dispatch can be in flight now).
        self._apply_pending_swap()
        self._fail_queue(lambda: Rejected(
            "service stopped", retryable=True, retry_after_s=1.0))

    def _fail_queue(self, make_error: Callable[[], ServeError]) -> None:
        with self._lock:
            leftovers = list(self._queue)
            self._queue.clear()
        for req in leftovers:
            req.ticket._fail(make_error())
            self._respond_span(req, "failed")

    def _dump_stop_stall(self, worker: threading.Thread,
                         timeout: float) -> None:
        """Wedged-worker diagnosis: every thread's stack to a stall_*
        file (stderr when even that fails — the diagnosis must never be
        the second fault), plus a `stall` event row."""
        from novel_view_synthesis_3d_tpu.utils import watchdog

        self._append_event(
            0, "stall",
            f"stop(): worker {worker.name!r} wedged past the "
            f"{timeout:.1f}s join (serve.stop_timeout_s); diagnosis "
            "stall_serve_stop_*.txt", model_version=self.model_version)
        self.flight.dump("stall", worker=worker.name,
                         timeout_s=timeout, dispatches=self.dispatches)
        body = (f"sampling-service stop(): worker {worker.name!r} still "
                f"alive after join timeout {timeout:.1f}s\n"
                f"time: {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}"
                f"\ndispatches: {self.dispatches}\n\n"
                + watchdog.thread_stacks())
        try:
            os.makedirs(self._results_folder, exist_ok=True)
            n = 0
            while os.path.exists(os.path.join(
                    self._results_folder, f"stall_serve_stop_{n}.txt")):
                n += 1
            path = os.path.join(self._results_folder,
                                f"stall_serve_stop_{n}.txt")
            with open(path, "w") as fh:
                fh.write(body)
            print(f"[serve] wedged-worker diagnosis: {path}",
                  file=sys.stderr, flush=True)
        except OSError:
            print(body, file=sys.stderr, flush=True)

    def begin_drain(self, reason: str = "") -> None:
        """Flip to DRAINING: admissions are rejected with a structured
        retryable reason; queued + in-ring work keeps being served until
        done (the worker then parks itself). Non-blocking — `drain()`
        adds the wait + stop. Idempotent."""
        with self._queue_cv:
            if self._draining or self._stop.is_set():
                return
            self._draining = True
            self._queue_cv.notify_all()
        self._serve_state_gauge.set(1.0)
        self._append_event(
            0, "drain",
            "accepting -> draining"
            + (f" ({reason})" if reason else "")
            + "; new admissions rejected retryably, in-flight work "
            f"finishes within serve.drain_timeout_s="
            f"{self.serve.drain_timeout_s:.0f}s",
            model_version=self.model_version)

    def drain(self, timeout_s: Optional[float] = None,
              reason: str = "") -> bool:
        """Graceful shutdown (the SIGTERM path of `nvs3d serve`):
        reject new admissions retryably, let every queued and in-ring
        request finish, then stop. Returns True when everything in
        flight completed within `timeout_s` (default
        serve.drain_timeout_s); on timeout the leftovers fail with a
        retryable Rejected via stop()."""
        timeout_s = (self.serve.drain_timeout_s if timeout_s is None
                     else float(timeout_s))
        self.begin_drain(reason)
        worker = self._worker
        if worker is None or not worker.is_alive():
            with self._lock:
                drained = not self._queue
        else:
            drained = self._drained_ev.wait(timeout_s)
        self._append_event(
            0, "drain",
            ("draining -> stopped (clean: queue and ring empty)"
             if drained else
             f"draining -> stopped (TIMEOUT after {timeout_s:.1f}s; "
             "leftover requests fail retryably)"),
            model_version=self.model_version)
        if not drained:
            self.flight.dump("drain_timeout", timeout_s=timeout_s,
                             dispatches=self.dispatches)
        self.stop()
        return drained

    def __enter__(self) -> "SamplingService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- params lifecycle (zero-downtime hot reload) -------------------
    @property
    def params(self):
        return self._live[0]

    @property
    def model_version(self) -> str:
        return self._live[1]

    def _stage_params(self, params):
        """Stage a param tree at the serving precision and place it
        where dispatch needs it (mesh-replicated or default device).

        Precision staging happens ON HOST first (sample/precision.py):
        bf16 casts / int8 quantization produce a fresh host tree, so the
        device upload ships the small representation and the weights
        REST on device at serving precision. float32 stages the caller's
        tree unchanged (bit-exact legacy path).

        Returns (staged_tree, owned_leaf_ids): only buffers UPLOADED
        HERE from host (numpy) leaves count as service-owned — the ones
        a later swap may free. A device-array input may come back from
        device_put as a NEW wrapper over the SAME buffer, so deleting by
        object identity would kill the caller's tree; those leaves are
        left to garbage collection instead. (At bf16/int8 every staged
        leaf is a derived host copy, so the service owns them all.)"""
        params = precision_lib.stage_params(params, self.precision)
        if self.mesh is not None:
            staged = mesh_lib.replicate(self.mesh, params)
        else:
            staged = jax.device_put(params, jax.devices()[0])
        owned = set()
        for inp, out in zip(jax.tree.leaves(params),
                            jax.tree.leaves(staged)):
            if not isinstance(inp, jax.Array) and out is not inp \
                    and hasattr(out, "delete"):
                owned.add(id(out))
        return staged, owned

    def _free_tree(self, tree, owned_ids, keep_ids=frozenset()) -> None:
        for leaf in jax.tree.leaves(tree):
            if (id(leaf) in owned_ids and id(leaf) not in keep_ids
                    and hasattr(leaf, "delete")):
                try:
                    leaf.delete()
                except Exception:
                    pass  # already deleted / non-owning view

    def swap_params(self, params, version: str, *,
                    step: Optional[int] = None,
                    timeout: Optional[float] = None) -> threading.Event:
        """Stage `params` alongside the live set and request a swap.

        The upload happens HERE (and is waited on), so the flip itself —
        applied by the worker between dispatches — is a reference
        assignment: no request ever blocks on a host→device transfer of
        the new weights. Requests in flight finish on the version they
        started on; every later dispatch serves `version`. Warm sampler
        programs survive (the cache key has no params in it).

        Returns the 'applied' event; `timeout` (seconds) waits for it —
        with an idle or stopped worker the swap is applied inline.
        """
        staged, owned = self._stage_params(params)
        jax.block_until_ready(staged)
        applied = threading.Event()
        pend = {"params": staged, "owned": owned, "version": version,
                "step": step, "applied": applied}
        with self._queue_cv:
            prev, self._pending_swap = self._pending_swap, pend
            self._queue_cv.notify_all()
        if prev is not None:
            # Superseded before it ever served: free its staging copy and
            # release anyone waiting on it (last writer wins).
            self._free_tree(prev["params"], prev["owned"],
                            keep_ids={id(l) for l in
                                      jax.tree.leaves(staged)})
            prev["applied"].set()
        if self._worker is None or not self._worker.is_alive():
            self._apply_pending_swap()
        if timeout is not None:
            applied.wait(timeout)
        return applied

    def _apply_pending_swap(self) -> None:
        """Flip to a staged param set; runs on the worker thread between
        dispatches (or inline when no worker is running), so no dispatch
        holds the old tree when its buffers are freed."""
        with self._queue_cv:
            pend, self._pending_swap = self._pending_swap, None
        if pend is None:
            return
        old, old_version = self._live
        with self.tracer.span("model_swap", version=pend["version"],
                              prev=old_version or "<initial>"):
            self._live = (pend["params"], pend["version"])
            self._free_tree(
                old, self._owned_ids,
                keep_ids={id(l) for l in jax.tree.leaves(pend["params"])})
            self._owned_ids = pend["owned"]
            # Conditioning-cache invalidation: the shared uncond halves
            # were encoded through the OLD weights. Per-request caches
            # need no action — the drain-on-swap contract means no ring
            # slot is alive here, so every in-flight row stayed pinned
            # to the activations (and weights) of its start version.
            if self._cond_cache:
                self._uncond_cache.clear()
        self._swaps += 1
        self._model_swaps_total.inc()
        self._model_version_gauge.set(
            float(pend["step"]) if pend["step"] is not None
            else float(self._swaps), version=pend["version"])
        self._append_event(
            pend["step"] or 0, "model_swap",
            f"{old_version or '<initial>'} -> {pend['version']} "
            f"(swap {self._swaps}, {len(self._programs)} warm programs "
            "kept)", model_version=pend["version"])
        pend["applied"].set()

    # -- submission ----------------------------------------------------
    def _step_debt_locked(self) -> int:
        """Denoise steps still owed: the ring's remaining steps (updated
        by the worker each dispatch) plus everything queued. One of the
        two brownout pressure signals — queue DEPTH is blind to a queue
        of three 256-step orbits. Caller holds self._lock."""
        debt = self._ring_debt
        for r in self._queue:
            steps = int(r.program_key[2])
            debt += steps * (r.num_frames if r.is_traj else 1)
        return debt

    def _brownout_check(self, request_id: int) -> int:
        """Evaluate the brownout ladder at admission time; returns the
        level (0 serving / 1 degraded / 2 shedding) and logs + gauges
        the transition when it moved."""
        bo = self.serve.brownout
        if not (bo.queue_soft or bo.queue_hard or bo.debt_soft
                or bo.debt_hard):
            return 0
        with self._lock:
            q = len(self._queue)
            debt = (self._step_debt_locked()
                    if (bo.debt_soft or bo.debt_hard) else 0)
            level = 0
            if ((bo.queue_soft and q >= bo.queue_soft)
                    or (bo.debt_soft and debt >= bo.debt_soft)):
                level = 1
            if ((bo.queue_hard and q >= bo.queue_hard)
                    or (bo.debt_hard and debt >= bo.debt_hard)):
                level = 2
            prev, self._brownout_level = self._brownout_level, level
        if level != prev:
            self._brownout_gauge.set(float(level))
            names = {0: "serving", 1: "degraded", 2: "shedding"}
            self._append_event(
                request_id, "brownout",
                f"level {prev} ({names[prev]}) -> {level} "
                f"({names[level]}): queued={q}, step_debt={debt}",
                model_version=self.model_version)
        return level

    def _reject_drain(self, ticket) -> None:
        self._log_event(ticket.request_id, "drain",
                        "admission rejected: service draining "
                        "(retryable)")
        raise Rejected(
            "service draining for restart; retry against a peer or "
            "after the restart", retryable=True,
            retry_after_s=self.serve.drain_timeout_s)

    def submit(self, cond: Dict[str, np.ndarray], *, seed: int = 0,
               sample_steps: Optional[int] = None,
               guidance_weight: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> Ticket:
        """Enqueue one request; returns immediately with a Ticket.

        `cond` holds UNBATCHED conditioning: x (H, W, 3), R1/R2 (3, 3),
        t1/t2 (3,), K (3, 3) — the service stacks requests into the
        batch axis. Raises Rejected when the queue is full (the events
        log records why), or on malformed conditioning. `trace_id`
        names the request's trace (obs/reqtrace.py; sanitized);
        default: minted from the request id.
        """
        missing = [k for k in COND_KEYS if k not in cond]
        if missing:
            raise Rejected(f"request missing conditioning keys {missing}")
        x = np.asarray(cond["x"])
        if x.ndim != 3:
            raise Rejected(
                f"cond['x'] must be unbatched (H, W, 3); got {x.shape}")
        steps = sample_steps or self.serve.sample_steps or \
            self.diffusion.sample_timesteps
        if not 1 <= int(steps) <= self.diffusion.timesteps:
            raise Rejected(
                f"sample_steps={steps} outside [1, diffusion.timesteps="
                f"{self.diffusion.timesteps}]")
        w = (self.diffusion.guidance_weight
             if guidance_weight is None else float(guidance_weight))
        if deadline_ms is None:
            deadline_ms = self.serve.default_deadline_ms
        program_key = (int(x.shape[0]), int(x.shape[1]), int(steps), w)
        ticket = Ticket(self._claim_id())
        level = self._brownout_check(ticket.request_id)
        if level >= 2:
            self._log_event(
                ticket.request_id, "reject",
                "brownout shed (level 2): load above "
                "serve.brownout.{queue,debt}_hard (retryable)")
            raise Rejected(
                "service shedding load (brownout level 2); retry with "
                "backoff", retryable=True,
                retry_after_s=self.serve.brownout.retry_after_s)
        req = _Request(
            ticket,
            {k: np.asarray(cond[k]) for k in COND_KEYS},
            np.asarray(jax.random.PRNGKey(seed)),
            program_key, time.monotonic(),
            float(deadline_ms) / 1000.0 if deadline_ms else 0.0)
        req.trace_id = reqtrace.mint(ticket.request_id, trace_id)
        req.swaps_at_submit = self._swaps
        with self._queue_cv:
            if self._stop.is_set():
                raise Rejected("service stopped")
            if self._draining:
                self._reject_drain(ticket)
            if len(self._queue) >= self.serve.queue_depth:
                self._log_event(
                    ticket.request_id, "reject",
                    f"queue full (depth {self.serve.queue_depth})")
                raise Rejected(
                    f"queue full (serve.queue_depth="
                    f"{self.serve.queue_depth}); retry with backoff",
                    retryable=True, retry_after_s=0.05)
            self._queue.append(req)
            self._queue_cv.notify_all()
        self._submit_span(req, "single", int(steps), level)
        return ticket

    def _submit_span(self, req: _Request, req_kind: str, steps: int,
                     brownout_level: int,
                     frames: Optional[int] = None) -> None:
        """The trace root (obs/reqtrace.py contract): a zero-duration
        request_submit marker carrying the span_id every request-scoped
        child points back at. Emitted AFTER the enqueue commits —
        rejected submissions have no trace."""
        attrs = dict(trace_id=req.trace_id,
                     span_id=reqtrace.root_span_id(req.trace_id),
                     request_id=req.ticket.request_id,
                     req_kind=req_kind, steps=steps,
                     brownout=brownout_level)
        if frames is not None:
            attrs["frames"] = int(frames)
        self.tracer.add_span("request_submit", 0.0, **attrs)

    def submit_trajectory(self, cond: Dict[str, np.ndarray], *,
                          poses, seed: int = 0,
                          sample_steps: Optional[int] = None,
                          guidance_weight: Optional[float] = None,
                          deadline_ms: Optional[float] = None,
                          k_max: Optional[int] = None,
                          trace_id: Optional[str] = None
                          ) -> TrajectoryTicket:
        """Enqueue one N-frame trajectory; returns a streaming ticket.

        `cond` holds the UNBATCHED source view: x (H, W, 3), R1 (3, 3),
        t1 (3,), K (3, 3). `poses` is the orbit — an (N, 4, 4) cam→world
        pose stack or a dict {"R2": (N, 3, 3), "t2": (N, 3)}. Each frame
        runs `sample_steps` denoise steps; every step conditions on a
        bank view per diffusion.stochastic_cond, and each finished frame
        is committed into the bank in-jit before the next re-enters the
        ring — the whole orbit stays device-resident. `k_max` bounds
        this request's sliding conditioning window (default, and upper
        bound: serve.k_max). `deadline_ms` covers the WHOLE orbit and is
        re-checked at each frame's admission; a mid-orbit expiry
        delivers the completed frames inside a TrajectoryExpired."""
        if self.serve.scheduler != "step" or self._k_max < 1:
            raise Rejected(
                "trajectory serving is disabled: it needs serve."
                "scheduler='step' and serve.k_max > 0 (got scheduler="
                f"{self.serve.scheduler!r}, k_max={self.serve.k_max}) — "
                "the frame bank is sized at service construction")
        missing = [k for k in TRAJ_COND_KEYS if k not in cond]
        if missing:
            raise Rejected(
                f"trajectory request missing conditioning keys {missing}")
        x = np.asarray(cond["x"])
        if x.ndim != 3:
            raise Rejected(
                f"cond['x'] must be unbatched (H, W, 3); got {x.shape}")
        poses_R, poses_t = _normalize_poses(poses)
        n_frames = poses_R.shape[0]
        if not 1 <= n_frames <= self.serve.max_frames:
            raise Rejected(
                f"trajectory has {n_frames} poses; serve.max_frames="
                f"{self.serve.max_frames} bounds a request (split the "
                "orbit, or raise serve.max_frames)")
        cap = self._k_max if k_max is None else int(k_max)
        if not 1 <= cap <= self._k_max:
            raise Rejected(
                f"k_max={k_max} outside [1, serve.k_max={self._k_max}] — "
                "the service's bank arrays are sized once; per-request "
                "windows can only shrink")
        ticket_id = self._claim_id()
        level = self._brownout_check(ticket_id)
        if level >= 2:
            self._log_event(
                ticket_id, "reject",
                "brownout shed (level 2): load above "
                "serve.brownout.{queue,debt}_hard (retryable)")
            raise Rejected(
                "service shedding load (brownout level 2); retry with "
                "backoff", retryable=True,
                retry_after_s=self.serve.brownout.retry_after_s)
        if level == 1:
            # Degraded admission: cheaper orbits instead of refusal —
            # a narrower conditioning window and/or a truncated pose
            # list, applied HERE so an in-flight orbit never changes
            # shape mid-ring.
            bo = self.serve.brownout
            if bo.k_cap and cap > bo.k_cap:
                cap = bo.k_cap
            if bo.max_frames_cap and n_frames > bo.max_frames_cap:
                poses_R = poses_R[:bo.max_frames_cap]
                poses_t = poses_t[:bo.max_frames_cap]
                n_frames = bo.max_frames_cap
                self._log_event(
                    ticket_id, "brownout",
                    f"degraded admission (level 1): orbit capped to "
                    f"{n_frames} frames, bank window {cap}")
        steps = sample_steps or self.serve.sample_steps or \
            self.diffusion.sample_timesteps
        if not 1 <= int(steps) <= self.diffusion.timesteps:
            raise Rejected(
                f"sample_steps={steps} outside [1, diffusion.timesteps="
                f"{self.diffusion.timesteps}]")
        w = (self.diffusion.guidance_weight
             if guidance_weight is None else float(guidance_weight))
        if deadline_ms is None:
            deadline_ms = self.serve.default_deadline_ms
        program_key = (int(x.shape[0]), int(x.shape[1]), int(steps), w)
        ticket = TrajectoryTicket(ticket_id, n_frames)
        full_cond = {k: np.asarray(cond[k]) for k in TRAJ_COND_KEYS}
        # R2/t2 ride as zeros so trajectory rows stack uniformly with
        # single-shot rows; the step program takes the CURRENT frame's
        # pose from the per-step device arguments instead.
        full_cond["R2"] = np.zeros((3, 3), np.float32)
        full_cond["t2"] = np.zeros((3,), np.float32)
        req = _TrajRequest(
            ticket, full_cond, np.asarray(jax.random.PRNGKey(seed)),
            program_key, time.monotonic(),
            float(deadline_ms) / 1000.0 if deadline_ms else 0.0,
            poses_R, poses_t, cap)
        req.trace_id = reqtrace.mint(ticket_id, trace_id)
        req.swaps_at_submit = self._swaps
        with self._queue_cv:
            if self._stop.is_set():
                raise Rejected("service stopped")
            if self._draining:
                self._reject_drain(ticket)
            if len(self._queue) >= self.serve.queue_depth:
                self._log_event(
                    ticket.request_id, "reject",
                    f"queue full (depth {self.serve.queue_depth})")
                raise Rejected(
                    f"queue full (serve.queue_depth="
                    f"{self.serve.queue_depth}); retry with backoff",
                    retryable=True, retry_after_s=0.05)
            self._queue.append(req)
            self._queue_cv.notify_all()
        self._submit_span(req, "trajectory", int(steps), level,
                          frames=n_frames)
        return ticket

    def _claim_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    # -- observability -------------------------------------------------
    def compile_counters(self) -> dict:
        counters = self._programs.counters()
        commit_fn = getattr(self, "_commit_fn", None)
        if commit_fn is not None:
            # The in-jit bank-commit program compiles once per
            # (k_max, H, W) shape; its executables count here so the
            # zero-recompile asserts cover the trajectory path too.
            size = getattr(commit_fn, "_cache_size", None)
            counters["commit_jit_entries"] = (
                int(size()) if callable(size) else 0)
        encode_fn = getattr(self, "_encode_fn", None)
        if encode_fn is not None:
            # The admission-time cond-encode program compiles once per
            # (B, H, W) encode shape; counting its executables here puts
            # it under the same zero-recompile asserts as the step and
            # commit programs (mixed cached/uncached warm traffic must
            # compile nothing).
            size = getattr(encode_fn, "_cache_size", None)
            counters["encode_jit_entries"] = (
                int(size()) if callable(size) else 0)
        return counters

    def summary(self) -> dict:
        try:
            fused = resolve_fused_step(self.diffusion.fused_step)
        except ValueError:
            fused = self.diffusion.fused_step
        out = dict(self.stats.summary(), **self.compile_counters(),
                   model_version=self.model_version,
                   model_swaps=self._swaps,
                   precision=self.precision, fused_step=fused,
                   anomalies=self.anomalies,
                   worker_restarts=self.worker_restarts,
                   brownout_level=self._brownout_level,
                   flight_dumps=len(self.flight.dumps))
        if self._banks is not None:
            out["schedule_bank"] = self._banks.counters()
        if self._cond_cache:
            out["cond_cache"] = self._cond_cache_stats()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out

    def _cond_cache_stats(self) -> dict:
        hits, misses = self._cc_hits, self._cc_misses
        total = hits + misses
        return {
            "enabled": True,
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
            "uncond_entries": len(self._uncond_cache),
            "resident_bytes": int(
                self._cond_resident_gauge.value() or 0),
        }

    def _log_event(self, request_id: int, kind: str, detail: str) -> None:
        """Event-log append via the obs bus, schema-compatible with the
        trainer's MetricsLogger.log_event (request id in the step
        column). Rare by construction (rejections and expiries)."""
        self._rejects_total.inc(kind=kind)
        self._append_event(request_id, kind, detail,
                           model_version=self.model_version)

    def _append_event(self, step: int, kind: str, detail: str, *,
                      model_version: str = "") -> None:
        # Events also land in the flight ring, so a dump's tail holds
        # the event that triggered it (anomaly/restart/drain/stall).
        self.flight.note("event", step=step, event=kind, detail=detail,
                         model_version=model_version)
        try:
            with self._events_lock:
                obs.append_event(self._results_folder, step, kind,
                                 detail, model_version=model_version)
        except OSError:
            pass  # the event log must never be the serving fault

    def _flight_span(self, rec: dict) -> None:
        """on_complete sink for the self-constructed tracer: flatten a
        span record into the flight ring (the bus.span_record shape,
        minus the JSONL file). `nvs3d serve` doesn't use this — its
        tracer feeds RunTelemetry's bus, whose tap IS the recorder."""
        self.flight.record(
            {"kind": "span", "name": rec["name"],
             "dur_s": round(rec["dur"], 6),
             **{k: v for k, v in rec.get("attrs", {}).items()
                if isinstance(v, (int, float, str, bool))}})

    def _slo_event(self, kind: str, detail: str) -> None:
        self._append_event(0, kind, detail,
                           model_version=self.model_version)

    def _respond_span(self, req: _Request, outcome: str, *,
                      steps_done: int = 0,
                      frames_done: Optional[int] = None) -> None:
        """Close a request's trace: ONE request_respond span covering
        submit→now, whatever path ended it (resolution, anomaly,
        expiry, worker failure), plus the SLO sample. Idempotent per
        request — the first closer wins (a quarantined slot must not be
        re-closed by a later ring unwind)."""
        if req.responded or not req.trace_id:
            req.responded = True
            return
        req.responded = True
        latency = max(0.0, time.monotonic() - req.t_submit)
        attrs = dict(
            trace_id=req.trace_id,
            parent_id=reqtrace.root_span_id(req.trace_id),
            request_id=req.ticket.request_id,
            outcome=outcome,
            latency_s=round(latency, 6),
            steps=int(req.program_key[2]),
            steps_done=int(steps_done),
            dispatches=req.rides,
            swap_drains=req.swap_drains,
            model_version=self.model_version)
        if frames_done is not None:
            attrs["frames_done"] = int(frames_done)
        self.tracer.add_span("request_respond", latency, **attrs)
        if self.slo is not None:
            self.slo.record(int(req.program_key[2]), latency,
                            ok=(outcome == "ok"))

    # -- batching worker -----------------------------------------------
    def _run_supervised(self) -> None:
        """Worker supervisor (the serving analogue of train/supervisor):
        a worker death — anything escaping `_run`'s per-dispatch guards
        — is restarted with bounded exponential backoff instead of
        stranding every ticket. Undispatched requests STAY QUEUED across
        the restart (the new worker admits them); in-flight ring rows
        were already failed retryably by `_run_stepper`'s unwind. Past
        serve.max_worker_restarts the service gives up loudly: the
        queue fails retryably and the service stops."""
        while True:
            try:
                self._run()
                return  # clean exit: stop() or drain completion
            except BaseException as exc:
                if self._stop.is_set():
                    return
                self.worker_restarts += 1
                self._worker_restarts_total.inc()
                n = self.worker_restarts
                budget = self.serve.max_worker_restarts
                if n > budget:
                    self._append_event(
                        -1, "worker_restart",
                        f"worker died ({exc!r}); restart budget "
                        f"serve.max_worker_restarts={budget} exhausted "
                        "— service stopping, queued requests fail "
                        "retryably", model_version=self.model_version)
                    print(f"[serve] worker died ({exc!r}); restart "
                          f"budget {budget} exhausted — stopping",
                          file=sys.stderr, flush=True)
                    self.flight.dump("worker_restart", restart=n,
                                     budget=budget, exhausted=True)
                    self._stop.set()
                    self._fail_queue(lambda: Rejected(
                        "service worker dead (restart budget "
                        "exhausted); retry against a peer",
                        retryable=True, retry_after_s=1.0))
                    return
                delay = min(30.0, self.serve.worker_backoff_s
                            * (2 ** (n - 1)))
                self._append_event(
                    -1, "worker_restart",
                    f"worker died ({exc!r}); supervised restart "
                    f"{n}/{budget} in {delay:.2f}s — undispatched "
                    "requests stay queued",
                    model_version=self.model_version)
                self.flight.dump("worker_restart", restart=n,
                                 budget=budget, exhausted=False)
                if delay > 0 and self._stop.wait(delay):
                    return

    def _run(self) -> None:
        if self.serve.scheduler == "step":
            self._run_stepper()
        else:
            self._run_request()

    def _run_request(self) -> None:
        """Whole-request dispatch (PR 3 semantics; serve.scheduler=
        'request'): one lax.scan program per coalesced group."""
        while not self._stop.is_set():
            faultinject.maybe_serve_worker_die(self.dispatches)
            with self._lock:
                if self._draining and not self._queue:
                    break  # drained: nothing queued, nothing in flight
            # Swaps apply HERE — between dispatches, never under one, so
            # freeing the old tree can't race an in-flight program.
            self._apply_pending_swap()
            group = self._collect_group()
            if not group:
                continue
            try:
                self._dispatch(group)
            except BaseException as exc:  # resolve, don't kill the worker
                for req in group:
                    req.ticket._fail(
                        ServeError(f"dispatch failed: {exc!r}"))
                    self._respond_span(req, "failed")
        self._drained_ev.set()

    # -- step-level continuous batching (serve.scheduler='step') --------
    def _run_stepper(self) -> None:
        """Persistent stepper: a ring of active slots advances one
        denoise step per dispatch; arrivals join between steps, finished
        rows exit immediately. `carry` keeps the ring's (z, keys, cond)
        device-resident while the composition is stable — the common
        no-join/no-exit iteration moves nothing through the host."""
        ring: List[_Slot] = []
        carry: Optional[dict] = None
        try:
            while not self._stop.is_set():
                # Worker-death drill: raises OUTSIDE the per-dispatch
                # guard below, so the exception unwinds the thread and
                # exercises the supervisor restart path.
                faultinject.maybe_serve_worker_die(self.dispatches)
                if not ring:
                    self._ring_debt = 0
                    with self._lock:
                        if self._draining and not self._queue:
                            break  # drained: ring and queue both empty
                    # Swaps apply only on an empty ring (drain-on-swap):
                    # in-flight requests keep their start version.
                    if carry is not None:
                        self._materialize(carry)
                        carry = None
                    self._apply_pending_swap()
                if self._admit(ring):
                    if carry is not None:
                        self._materialize(carry)
                        carry = None
                if self._stop.is_set():
                    break
                if not ring:
                    continue
                try:
                    carry = self._ring_step(ring, carry)
                except BaseException as exc:  # fail the ring, keep serving
                    for slot in ring:
                        slot.req.ticket._fail(
                            ServeError(f"ring step failed: {exc!r}"))
                        self._respond_span(slot.req, "failed",
                                           steps_done=slot.steps_done)
                        if slot.is_traj:
                            self._traj_exit()
                    ring.clear()
                    carry = None
            self._drained_ev.set()
        finally:
            # Stop: the remaining rows were ASKED to die — retryable
            # backpressure. A crash unwinding through here instead means
            # their device state is lost mid-flight: also retryable (the
            # supervisor restarts the worker, but ring rows cannot be
            # replayed — their PRNG position is gone), with a hint.
            if self._stop.is_set():
                err_msg, after = "service stopped", 1.0
            else:
                err_msg = ("serving worker died mid-flight; in-ring "
                           "state lost — safe to retry")
                after = self.serve.worker_backoff_s * 2
            for slot in ring:
                slot.req.ticket._fail(Rejected(
                    err_msg, retryable=True, retry_after_s=after))
                self._respond_span(slot.req, "failed",
                                   steps_done=slot.steps_done)
                if slot.is_traj:
                    self._traj_exit()
            self._ring_debt = 0

    def _admit(self, ring: List[_Slot]) -> bool:
        """Move queued requests into free ring slots; True if the ring
        composition changed. Blocks only while the ring is empty and
        there is nothing to do. On an EMPTY ring the oldest request is
        held open for flush_timeout_ms so co-riders share the first
        dispatch (the whole-request dispatcher's coalescing contract);
        with steps already in flight arrivals join immediately. While a
        swap is pending nothing is admitted — the ring drains, queued
        requests ride the new version."""
        flush_s = self.serve.flush_timeout_ms / 1000.0
        admitted: List[_Request] = []
        expired: List[tuple] = []
        with self._queue_cv:
            if not ring:
                while (not self._queue and not self._stop.is_set()
                       and self._pending_swap is None
                       and not self._draining):
                    self._queue_cv.wait(timeout=0.1)
                if (self._stop.is_set() or not self._queue
                        or self._pending_swap is not None):
                    return False
                head = self._queue[0]
                deadline = head.t_submit + flush_s
                shape = head.shape
                while not self._stop.is_set():
                    ready = sum(1 for r in self._queue if r.shape == shape)
                    if ready >= self.serve.max_batch:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._queue_cv.wait(timeout=min(remaining, 0.05))
                if self._stop.is_set():
                    return False
            elif self._pending_swap is not None:
                return False
            shape = ring[0].shape if ring else None
            kept: List[_Request] = []
            now = time.monotonic()
            free = self.serve.max_batch - len(ring)
            for r in self._queue:
                waited = now - r.t_submit
                if r.deadline_s and waited > r.deadline_s:
                    expired.append((r, waited))
                    continue
                if shape is None:
                    shape = r.shape
                if r.shape == shape and len(admitted) < free:
                    admitted.append(r)
                else:
                    kept.append(r)  # full ring or foreign image size
            self._queue.clear()
            self._queue.extend(kept)
        for r, waited in expired:
            self._log_event(
                r.ticket.request_id, "deadline",
                f"queued {waited * 1e3:.1f}ms > deadline "
                f"{r.deadline_s * 1e3:.0f}ms")
            msg = (f"request waited {waited * 1e3:.1f}ms, deadline was "
                   f"{r.deadline_s * 1e3:.0f}ms")
            r.ticket._fail(
                TrajectoryExpired(msg, frames=[], frame_index=0)
                if r.is_traj else DeadlineExceeded(msg))
            self._respond_span(r, "expired")
        if not admitted:
            return False
        now = time.monotonic()
        version = self._live[1]
        for r in admitted:
            # Swap-drain attribution: every swap applied between this
            # request's submission and its ring admission drained the
            # ring in its path (the drain-on-swap contract).
            r.swap_drains = self._swaps - r.swaps_at_submit
            steps = int(r.program_key[2])
            try:
                bank = self._banks.get(steps)
                fbank = None
                if r.is_traj:
                    # One conditioning upload per ORBIT (here), not per
                    # frame: the bank seeds with the source view and
                    # grows on device as frames commit in-jit.
                    fbank = FrameBank(self._k_max, r.k_cap, r.cond["x"],
                                      r.cond["R1"], r.cond["t1"])
                cc = cc_bank = None
                if self._cond_cache:
                    # The cond-cache tentpole: encode the request's
                    # conditioning branch ONCE, here, at admission; the
                    # step program consumes the activations as device
                    # arguments every step of the row's lifetime.
                    cc, cc_bank = self._admit_encode(r, fbank)
            except Exception as exc:
                # A request the schedule/bank math cannot serve (e.g. a
                # step count respace() rejects) fails ITS ticket — an
                # admission error must never kill the worker thread and
                # wedge every later request behind it.
                r.ticket._fail(Rejected(
                    f"admission failed for request "
                    f"{r.ticket.request_id}: {exc!r}"))
                self._respond_span(r, "failed")
                continue
            if r.is_traj:
                self._traj_in_ring += 1
                self._traj_active.set(float(self._traj_in_ring))
            slot = _Slot(r, bank, version, now, fbank=fbank)
            slot.cc, slot.cc_bank = cc, cc_bank
            ring.append(slot)
            # step_wait: submit → ring admission (the stepper's analogue
            # of queue_wait; bounded by steps in flight, not by whole
            # requests ahead).
            self.tracer.add_span("step_wait", now - r.t_submit,
                                 request_id=r.ticket.request_id,
                                 steps=slot.bank.n,
                                 trace_id=r.trace_id,
                                 parent_id=reqtrace.root_span_id(
                                     r.trace_id),
                                 swap_drains=r.swap_drains)
        return True

    def _place(self, tree, bucket: int):
        """Device placement for one ring dispatch: shard over the mesh
        'data' axis when the bucket divides it, replicate over the mesh
        otherwise, default device without a mesh (same policy as the
        whole-request dispatcher)."""
        if mesh_lib.divides_data_axis(self.mesh, bucket):
            return mesh_lib.shard_batch(self.mesh, tree)
        if self.mesh is not None:
            return jax.device_put(tree, mesh_lib.replicated(self.mesh))
        return jax.device_put(tree, jax.devices()[0])

    def _false_rows(self, bucket: int):
        """Cached device-staged all-False (bucket,) `first` vector."""
        dev = self._false_cache.get(bucket)
        if dev is None:
            dev = self._place(np.zeros(bucket, bool), bucket)
            self._false_cache[bucket] = dev
        return dev

    def _materialize(self, carry: dict) -> None:
        """Pull the carry's device-resident (z, keys) back into the host
        slot state — the ring composition is about to change, so the next
        dispatch rebuilds its batch from rows."""
        z_host = np.asarray(jax.device_get(carry["z"]))
        k_host = np.asarray(jax.device_get(carry["keys"]))
        for i, slot in enumerate(carry["slots"]):
            slot.z = z_host[i]
            slot.keys = k_host[i]

    def _step_cache_key(self, bucket: int, H: int, W: int) -> tuple:
        """Stepper program identity: bucket SHAPE plus the DiffusionConfig
        fields the compiled step bakes in — including the serving
        precision and the fused-step flag, which change the lowered
        program (in-jit dequant / the Pallas kernel call). Deliberately
        NO steps, t, or guidance weight — those are device arguments,
        which is what makes a mixed 4/256-step warm sweep compile
        nothing (the PR 3 key folded `steps` in, which under step-level
        scheduling would have recompiled per step count). k_max and
        stochastic_cond ride along but are SERVICE constants (they size
        the bank arrays / pick the gather), so mixed single-shot and
        trajectory traffic still shares one program per bucket."""
        d = self.diffusion
        return (bucket, H, W, d.sampler, d.cfg_rescale, d.ddim_eta,
                d.objective, d.clip_denoised, d.schedule, d.timesteps,
                self.precision, d.fused_step, self._k_max,
                d.stochastic_cond, self._cond_cache)

    def _build_step_program(self):
        if self._k_max > 0:
            return make_bank_step_fn(
                self.model, self.diffusion, self._k_max,
                param_transform=self._param_transform,
                cond_cache=self._cond_cache)
        return make_slot_step_fn(self.model, self.diffusion,
                                 param_transform=self._param_transform,
                                 cond_cache=self._cond_cache)

    def _zero_bank(self, H: int, W: int) -> tuple:
        """Staged-once zero bank arrays for single-shot rows riding a
        bank-enabled ring (their count=0 row never reads them)."""
        import jax.numpy as jnp

        zb = self._zero_bank_cache.get((H, W))
        if zb is None:
            zb = (jnp.zeros((self._k_max, H, W, 3), jnp.float32),
                  jnp.zeros((self._k_max, 3, 3), jnp.float32),
                  jnp.zeros((self._k_max, 3), jnp.float32))
            self._zero_bank_cache[(H, W)] = zb
        return zb

    # -- conditioning cache (serve.cond_cache) --------------------------
    @staticmethod
    def _cc_nbytes(cc) -> int:
        """Device bytes of one cached-conditioning pytree."""
        if cc is None:
            return 0
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree.leaves(cc))

    def _encode_call(self, cond: dict, mask: np.ndarray) -> tuple:
        """Run the admission-time encode program and account it: one
        miss counter tick per call, and a compile-ledger entry whenever
        the call grew the encode jit cache (a NEW (B, H, W) encode shape
        — the event the warm-traffic zero-recompile asserts police,
        under the name 'serve_cond_encode')."""
        params, _ = self._live
        t0 = time.perf_counter()
        pose, feats = self._encode_fn(params, cond, mask)
        jax.block_until_ready(feats)
        wall = time.perf_counter() - t0
        self._cc_misses += 1
        self._cond_misses_total.inc()
        size_fn = getattr(self._encode_fn, "_cache_size", None)
        size = int(size_fn()) if callable(size_fn) else 0
        if size > self._encode_entries:
            self._encode_entries = size
            x = np.asarray(cond["x"])
            self._compile_ledger.record(
                "serve_cond_encode",
                {"args": {"B": repr(int(x.shape[0])),
                          "H": repr(int(x.shape[1])),
                          "W": repr(int(x.shape[2]))}},
                wall_s=wall, backend=jax.default_backend())
        return tuple(pose), feats

    def _ensure_uncond(self, H: int, W: int, cond1: dict) -> bool:
        """Fill the global per-(H, W) uncond pose-embedding cache if
        empty; True on a hit. The CFG mask zeroes the pose embedding
        before the per-level convs, so the masked halves are request-
        independent (but NOT zero — conv biases and learned embeddings
        survive): any request's conditioning serves, at B=1, and the
        (1, …) result broadcasts in-program over every guidance pair."""
        key = (H, W)
        if key in self._uncond_cache:
            return True
        pose, _ = self._encode_call(
            cond1, np.zeros((cond1["x"].shape[0],), np.float32))
        if self.mesh is not None:
            pose = jax.device_put(pose, mesh_lib.replicated(self.mesh))
        self._uncond_cache[key] = pose
        return False

    def _encode_bank(self, fbank: FrameBank, R2, t2, K) -> tuple:
        """Encode every bank entry against the CURRENT target pose, at
        B=k_max (zero-padded entries encode garbage that idx, bounded by
        count, never selects). Called at trajectory admission and again
        at each frame boundary — exactly when the target pose advances
        and the bank grows."""
        k = fbank.k_max
        cond = {
            "x": fbank.x, "R1": fbank.R, "t1": fbank.t,
            "R2": np.broadcast_to(
                np.asarray(R2, np.float32), (k, 3, 3)),
            "t2": np.broadcast_to(np.asarray(t2, np.float32), (k, 3)),
            "K": np.broadcast_to(np.asarray(K, np.float32), (k, 3, 3)),
        }
        return self._encode_call(cond, np.ones((k,), np.float32))

    def _admit_encode(self, r: _Request,
                      fbank: Optional[FrameBank]) -> tuple:
        """The admission-time encode (the cond-cache tentpole): one
        B=1 encode for the request's cond branch, the shared uncond
        fill if this (H, W) has none yet, and — for trajectories — the
        B=k_max bank-entry encode against the first target pose.
        Returns (cc, cc_bank) for the slot. Runs inside _admit's
        per-request try: an encode failure fails THIS ticket, never the
        worker."""
        H, W = r.shape
        cond1 = {k: np.asarray(r.cond[k])[None] for k in COND_KEYS}
        with self.tracer.span(
                "cond_cache",
                request_id=r.ticket.request_id,
                trace_id=r.trace_id,
                parent_id=reqtrace.root_span_id(r.trace_id)) as span:
            uncond_hit = self._ensure_uncond(H, W, cond1)
            cc = self._encode_call(cond1, np.ones((1,), np.float32))
            cc_bank = None
            if fbank is not None:
                cc_bank = self._encode_bank(
                    fbank, r.poses_R[0], r.poses_t[0], r.cond["K"])
            span.set(uncond=("hit" if uncond_hit else "miss"),
                     bytes=self._cc_nbytes(cc) + self._cc_nbytes(cc_bank))
        return cc, cc_bank

    def _zero_cc_bank(self, H: int, W: int, cc: tuple) -> tuple:
        """Staged-once zero cached-bank activations for single-shot rows
        riding a cond-cached bank ring (count=0 rows never select them);
        shapes derived from a request-level cc, which the admission
        order guarantees exists before any stack needs zeros."""
        import jax.numpy as jnp

        zb = self._zero_cc_cache.get((H, W))
        if zb is None:
            pose_c, feats_c = cc
            zb = (tuple(
                jnp.zeros((self._k_max,) + p.shape[1:], p.dtype)
                for p in pose_c),
                jnp.zeros((self._k_max,) + feats_c.shape[1:],
                          feats_c.dtype))
            self._zero_cc_cache[(H, W)] = zb
        return zb

    def _cc_resident(self, ring: List[_Slot]) -> int:
        """Current device residency of the conditioning cache: every
        ring slot's activations plus the shared uncond halves."""
        total = sum(self._cc_nbytes(s.cc) + self._cc_nbytes(s.cc_bank)
                    for s in ring)
        total += sum(self._cc_nbytes(p)
                     for p in self._uncond_cache.values())
        return total

    def _bank_sig(self, ring: List[_Slot]) -> tuple:
        """Identity of the ring's stacked bank content: any commit bumps
        a slot's total, forcing a device-side restack next dispatch."""
        return tuple((id(s), s.fbank.total) if s.is_traj else None
                     for s in ring)

    def _stack_banks(self, ring: List[_Slot], bucket: int,
                     H: int, W: int) -> tuple:
        """Stack per-slot bank arrays into the (bucket, k_max, …) step
        arguments — a DEVICE-side stack (the per-slot banks are already
        device-resident), placed like every other ring tensor."""
        import jax.numpy as jnp

        zx, zR, zt = self._zero_bank(H, W)
        pad = bucket - len(ring)
        xs = [s.fbank.x if s.is_traj else zx for s in ring] + [zx] * pad
        Rs = [s.fbank.R if s.is_traj else zR for s in ring] + [zR] * pad
        ts = [s.fbank.t if s.is_traj else zt for s in ring] + [zt] * pad
        return (self._place(jnp.stack(xs), bucket),
                self._place(jnp.stack(Rs), bucket),
                self._place(jnp.stack(ts), bucket))

    def _traj_exit(self) -> None:
        self._traj_in_ring = max(0, self._traj_in_ring - 1)
        self._traj_active.set(float(self._traj_in_ring))

    def _ring_step(self, ring: List[_Slot],
                   carry: Optional[dict]) -> Optional[dict]:
        """One denoise step over the whole ring. Returns the device-
        resident carry for the next iteration, or None when rows exited
        (the composition changed, so the next dispatch rebuilds).

        Trajectory frame boundaries are NOT composition changes: a slot
        whose frame finished streams it to the client, commits it into
        its device bank in-jit, and re-arms for the next pose while the
        carry (z, keys, cond, banks) stays on device — only an expiry or
        the orbit's LAST frame makes the slot exit the ring."""
        self.dispatches += 1
        self._last_dispatch_t = time.time()
        if self._profiler is not None:
            self._profiler.on_step(self.dispatches)
        faultinject.maybe_serve_dispatch_raise(self.dispatches)
        faultinject.maybe_serve_slow_step(self.dispatches)
        nan_at = faultinject.serve_nan_spec()
        if nan_at is not None and nan_at[0] == self.dispatches:
            # Poison one row's carried latent at the host boundary; the
            # DEVICE-side finite mask must catch it downstream — the
            # drill proves detection, not just injection.
            if carry is not None:
                self._materialize(carry)
                carry = None
            victim = ring[min(nan_at[1], len(ring) - 1)]
            if victim.z is not None:
                victim.z = np.full_like(victim.z, np.nan)
        n = len(ring)
        bucket = bucket_for(n, self.serve.max_batch)
        H, W = ring[0].shape
        params, _ = self._live
        pad = bucket - n
        sig = (tuple(id(s) for s in ring), bucket)
        bank_mode = self._k_max > 0
        bank_dev = bank_sig = None
        cc_pose = cc_feats = cc_uncond = cc_bank_dev = None
        with self.tracer.span("batch_form", bucket=bucket, batch_n=n):
            if carry is not None and carry["sig"] != sig:
                self._materialize(carry)
                carry = None
            if carry is None:
                zeros_img = np.zeros((H, W, 3), np.float32)
                z = np.stack(
                    [s.z if s.z is not None else zeros_img for s in ring]
                    + [zeros_img] * pad)
                keys = np.stack([s.keys for s in ring]
                                + [np.zeros(2, np.uint32)] * pad)
                cond = {
                    k: np.stack([s.req.cond[k] for s in ring]
                                + [ring[-1].req.cond[k]] * pad)
                    for k in COND_KEYS
                }
                z_dev = self._place(z, bucket)
                keys_dev = self._place(keys, bucket)
                cond_dev = self._place(cond, bucket)
            else:
                z_dev, keys_dev, cond_dev = (
                    carry["z"], carry["keys"], carry["cond"])
            if self._cond_cache:
                # Slot-level cached activations: a DEVICE-side
                # concatenate of the per-slot B=1 encodes (pad rows
                # repeat the last real row, like cond) — restacked only
                # when the ring composition changes, exactly the cond
                # lifecycle. The shared uncond halves ride as (1, …)
                # device arguments broadcast in-program.
                import jax.numpy as jnp
                if carry is None:
                    rows = [s.cc for s in ring] + [ring[-1].cc] * pad
                    cc_pose = tuple(
                        self._place(jnp.concatenate(
                            [r[0][lev] for r in rows], axis=0), bucket)
                        for lev in range(len(rows[0][0])))
                    cc_feats = self._place(jnp.concatenate(
                        [r[1] for r in rows], axis=0), bucket)
                else:
                    cc_pose, cc_feats = carry["cc"]
                cc_uncond = self._uncond_cache[(H, W)]
            # Per-row schedule coefficients: ONE packed (B, K) host
            # gather + device transfer per step (bank.table rows) — this
            # is what keeps t/steps/w out of the program identity. Pad
            # rows repeat the last real row's coefficients so their
            # (discarded) math stays finite. `first`/`w` only change
            # when the ring composition does, so the carry fast path
            # re-uploads nothing but the coefficient matrix (plus, in
            # bank mode, the tiny per-step pose/fill vectors).
            last = ring[-1]
            coefs = np.stack(
                [s.bank.table[s.t] for s in ring]
                + [last.bank.table[last.t]] * pad)
            coefs_dev = self._place(coefs, bucket)
            if carry is None:
                first = np.asarray([s.first for s in ring] + [False] * pad)
                w = np.asarray([s.w for s in ring] + [last.w] * pad,
                               np.float32)
                first_dev = self._place(first, bucket)
                w_dev = self._place(w, bucket)
            else:
                w_dev = carry["w"]
                if any(s.first for s in ring):
                    # Trajectory re-arms flipped `first` back on mid-
                    # carry: one (bucket,) bool upload re-draws ONLY
                    # those rows' init noise.
                    first_dev = self._place(
                        np.asarray([s.first for s in ring]
                                   + [False] * pad), bucket)
                else:
                    first_dev = carry["first"]
            if bank_mode:
                # The current frame's target pose and the bank fill ride
                # as DEVICE ARGUMENTS (like the coefficients), so
                # advancing a trajectory to its next orbit pose never
                # rebuilds the ring or touches the program identity —
                # but they only CHANGE at frame boundaries, so the carry
                # fast path reuses the staged vectors between them.
                bank_sig = self._bank_sig(ring)
                if carry is not None and carry.get("bank_sig") == bank_sig:
                    R2_dev, t2_dev, state_dev = carry["pose"]
                    bank_dev = carry["bank"]
                    if self._cond_cache:
                        cc_bank_dev = carry["cc_bank"]
                else:
                    tp = [s.target_pose() for s in ring]
                    R2s = np.stack([p[0] for p in tp] + [tp[-1][0]] * pad
                                   ).astype(np.float32)
                    t2s = np.stack([p[1] for p in tp] + [tp[-1][1]] * pad
                                   ).astype(np.float32)
                    state = np.asarray(
                        [[s.fbank.count, s.fbank.latest] if s.is_traj
                         else [0, 0] for s in ring] + [[0, 0]] * pad,
                        np.int32)
                    R2_dev = self._place(R2s, bucket)
                    t2_dev = self._place(t2s, bucket)
                    state_dev = self._place(state, bucket)
                    bank_dev = self._stack_banks(ring, bucket, H, W)
                    if self._cond_cache:
                        # Cached bank-entry activations follow the bank
                        # lifecycle: restacked when a commit (or a frame
                        # boundary's re-encode) bumps the bank_sig.
                        import jax.numpy as jnp
                        cbs = [s.cc_bank if s.is_traj
                               else self._zero_cc_bank(H, W, s.cc)
                               for s in ring]
                        cbs += [cbs[-1]] * pad
                        cc_bank_dev = (
                            tuple(self._place(jnp.stack(
                                [c[0][lev] for c in cbs]), bucket)
                                for lev in range(len(cbs[0][0]))),
                            self._place(jnp.stack(
                                [c[1] for c in cbs]), bucket))
            entry = self._programs.get(self._step_cache_key(bucket, H, W))
        cold = not entry["warm"]
        t0 = time.perf_counter()
        if bank_mode:
            args = (params, z_dev, keys_dev, first_dev, cond_dev,
                    coefs_dev, w_dev, R2_dev, t2_dev, bank_dev[0],
                    bank_dev[1], bank_dev[2], state_dev)
            if self._cond_cache:
                args += ((cc_pose, cc_uncond, cc_feats,
                          cc_bank_dev[0], cc_bank_dev[1]),)
            z_next, keys_next, finite_dev = entry["fn"](*args)
        else:
            args = (params, z_dev, keys_dev, first_dev, cond_dev,
                    coefs_dev, w_dev)
            if self._cond_cache:
                args += ((cc_pose, cc_uncond, cc_feats),)
            z_next, keys_next, finite_dev = entry["fn"](*args)
        jax.block_until_ready(z_next)
        self._pace_dispatch(t0)
        elapsed = time.perf_counter() - t0
        entry["warm"] = True
        # Rider attribution (obs/reqtrace.py contract): ONE row per
        # dispatch naming every rider, the service-global dispatch
        # ordinal, and the step debt ENTERING this dispatch — per-request
        # timelines are joined offline, so tracing cost doesn't scale
        # with batch size.
        debt_in = sum(
            (s.t + 1) + ((s.req.num_frames - s.frame_index - 1)
                         * s.bank.n if s.is_traj else 0)
            for s in ring)
        for s in ring:
            s.req.rides += 1
        step_attrs = dict(bucket=bucket, batch_n=n,
                          dispatch=self.dispatches,
                          riders=",".join(
                              str(s.req.ticket.request_id)
                              for s in ring),
                          debt=debt_in)
        if self._cond_cache:
            # Cache-hit attribution: every row this dispatch stepped was
            # served from cached activations (the cache is filled at
            # admission, before the row's first step, so there is no
            # partially-cached row).
            resident = self._cc_resident(ring)
            self._cc_hits += n
            self._cond_hits_total.inc(n)
            self._cond_resident_gauge.set(float(resident))
            step_attrs.update(cc_hits=n, cc_bytes=resident)
        self.tracer.add_span("compile" if cold else "ring_step", elapsed,
                             **step_attrs)
        self.stats.record_span("ring_step", elapsed)
        # In-ring anomaly quarantine: the step program's third output is
        # a per-row finite mask (a device-side reduce — the host reads a
        # (bucket,) bool, never the latent). A row under strikes keeps
        # stepping (NaN can't heal, but the ladder is explicit); a row
        # AT the strike budget — or any non-finite row at a frame or
        # request boundary, where the only alternative is emitting the
        # garbage — is evicted and its ticket failed with SampleAnomaly.
        finite = np.asarray(jax.device_get(finite_dev))
        anomalous: List[_Slot] = []
        for i, s in enumerate(ring):
            if finite[i]:
                s.strikes = 0
            else:
                s.strikes += 1
                if s.strikes >= self.serve.anomaly_strikes:
                    anomalous.append(s)
        anom_ids = {id(s) for s in anomalous}
        finished: List[_Slot] = []
        rearm: List[_Slot] = []
        for i, s in enumerate(ring):
            if s.first:
                s.bucket0, s.batch0 = bucket, n
                s.first = False
            # Cold dispatches land in compile_s, warm ones in device_s —
            # the 'device' span keeps its PR 3 meaning (warm device time).
            if cold:
                s.compile_s += elapsed
            else:
                s.device_s += elapsed
            s.steps_done += 1
            s.t -= 1
            if id(s) in anom_ids:
                continue
            if s.t < 0:
                if not finite[i]:
                    # Boundary forces the verdict regardless of strike
                    # budget: a non-finite frame must never stream,
                    # resolve, or commit into a bank.
                    anomalous.append(s)
                    anom_ids.add(id(s))
                elif s.is_traj and s.frame_index + 1 < s.req.num_frames:
                    rearm.append(s)
                else:
                    finished.append(s)
        self._ring_debt = sum(
            (s.t + 1) + ((s.req.num_frames - s.frame_index - 1)
                         * s.bank.n if s.is_traj else 0)
            for s in ring if id(s) not in anom_ids)
        if not finished and not rearm and not anomalous:
            # Every continuing row has now taken its first step, so the
            # carried `first` is the cached all-False vector (reusing
            # this dispatch's `first_dev` would re-draw init noise).
            return {"z": z_next, "keys": keys_next, "cond": cond_dev,
                    "first": self._false_rows(bucket), "w": w_dev,
                    "sig": sig, "slots": list(ring),
                    "bank": bank_dev, "bank_sig": bank_sig,
                    "pose": ((R2_dev, t2_dev, state_dev) if bank_mode
                             else None),
                    "cc": (cc_pose, cc_feats), "cc_bank": cc_bank_dev}
        fin_ids = {id(s) for s in finished}
        rearm_ids = {id(s) for s in rearm}
        z_host = k_host = None
        if finished:
            z_host = np.asarray(jax.device_get(z_next))
            k_host = np.asarray(jax.device_get(keys_next))
        expired: List[_Slot] = []
        with self.tracer.span("respond",
                              batch_n=(len(finished) + len(rearm)
                                       + len(anomalous))):
            for s in anomalous:
                self._quarantine_slot(s)
            for i, s in enumerate(ring):
                if id(s) in rearm_ids:
                    # Frame boundary: deliver + in-jit bank commit +
                    # re-arm (or expire at this frame's admission).
                    frame_dev = z_next[i]
                    frame = (z_host[i] if z_host is not None
                             else np.asarray(jax.device_get(frame_dev)))
                    if not self._frame_boundary(s, frame, frame_dev):
                        expired.append(s)
                elif id(s) in fin_ids:
                    if s.is_traj:
                        self._finish_trajectory(s, z_host[i])
                    else:
                        self._resolve_slot(s, z_host[i])
            if not finished and not expired and not anomalous:
                # Pure frame boundary: the ring composition is
                # unchanged, the carry stays device-resident. The stale
                # bank_sig forces a device-side restack next dispatch
                # (the re-armed slots' banks just grew — and, under the
                # cond cache, their cc_bank was just re-encoded).
                return {"z": z_next, "keys": keys_next, "cond": cond_dev,
                        "first": self._false_rows(bucket), "w": w_dev,
                        "sig": sig, "slots": list(ring),
                        "bank": bank_dev, "bank_sig": bank_sig,
                        "pose": (R2_dev, t2_dev, state_dev),
                        "cc": (cc_pose, cc_feats), "cc_bank": cc_bank_dev}
            # Rows exited: rebuild next dispatch from host state.
            if z_host is None:
                z_host = np.asarray(jax.device_get(z_next))
                k_host = np.asarray(jax.device_get(keys_next))
            exit_ids = fin_ids | {id(s) for s in expired} | anom_ids
            keep: List[_Slot] = []
            for i, s in enumerate(ring):
                if id(s) in exit_ids:
                    continue
                s.z = z_host[i]
                s.keys = k_host[i]
                keep.append(s)
            ring[:] = keep
        return None

    def _quarantine_slot(self, slot: _Slot) -> None:
        """Evict a poisoned ring row: fail its ticket with a structured
        SampleAnomaly, log + count the anomaly, and never let the
        non-finite latent reach a stream, a resolution, or a bank
        commit. Co-riders are untouched (ring-composition invariance
        bounds the blast radius to one row)."""
        req = slot.req
        self.anomalies += 1
        self._anomalies_total.inc()
        where = f"after step {slot.steps_done}"
        if slot.is_traj:
            where += (f" of frame {slot.frame_index}/"
                      f"{req.num_frames}")
        self._log_event(
            req.ticket.request_id, "anomaly",
            f"non-finite latent {where} (strike {slot.strikes}/"
            f"{self.serve.anomaly_strikes}); slot quarantined, ticket "
            "failed retryably")
        msg = (f"sample went non-finite {where}; the row was "
               "quarantined before anything was streamed or committed "
               "— safe to retry")
        if slot.is_traj:
            with req.ticket._lock:
                done_frames = list(req.ticket._frames)
            req.ticket._fail(SampleAnomaly(
                msg + f"; {len(done_frames)} completed frames attached",
                frames=done_frames, frame_index=slot.frame_index))
            self._traj_exit()
        else:
            req.ticket._fail(SampleAnomaly(msg))
        self._respond_span(
            req, "anomaly", steps_done=slot.steps_done,
            frames_done=slot.frame_index if slot.is_traj else None)
        self.flight.dump("anomaly", request_id=req.ticket.request_id,
                         dispatch=self.dispatches,
                         steps_done=slot.steps_done)

    def _frame_boundary(self, slot: _Slot, frame: np.ndarray,
                        frame_dev) -> bool:
        """One finished (non-final) trajectory frame: stream it, commit
        it into the slot's device bank in-jit, check the request
        deadline AT THIS FRAME'S ADMISSION, and re-arm the slot for the
        next pose. Returns False when the deadline expired (the slot
        must leave the ring; completed frames ride the error)."""
        req = slot.req
        now = time.monotonic()
        self._stream_frame(slot, frame, now)
        R2, t2 = slot.target_pose()
        slot.fbank.commit(self._commit_fn, frame_dev, R2, t2)
        slot.frame_index += 1
        waited = now - req.t_submit
        if req.deadline_s and waited > req.deadline_s:
            self._log_event(
                req.ticket.request_id, "deadline",
                f"trajectory expired at frame {slot.frame_index}/"
                f"{req.num_frames} admission: {waited * 1e3:.1f}ms > "
                f"deadline {req.deadline_s * 1e3:.0f}ms")
            with req.ticket._lock:
                done_frames = list(req.ticket._frames)
            req.ticket._fail(TrajectoryExpired(
                f"trajectory deadline ({req.deadline_s * 1e3:.0f}ms) "
                f"passed after {slot.frame_index} of {req.num_frames} "
                f"frames ({waited * 1e3:.1f}ms elapsed); completed "
                "frames attached",
                frames=done_frames, frame_index=slot.frame_index))
            self._respond_span(req, "expired",
                               steps_done=slot.steps_done,
                               frames_done=slot.frame_index)
            self._traj_exit()
            return False
        if self._cond_cache:
            # Re-encode the bank-entry activations for the NEXT frame:
            # its target pose changes every entry's pose embedding, and
            # the bank just grew by the committed frame. frame_index was
            # advanced above, so target_pose() is the next pose — the
            # same one the next dispatch restacks into R2/t2 (the stale
            # bank_sig forces that restack, which also picks this up).
            # Runs on the pinned weights: swaps drain the ring, so
            # self._live cannot change while this slot is in flight.
            R2n, t2n = slot.target_pose()
            slot.cc_bank = self._encode_bank(slot.fbank, R2n, t2n,
                                             req.cond["K"])
        slot.t = slot.bank.n - 1
        slot.first = True  # next frame draws fresh init noise in-jit
        slot.frame_t0 = now
        return True

    def _stream_frame(self, slot: _Slot, frame: np.ndarray,
                      now: float) -> None:
        """Deliver one completed frame on the trajectory ticket and
        account it (span + gauges + per-frame telemetry row)."""
        req = slot.req
        dur = max(0.0, now - slot.frame_t0)
        timing = {"frame_index": slot.frame_index, "frame_s": dur,
                  "steps": slot.bank.n, "model_version": slot.version}
        req.ticket.model_version = slot.version
        req.ticket._deliver(frame, timing)
        # Per-frame telemetry: a `trajectory_frame` span row (child of
        # the ring_step stream) lands in telemetry.jsonl with the
        # request id + frame index via the bus-wired tracer.
        self.tracer.add_span("trajectory_frame", dur,
                             request_id=req.ticket.request_id,
                             frame_index=slot.frame_index,
                             steps=slot.bank.n,
                             model_version=slot.version,
                             trace_id=req.trace_id,
                             parent_id=reqtrace.root_span_id(
                                 req.trace_id))
        self.stats.record_span("trajectory_frame", dur)
        self._frames_count += 1
        self._frames_total.inc()
        if self._frames_t0 is None:
            self._frames_t0 = time.perf_counter()
        elapsed = time.perf_counter() - self._frames_t0
        if elapsed > 0:
            self._frames_per_sec.set(self._frames_count / elapsed)

    def _finish_trajectory(self, slot: _Slot, frame: np.ndarray) -> None:
        """The orbit's LAST frame: deliver it and complete the ticket."""
        req = slot.req
        now = time.monotonic()
        self._stream_frame(slot, frame, now)
        qw = max(0.0, slot.t_admit - req.t_submit)
        timing = {
            "queue_wait_s": qw,
            "device_s": slot.device_s,
            "bucket": slot.bucket0,
            "batch_n": slot.batch0,
            "steps": slot.steps_done,
            "frames": req.num_frames,
            "model_version": slot.version,
        }
        if slot.compile_s:
            timing["compile_s"] = slot.compile_s
        req.ticket.model_version = slot.version
        self.stats.record_span("queue_wait", qw)
        self.stats.record_span("device", slot.device_s)
        if slot.compile_s:
            self.stats.record_span("compile", slot.compile_s)
        self.tracer.add_span("queue_wait", qw,
                             request_id=req.ticket.request_id,
                             trace_id=req.trace_id,
                             parent_id=reqtrace.root_span_id(
                                 req.trace_id))
        req.ticket._complete(timing)
        self._respond_span(req, "ok", steps_done=slot.steps_done,
                           frames_done=req.num_frames)
        self.stats.count_requests(1)
        self._requests_total.inc(1)
        self._traj_exit()

    def _resolve_slot(self, slot: _Slot, image: np.ndarray) -> None:
        req = slot.req
        qw = max(0.0, slot.t_admit - req.t_submit)
        timing = {
            "queue_wait_s": qw,
            "device_s": slot.device_s,
            "bucket": slot.bucket0,
            "batch_n": slot.batch0,
            "steps": slot.steps_done,
            "model_version": slot.version,
        }
        if slot.compile_s:
            timing["compile_s"] = slot.compile_s
        req.ticket.model_version = slot.version
        self.stats.record_span("queue_wait", qw)
        self.stats.record_span("device", slot.device_s)
        if slot.compile_s:
            self.stats.record_span("compile", slot.compile_s)
        self.tracer.add_span("queue_wait", qw,
                             request_id=req.ticket.request_id,
                             trace_id=req.trace_id,
                             parent_id=reqtrace.root_span_id(
                                 req.trace_id))
        req.ticket._resolve(image, timing)
        self._respond_span(req, "ok", steps_done=slot.steps_done)
        self.stats.count_requests(1)
        self._requests_total.inc(1)

    def _collect_group(self) -> List[_Request]:
        """Pop one coalescable group: same program key, oldest first,
        held open for flush_timeout_ms or until max_batch riders."""
        flush_s = self.serve.flush_timeout_ms / 1000.0
        with self._queue_cv:
            while (not self._queue and not self._stop.is_set()
                   and self._pending_swap is None
                   and not self._draining):
                self._queue_cv.wait(timeout=0.1)
            if self._stop.is_set():
                return []
            if not self._queue:
                return []  # woken by a swap/drain: let _run handle it
            first = self._queue[0]
            key = first.program_key
            deadline = first.t_submit + flush_s
            while True:
                ready = sum(1 for r in self._queue if r.program_key == key)
                if ready >= self.serve.max_batch or self._stop.is_set():
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._queue_cv.wait(timeout=min(remaining, 0.05))
            if self._stop.is_set():
                return []  # stop() fails whatever is still queued
            group: List[_Request] = []
            kept: List[_Request] = []
            for r in self._queue:
                if (r.program_key == key
                        and len(group) < self.serve.max_batch):
                    group.append(r)
                else:
                    kept.append(r)
            self._queue.clear()
            self._queue.extend(kept)
        # Expire requests whose queue wait blew their deadline — serving
        # them would spend device time on an answer nobody is waiting for.
        now = time.monotonic()
        live = []
        for r in group:
            waited = now - r.t_submit
            if r.deadline_s and waited > r.deadline_s:
                self._log_event(
                    r.ticket.request_id, "deadline",
                    f"queued {waited * 1e3:.1f}ms > deadline "
                    f"{r.deadline_s * 1e3:.0f}ms")
                r.ticket._fail(DeadlineExceeded(
                    f"request waited {waited * 1e3:.1f}ms, deadline was "
                    f"{r.deadline_s * 1e3:.0f}ms"))
                self._respond_span(r, "expired")
            else:
                live.append(r)
        return live

    # Field names matching the program-cache key tuples positionally —
    # the ledger fingerprints each key field by name so a recompile diff
    # reads "steps: 4 -> 256", not "position 3 changed".
    _STEP_KEY_FIELDS = ("bucket", "H", "W", "sampler", "cfg_rescale",
                        "ddim_eta", "objective", "clip_denoised",
                        "schedule", "timesteps", "precision", "fused_step",
                        "k_max", "stochastic_cond", "cond_cache")
    _BATCH_KEY_FIELDS = ("bucket", "H", "W", "steps", "guidance",
                         "sampler", "cfg_rescale", "ddim_eta", "objective",
                         "schedule", "precision", "fused_step")

    def _record_build(self, key: tuple, build_s: float) -> None:
        """Program-cache build observer → compile ledger entry. The
        ledger keys every sampler build under ONE name so any second
        build is classified (and diffed) as a recompile — exactly the
        event the warm-sweep zero-recompile asserts police."""
        fields = (self._STEP_KEY_FIELDS
                  if self.serve.scheduler == "step"
                  else self._BATCH_KEY_FIELDS)
        args = {name: repr(v) for name, v in zip(fields, key)}
        self._compile_ledger.record(
            f"serve_{self.serve.scheduler}", {"args": args},
            wall_s=build_s, backend=jax.default_backend())

    def _pace_dispatch(self, t0: float) -> None:
        """serve.step_floor_ms pacing: sleep out the residual so the
        dispatch takes at least the floor. Runs AFTER block_until_ready
        — the device program is untouched; the sleep releases the GIL
        (and the core), which is the point: it rate-limits this replica
        without burning CPU. No-op at the default 0."""
        floor_s = self.serve.step_floor_ms / 1000.0
        if floor_s <= 0.0:
            return
        residual = floor_s - (time.perf_counter() - t0)
        if residual > 0.0:
            time.sleep(residual)

    def health_snapshot(self) -> dict:
        """JSON progress facts for /healthz (obs/server.py's provider
        contract): the dispatch heartbeat age, queue depth, step debt,
        brownout level, the drain state machine's state, and the live
        model version — enough for a probe to tell wedged from idle, and
        for the fleet router (serve/router.py) to run least-step-debt
        dispatch and drain detection without scraping Prometheus.

        `serve_state` ∈ ok|draining|stopped is the PR 11 state machine's
        position (`status` keeps carrying the same value — it predates
        the router and external probes key on it). `slo_fast_burn` rides
        along when the service scores an SLO (serve.slo.targets): the
        worst per-class fast-window burn rate, the number the rolling-
        deploy gate (serve/deploy.py) watches during canary probation.
        """
        with self._lock:
            depth = len(self._queue)
            debt = self._step_debt_locked()
            level = self._brownout_level
        state = ("stopped" if self._worker is None
                 else "draining" if self._draining else "ok")
        snap = {
            "status": state,
            "serve_state": state,
            "role": "serve",
            "dispatches": int(self.dispatches),
            "queue_depth": depth,
            "step_debt": int(debt),
            "brownout_level": int(level),
            "last_dispatch_age_s": round(
                time.time() - self._last_dispatch_t, 3),
            "model_version": self.model_version,
            # Program builds since boot: the fleet chaos drills assert
            # this stays flat on SURVIVORS across kills/restarts (warm
            # traffic never recompiles) without scraping Prometheus.
            "programs_built": int(self._programs.builds),
        }
        if self._cond_cache:
            # Replica health gains the cache's hit/miss/residency facts
            # so the fleet router (and a probe) can see cache health
            # without scraping Prometheus.
            snap["cond_cache"] = self._cond_cache_stats()
        if self.slo is not None:
            slo_snap = self.slo.snapshot()
            burns = [c.get("fast_burn", 0.0) for c in slo_snap.values()]
            snap["slo_fast_burn"] = round(max(burns), 3) if burns else 0.0
            snap["slo_breached"] = any(
                c.get("breached") for c in slo_snap.values())
            # Gray-failure gauge: the fleet router demotes a replica
            # whose p99 drifts far above its peers' (slow-but-alive).
            snap["latency_p99_s"] = round(self.slo.latency_p99(), 6)
        return snap

    def _cache_key(self, bucket: int, H: int, W: int, steps: int,
                   w: float) -> tuple:
        """Full program-cache key: the per-request shape/steps/guidance
        knobs PLUS every DiffusionConfig field the compiled sampler bakes
        in (sampler, cfg_rescale, ddim_eta, objective, schedule). The
        config fields are constant for one service instance today, but
        keying on them keeps the cache correct if per-request overrides
        are ever extended to cover them. Precision and the fused-step
        flag fold in for the same reason (they change the lowered
        program: in-jit dequant / the Pallas kernel call)."""
        d = self.diffusion
        return (bucket, H, W, steps, w, d.sampler, d.cfg_rescale,
                d.ddim_eta, d.objective, d.schedule,
                self.precision, d.fused_step)

    def _build_program(self, steps: int, w: float):
        import dataclasses

        dcfg = self.diffusion
        if w != dcfg.guidance_weight:
            dcfg = dataclasses.replace(dcfg, guidance_weight=w)
        schedule = sampling_schedule(dcfg, steps)
        return make_request_sampler(self.model, schedule, dcfg,
                                    param_transform=self._param_transform)

    def _dispatch(self, group: List[_Request]) -> None:
        self.dispatches += 1
        self._last_dispatch_t = time.time()
        if self._profiler is not None:
            self._profiler.on_step(self.dispatches)
        faultinject.maybe_serve_dispatch_raise(self.dispatches)
        n = len(group)
        bucket = bucket_for(n, self.serve.max_batch)
        H, W, steps, w = group[0].program_key
        # One consistent (params, version) pair for the WHOLE dispatch:
        # a swap landing mid-flight flips _live but this batch finishes —
        # and is attributed — on the version it started with.
        params, version = self._live
        # Pad rows repeat the LAST request (any valid row works — per-
        # sample RNG streams make rows independent); their outputs are
        # dropped below. Pad keys are zeros: never read by real rows.
        pad = bucket - n
        with self.tracer.span("batch_form", bucket=bucket, batch_n=n):
            cond = {
                k: np.stack([r.cond[k] for r in group]
                            + [group[-1].cond[k]] * pad)
                for k in COND_KEYS
            }
            keys = np.stack([r.key for r in group]
                            + [np.zeros_like(group[-1].key)] * pad)
            if mesh_lib.divides_data_axis(self.mesh, bucket):
                cond_dev = mesh_lib.shard_batch(self.mesh, cond)
                keys_dev = mesh_lib.shard_batch(self.mesh, keys)
            elif self.mesh is not None:
                # Ragged bucket (doesn't divide the 'data' axis):
                # replicate the batch over the mesh. Params are committed
                # to the mesh's device set, so a single-device put here
                # would make jit reject the mixed placement; replicated
                # compute is merely wasteful.
                rep = mesh_lib.replicated(self.mesh)
                cond_dev = jax.device_put(cond, rep)
                keys_dev = jax.device_put(keys, rep)
            else:
                dev = jax.devices()[0]
                cond_dev = jax.device_put(cond, dev)
                keys_dev = jax.device_put(keys, dev)
            entry = self._programs.get(
                self._cache_key(bucket, H, W, steps, w), steps, w)
        cold = not entry["warm"]
        t_disp = time.monotonic()
        t0 = time.perf_counter()
        imgs = np.asarray(jax.device_get(
            entry["fn"](params, keys_dev, cond_dev)))
        self._pace_dispatch(t0)
        elapsed = time.perf_counter() - t0
        entry["warm"] = True
        span = "compile" if cold else "device"
        for r in group:
            r.swap_drains = self._swaps - r.swaps_at_submit
            r.rides += 1
        self.tracer.add_span(span, elapsed, bucket=bucket, batch_n=n,
                             model_version=version,
                             dispatch=self.dispatches,
                             riders=",".join(str(r.ticket.request_id)
                                             for r in group))
        with self.tracer.span("respond", batch_n=n,
                              model_version=version):
            for i, r in enumerate(group):
                timing = {
                    "queue_wait_s": max(0.0, t_disp - r.t_submit),
                    f"{span}_s": elapsed,
                    "bucket": bucket,
                    "batch_n": n,
                    "model_version": version,
                }
                r.ticket.model_version = version
                self.stats.record_span("queue_wait",
                                       timing["queue_wait_s"])
                self.stats.record_span(span, elapsed)
                self.tracer.add_span(
                    "queue_wait", timing["queue_wait_s"],
                    request_id=r.ticket.request_id,
                    trace_id=r.trace_id,
                    parent_id=reqtrace.root_span_id(r.trace_id))
                r.ticket._resolve(imgs[i], timing)
                self._respond_span(r, "ok", steps_done=int(steps))
        self.stats.count_requests(n)
        self._requests_total.inc(n)


def request_cond_from_batch(batch: Dict[str, np.ndarray],
                            i: int = 0) -> Dict[str, np.ndarray]:
    """Unbatched request conditioning from row i of a batched cond dict
    (test/bench convenience)."""
    return {k: np.asarray(batch[k])[i] for k in COND_KEYS}
