"""Serving-precision staging: f32 / bf16 / weight-only int8 params.

Every registry version stores full-f32 weights; what a serving process
PUTS ON DEVICE is a deployment choice (`serve.precision`):

  - 'float32'  — the weights as published (exact; the default).
  - 'bfloat16' — every float leaf cast to bf16 at stage time. Halves
    the weights' HBM residency and host→device transfer; flax promotes
    them to the model compute dtype on-chip, so the bandwidth saving is
    real (HBM reads move half the bytes) and no model code changes.
  - 'int8'     — per-channel symmetric WEIGHT-ONLY int8 for conv/dense
    kernels (flax leaves named 'kernel', rank ≥ 2; the output-channel
    axis is last in both HWIO conv and (in, out) dense layouts) with
    f32 scales, bf16 for everything else (norm scales/biases, embedding
    tables — small, and int8 would cost real quality there). Quantized
    leaves ride as QuantLeaf pytree nodes; the sampler program
    dequantizes INSIDE the jitted step (`make_resolver`), so weights
    rest in HBM at 1 byte/param and the f32 copy exists only as XLA
    fusion-managed intermediates.

The quality cost of a precision is charged where it matters: the
registry gate probes candidates AT the serving precision
(registry/gate.py make_psnr_probe(precision=...)), so quantization loss
counts against `registry.gate_margin_db` and a version that only looks
good in f32 cannot be promoted into an int8 deployment.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

PRECISIONS = ("float32", "bfloat16", "int8")


def validate_precision(precision: str) -> str:
    """Loud membership check (mirrors train.adam_mu_dtype style)."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"serve.precision={precision!r} must be one of "
            f"{PRECISIONS} ('float32' = weights as published, "
            "'bfloat16' = cast at stage time, 'int8' = per-channel "
            "symmetric weight-only quantization with f32 scales)")
    return precision


@flax.struct.dataclass
class QuantLeaf:
    """One weight-only-quantized param leaf (a pytree node).

    `q` int8 values, `scale` f32 per-output-channel scale shaped to
    broadcast against q (all-but-last axes are 1). Rides through
    device_put / jit like any array pair; `make_resolver` turns it back
    into a compute-dtype tensor inside the program."""

    q: jnp.ndarray
    scale: jnp.ndarray


def quantize_int8(w: np.ndarray) -> QuantLeaf:
    """Per-channel symmetric int8 quantization over the LAST axis.

    scale_c = max(|w[..., c]|) / 127 (1.0 where a channel is all-zero,
    so dequantization is exact there); q = round(w / scale) clipped to
    [-127, 127] — symmetric, zero-point-free, round-half-even (numpy
    rint = the IEEE default, matching jnp.round). Roundtrip error is
    bounded by scale/2 per element (tests/test_fused_step.py)."""
    w = np.asarray(jax.device_get(w), np.float32)
    axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=axes, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return QuantLeaf(q=q, scale=scale)


def dequantize_int8(leaf: QuantLeaf, dtype=jnp.float32) -> jnp.ndarray:
    """scale · q in f32, cast to `dtype` (works on numpy or jnp)."""
    return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)


def _is_float_dtype(dtype) -> bool:
    # ml_dtypes (bfloat16) are not numpy np.floating subtypes.
    return np.issubdtype(dtype, np.floating) or dtype == jnp.bfloat16


def _quantizable(path: tuple, leaf) -> bool:
    """Conv/dense kernels only: flax names them 'kernel' and they are
    rank >= 2 with output channels last. Everything else (GroupNorm
    scale/bias, conv bias, learned embeddings) stays bf16."""
    return (bool(path) and path[-1] == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and _is_float_dtype(leaf.dtype))


def _map_with_path(tree: Any, fn: Callable[[tuple, Any], Any],
                   path: tuple = ()) -> Any:
    if isinstance(tree, dict):
        return {k: _map_with_path(v, fn, path + (k,))
                for k, v in tree.items()}
    return fn(path, tree)


def stage_params(params, precision: str):
    """Host-side staging of a param tree at `precision` (see module
    docstring). Returns a NEW host tree for bf16/int8 (quantization and
    casts run on host numpy, so the device upload ships the small
    representation); float32 returns `params` unchanged — the legacy
    path stays bit-exact, including buffer-ownership semantics."""
    validate_precision(precision)
    if precision == "float32":
        return params

    def cast_bf16(leaf):
        a = np.asarray(jax.device_get(leaf))
        if _is_float_dtype(a.dtype):
            return a.astype(jnp.bfloat16)
        return a

    if precision == "bfloat16":
        return jax.tree.map(cast_bf16, params)

    def stage_leaf(path, leaf):
        if _quantizable(path, leaf):
            return quantize_int8(leaf)
        return cast_bf16(leaf)

    return _map_with_path(params, stage_leaf)


def make_resolver(precision: str) -> Optional[Callable]:
    """The in-program param transform for `precision`.

    None for float32/bfloat16 (the staged tree feeds the model
    directly — flax's promote_dtype handles bf16 on-chip). For int8, a
    jit-traceable tree map dequantizing every QuantLeaf to bf16; it
    runs INSIDE the sampler program, so the resting representation in
    HBM stays int8 and the dequantized tensor is an XLA-managed
    intermediate of each dispatch."""
    validate_precision(precision)
    if precision != "int8":
        return None

    def resolve(params):
        return jax.tree.map(
            lambda leaf: (dequantize_int8(leaf, jnp.bfloat16)
                          if isinstance(leaf, QuantLeaf) else leaf),
            params, is_leaf=lambda x: isinstance(x, QuantLeaf))

    return resolve
