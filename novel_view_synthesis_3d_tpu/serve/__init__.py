"""Fleet-scale serving (docs/DESIGN.md "Fleet serving" +
"Fleet survivability").

A thin routing layer over N independent `SamplingService` replicas —
the Pathways/disaggregated-serving shape (PAPERS.md): each replica is
one process with its own mesh, registry watcher, and telemetry dir;
the router holds NO model state, only health snapshots, an
outstanding-work ledger, and journaled affinity overrides (the pins
themselves derive from a consistent-hash ring, so a restarted router
reconstructs them from nothing).

  - `serve/replica.py`  — the replica boundary: LocalReplica (in-
    process, tests), HttpReplica + ReplicaServer (subprocess fleet),
    and the structured-error wire format that carries PR 11's
    retryable-reject contract across the process boundary.
  - `serve/router.py`   — FleetRouter: least-step-debt dispatch,
    consistent-hash session affinity, transparent failover with
    per-request retry budgets + per-hop timeouts, hedged dispatch and
    gray-failure demotion, fleet metrics/SLO aggregation.
  - `serve/journal.py`  — append-only router journal: crash-safe
    replay of the outstanding ledger + affinity overrides, reconciled
    against live /healthz after a router restart.
  - `serve/fleet_supervisor.py` — FleetSupervisor: replica process
    resurrection with PR 2 backoff discipline (dead / stale-heartbeat
    / probe-failure detectors, same-port respawn, loud giveup).
  - `serve/deploy.py`   — registry-channel rolling deploys with the
    SLO-burn + swap-breaker gate and auto-rollback
    (`nvs3d route deploy`).
  - `serve/replica_main.py` / `serve/router_main.py` — subprocess
    entrypoints (`python -m novel_view_synthesis_3d_tpu.serve.…`).
"""

from novel_view_synthesis_3d_tpu.serve.replica import (  # noqa: F401
    LocalReplica,
    HttpReplica,
    ReplicaServer,
    ReplicaUnreachable,
    replica_health,
)
from novel_view_synthesis_3d_tpu.serve.router import (  # noqa: F401
    FleetRouter,
    FleetSaturated,
    HashRing,
    HopTimeout,
    NoReplicaAvailable,
)
from novel_view_synthesis_3d_tpu.serve.journal import (  # noqa: F401
    RouterJournal,
)
from novel_view_synthesis_3d_tpu.serve.fleet_supervisor import (  # noqa: F401,E501
    FleetSupervisor,
    ReplicaSpec,
)
from novel_view_synthesis_3d_tpu.serve.deploy import (  # noqa: F401
    rolling_deploy,
)
