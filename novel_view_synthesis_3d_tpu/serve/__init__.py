"""Fleet-scale serving (docs/DESIGN.md "Fleet serving").

A thin routing layer over N independent `SamplingService` replicas —
the Pathways/disaggregated-serving shape (PAPERS.md): each replica is
one process with its own mesh, registry watcher, and telemetry dir;
the router holds NO model state, only health snapshots, an
outstanding-work ledger, and the orbit-session affinity table.

  - `serve/replica.py`  — the replica boundary: LocalReplica (in-
    process, tests), HttpReplica + ReplicaServer (subprocess fleet),
    and the structured-error wire format that carries PR 11's
    retryable-reject contract across the process boundary.
  - `serve/router.py`   — FleetRouter: least-step-debt dispatch,
    session affinity, transparent failover with per-request retry
    budgets, fleet metrics/SLO aggregation.
  - `serve/deploy.py`   — registry-channel rolling deploys with the
    SLO-burn + swap-breaker gate and auto-rollback
    (`nvs3d route deploy`).
  - `serve/replica_main.py` — subprocess entrypoint
    (`python -m novel_view_synthesis_3d_tpu.serve.replica_main`).
"""

from novel_view_synthesis_3d_tpu.serve.replica import (  # noqa: F401
    LocalReplica,
    HttpReplica,
    ReplicaServer,
    ReplicaUnreachable,
    replica_health,
)
from novel_view_synthesis_3d_tpu.serve.router import (  # noqa: F401
    FleetRouter,
    FleetSaturated,
    NoReplicaAvailable,
)
from novel_view_synthesis_3d_tpu.serve.deploy import (  # noqa: F401
    rolling_deploy,
)
