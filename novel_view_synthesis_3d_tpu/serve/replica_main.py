"""Replica process entrypoint:

    python -m novel_view_synthesis_3d_tpu.serve.replica_main spec.json

One fleet replica = one OS process owning its own JAX runtime, mesh,
SamplingService, registry watcher, and telemetry directory. The spec
file (JSON) describes everything; the process answers the replica
handle protocol over HTTP (serve/replica.py ReplicaServer) and writes
`ready_file` ({"port", "pid", "url"}) once it is accepting traffic —
the fleet launcher (serve_bench --fleet, `nvs3d route`) polls for it
instead of racing the bind.

Once serving, a daemon thread touches `ready_file`'s mtime every
`heartbeat_s` (default 2.0) — the fleet supervisor's liveness signal: a
process that is alive but wedged (event loop stuck, not just slow)
stops heartbeating, and heartbeat age is checkable with a stat, no HTTP
round-trip to a possibly-hung server.

Spec keys:
    name            fleet identity (required)
    results_folder  this replica's telemetry dir (required; fleet trace
                    reconstruction reads <fleet_dir>/replica_<name>/)
    ready_file      path to write the readiness JSON (required)
    heartbeat_s     ready-file mtime touch period (default 2.0)
    preset          config preset (default "tiny64")
    sidelength      image sidelength override (default 16)
    steps           diffusion.sample_timesteps (default 4)
    overrides       {dotted.key: value} extra config overrides
    port            bind port (default 0 = ephemeral)
    jax_cache_dir   shared persistent compile cache (optional; fleet
                    benches share one so N replicas pay one compile)
    registry        {"dir": ..., "channel": ..., "poll_s": ...} —
                    subscribe a RegistryWatcher; initial weights load
                    from the channel head when it points at a version

Without a registry (or with an empty channel) the replica builds
SYNTHETIC weights: model.init with a fixed seed, so every replica in a
fleet holds byte-identical params — orbit failover continuations are
seamless across replicas by construction.

SIGTERM/SIGINT runs the PR 11 drain state machine (admissions reject
retryably, queued + in-ring work finishes) before exit — `kill -TERM`
IS the graceful retirement path; `kill -9` is what the chaos lane does.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading


def _build_synthetic(cfg):
    """Deterministic synthetic weights (mirrors tools/serve_bench.build:
    fixed-seed model.init on a synthetic batch)."""
    import jax
    import jax.numpy as jnp

    from novel_view_synthesis_3d_tpu.data.synthetic import (
        make_example_batch)
    from novel_view_synthesis_3d_tpu.models.xunet import XUNet

    model = XUNet(cfg.model)
    batch = make_example_batch(
        batch_size=8, sidelength=cfg.data.img_sidelength, seed=0)
    mb = {
        "x": jnp.asarray(batch["x"]), "z": jnp.asarray(batch["target"]),
        "logsnr": jnp.zeros((batch["x"].shape[0],)),
        "R1": jnp.asarray(batch["R1"]), "t1": jnp.asarray(batch["t1"]),
        "R2": jnp.asarray(batch["R2"]), "t2": jnp.asarray(batch["t2"]),
        "K": jnp.asarray(batch["K"]),
    }
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        mb, cond_mask=jnp.ones((batch["x"].shape[0],)),
        train=False)["params"]
    return model, params


def _heartbeat(ready_file: str, stop: "threading.Event",
               period_s: float) -> None:
    """Touch the ready file's mtime every `period_s` while serving. The
    faultinject heartbeat-stop hook freezes it (wedged-process drill)."""
    from novel_view_synthesis_3d_tpu.utils import faultinject

    while not stop.wait(period_s):
        if faultinject.serve_heartbeat_stopped():
            continue
        try:
            os.utime(ready_file, None)
        except OSError:
            pass  # file mid-replace by a supervisor respawn: skip one


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m novel_view_synthesis_3d_tpu.serve."
              "replica_main <spec.json>", file=sys.stderr)
        return 2
    with open(argv[0]) as fh:
        spec = json.load(fh)

    if spec.get("jax_cache_dir"):
        from novel_view_synthesis_3d_tpu.utils.xla_cache import (
            setup_compilation_cache)

        setup_compilation_cache(default_dir=spec["jax_cache_dir"],
                                min_entry_bytes=0)

    from novel_view_synthesis_3d_tpu import obs
    from novel_view_synthesis_3d_tpu.config import get_preset
    from novel_view_synthesis_3d_tpu.sample.service import SamplingService
    from novel_view_synthesis_3d_tpu.serve.replica import (
        LocalReplica,
        ReplicaServer,
    )

    name = spec["name"]
    results_folder = spec["results_folder"]
    os.makedirs(results_folder, exist_ok=True)
    cfg = get_preset(spec.get("preset", "tiny64")).override(**{
        "data.img_sidelength": int(spec.get("sidelength", 16)),
        "diffusion.sample_timesteps": int(spec.get("steps", 4)),
        "serve.results_folder": results_folder,
    })
    if spec.get("overrides"):
        cfg = cfg.override(**dict(spec["overrides"]))
    cfg = cfg.validate()

    model, params = _build_synthetic(cfg)
    model_version = ""
    store = None
    reg_spec = spec.get("registry") or {}
    if reg_spec.get("dir"):
        from novel_view_synthesis_3d_tpu.registry import RegistryStore

        store = RegistryStore(reg_spec["dir"])
        vid = store.read_channel(reg_spec.get("channel", "stable"))
        if vid:
            params = store.load_params(vid)
            model_version = vid

    telemetry = obs.RunTelemetry.create(cfg.obs, results_folder)
    profiler = (obs.make_profiler(cfg.obs.profile, results_folder,
                                  cfg.model, telemetry.bus,
                                  telemetry.registry, unit="dispatch")
                if cfg.obs.enabled else None)
    service = SamplingService(
        model, params, cfg.diffusion, cfg.serve,
        results_folder=results_folder, tracer=telemetry.tracer,
        flight=telemetry.flight, profiler=profiler,
        model_version=model_version)
    watcher = None
    if store is not None:
        from novel_view_synthesis_3d_tpu.registry import RegistryWatcher

        bus = telemetry.bus
        watcher = RegistryWatcher(
            service, store, reg_spec.get("channel", "stable"),
            poll_s=float(reg_spec.get("poll_s", 2.0)),
            event_cb=lambda s, kind, detail, version="": bus.event(
                s, kind, detail, model_version=version,
                echo=f"[{name}]"))
    if telemetry.server is not None:
        telemetry.server.set_health_provider(service.health_snapshot)

    core = LocalReplica(name, service, watcher=watcher,
                        run_dir=results_folder)
    server = ReplicaServer(core, port=int(spec.get("port", 0)))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    ready = {"port": server.port, "pid": os.getpid(),
             "url": server.url(), "name": name}
    tmp = spec["ready_file"] + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(ready, fh)
    os.replace(tmp, spec["ready_file"])
    threading.Thread(
        target=_heartbeat,
        args=(spec["ready_file"], stop,
              float(spec.get("heartbeat_s", 2.0))),
        daemon=True, name="ready-heartbeat").start()
    print(f"replica {name} serving on {server.url()}", flush=True)

    stop.wait()
    print(f"replica {name}: draining", flush=True)
    try:
        service.begin_drain()
        service.drain(float(spec.get("drain_timeout_s", 60.0)))
    finally:
        server.close()
        core.close()
        telemetry.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
